"""Statement executor: AST -> scan -> device reduce -> InfluxDB JSON rows.

The single-node equivalent of the reference's StatementExecutor
(lifted/influx/coordinator/statement_executor.go:206) + executor.Select
(engine/executor/select.go:52) + the store-side cursor/agg stack
(engine/iterators.go, aggregate_cursor.go): shard mapping, index search,
chunk scan with pre-agg skipping, then one jitted segmented-reduction
program per aggregate (models/templates.py), then fill/limit/format.

Results use influx wire shape:
    {"results": [{"statement_id": 0, "series": [
        {"name": ..., "tags": {...}, "columns": [...], "values": [[...]]}]}]}
Times in values are int ns; the HTTP layer formats RFC3339/epoch.
"""

from __future__ import annotations

import math
import os
import re
import threading as _threading
import time as _time
from dataclasses import dataclass

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.ops import aggregates as aggmod
from opengemini_tpu.parallel import cluster as pcluster
from opengemini_tpu.ops import window as winmod
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query import functions as fnmod
from opengemini_tpu.record import FieldType, FieldTypeConflict
from opengemini_tpu.sql import ast
from opengemini_tpu.meta.users import AuthError as _AuthError
from opengemini_tpu.storage.engine import WriteError
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER, QueryKilled
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.sql.parser import parse

NS = 1_000_000_000
MAX_SELECT_BUCKETS = 1_000_000  # influx max-select-buckets guard


class QueryError(Exception):
    pass


@dataclass
class ScanContext:
    """Output of the shared select prologue (_scan_context)."""

    sc: object
    shards: list
    tmin: int
    tmax: int
    schema: dict
    tag_keys: set
    group_time: object
    aligned: int
    W: int
    group_tags: list
    group_keys: list
    scan_plan: list
    live: list | None = None  # cluster live set pinned by the remote round


# host calls safe on string columns (python-object values end-to-end)
_STRING_OK_HOST = {"count", "count_distinct", "mode", "first", "last",
                   "distinct", "elapsed", "absent"}


def pick_batch(schema, agg_names, field: str, dtype, grid_ctx=None):
    """Batch implementation for one field given the aggregate names that
    will run on it. With a GROUP BY time() context (`grid_ctx` =
    (W, every_ns)), dense-capable aggregates try the regular-grid
    windows-on-lanes batch first (models/grid.py — the fastest layout,
    with built-in fallback when the scanned data is not constant-stride);
    otherwise they use the ragged->dense bucketed batch (~100x over
    scatter on TPU, models/ragged.py); rank-based ones
    (percentile/median/count_distinct) keep the lexsort AggBatch. Shared
    by the local aggregate path and the data-node partial computation
    (query/partials.py) so both sides pick identical numerics."""
    from opengemini_tpu.models import grid as _grid
    from opengemini_tpu.models import ragged as _ragged
    from opengemini_tpu.models import templates as _templates

    if (
        schema.get(field) == FieldType.INT
        and all(n in _ragged.INT_EXACT_AGGS for n in agg_names)
        and any(n in ("sum", "mean") for n in agg_names)
    ):
        # int64-exact host path: float compute would corrupt ints beyond
        # the mantissa (2^24 on-TPU f32). count alone is value-independent
        # and stays on the fast device path.
        return _ragged.IntExactBatch()
    # NOTE: a configured device mesh no longer reroutes dense-capable
    # aggregates to AggBatch — the grid and bucketed layouts themselves go
    # multi-chip by sharding their independent row axes (zero-collective
    # GSPMD partitioning, distributed.shard_leading_axis), so multi-chip
    # keeps the 62-160+ G rows/s dense kernels instead of the scatter
    # family. AggBatch's shard_map path still serves its own cases.
    if (
        grid_ctx is not None
        and not os.environ.get("OGTPU_DISABLE_GRID")  # A/B knob (bench.py)
        and schema.get(field) in (FieldType.FLOAT, FieldType.INT)
        and all(n in _grid.GRID_AGGS for n in agg_names)
    ):
        return _grid.GridBatch(dtype, grid_ctx[0], grid_ctx[1])
    if all(n in _ragged.DENSE_AGGS for n in agg_names):
        return _ragged.BucketedBatch(dtype)
    return _templates.AggBatch(dtype)


def _check_host_field_type(call_name: str, field: str, schema: dict) -> None:
    if schema.get(field) == FieldType.STRING and call_name not in _STRING_OK_HOST:
        raise QueryError(f"{call_name}() is not supported on string field {field!r}")


_READONLY_STMTS = (
    ast.SelectStatement,
    ast.UnionStatement,
    ast.ShowDatabases,
    ast.ShowMeasurements,
    ast.ShowTagKeys,
    ast.ShowTagValues,
    ast.ShowFieldKeys,
    ast.ShowSeries,
    ast.ShowRetentionPolicies,
    ast.ShowContinuousQueries,
    ast.ShowUsers,
    ast.ShowGrants,
    ast.ShowMeasurementCardinality,
    ast.ShowSeriesCardinality,
    ast.ShowSeriesExactCardinality,
    ast.ShowShards,
    ast.ShowStats,
    ast.ShowDiagnostics,
    ast.ShowStreams,
    ast.ShowSubscriptions,
    ast.ShowQueries,
)


def _is_readonly(stmt) -> bool:
    if isinstance(stmt, ast.ExplainStatement):
        # EXPLAIN ANALYZE executes the inner select — INTO would mutate
        return stmt.select is None or stmt.select.into is None
    if not isinstance(stmt, _READONLY_STMTS):
        return False
    # SELECT ... INTO mutates
    return not (isinstance(stmt, ast.SelectStatement) and stmt.into is not None)


class Executor:
    def __init__(self, engine, users=None, auth_enabled: bool = False,
                 meta_store=None):
        from opengemini_tpu.meta.users import UserStore

        self.engine = engine
        self.users = users if users is not None else UserStore(
            os.path.join(engine.root, "users.json")
        )
        self.auth_enabled = auth_enabled
        # when clustered, database/RP/user DDL replicates through raft
        self.meta_store = meta_store
        # multi-node data plane (parallel/cluster.DataRouter): peers serve
        # raw columns, aggregation stays on this node's device
        self.router = None
        # serializes leader-side user DDL: check-then-propose must not race
        # across HTTP threads (duplicate CREATE USER would silently replace
        # the first user's credentials)
        self._user_ddl_lock = _threading.Lock()
        # incremental GROUP BY time() result cache (query/resultcache.py)
        from opengemini_tpu.query.resultcache import IncrementalCache

        self._inc_cache = IncrementalCache()
        # per-thread stack of CTE names being expanded (cycle detection)
        self._cte_state = _threading.local()

    def _replicate_ddl(self, cmd: dict) -> bool:
        """Route a DDL command through the raft meta store when clustered.
        Returns True when handled (leader path; the engine change arrives
        via the FSM listener). Raises on follower (client must redirect)."""
        if self.meta_store is None:
            return False
        self._require_leader()
        if not self.meta_store.propose_and_wait(cmd):
            raise QueryError("meta proposal failed (no quorum?)")
        return True

    # aggregates the downsample rewrite path can actually execute per field
    # type: integers must stay on the exact host int64 path (sum/min/max/
    # first/last) or produce float output (mean/stddev/median); count,
    # count_distinct, spread and percentile would fail at rewrite time for
    # INT fields, and percentile lacks its parameter in every path
    _DOWNSAMPLE_AGGS = {
        "float": {"sum", "count", "mean", "min", "max", "first", "last",
                  "spread", "stddev", "median"},
        "integer": {"sum", "mean", "min", "max", "first", "last",
                    "stddev", "median"},
        "boolean": {"first", "last"},
    }

    def _create_downsample(self, stmt, db: str) -> dict:
        """CREATE DOWNSAMPLE (reference: CreateDownSampleStatement semantics,
        meta downsample policies + engine_downsample.go): level i rewrites
        shards older than SAMPLEINTERVAL[i] at TIMEINTERVAL[i] resolution."""
        from opengemini_tpu.ops import aggregates as aggmod
        from opengemini_tpu.storage.engine import DownsamplePolicy

        tgt = stmt.database or db
        if not stmt.rp:
            raise QueryError("CREATE DOWNSAMPLE requires ON [db.]rp")
        samples, times = stmt.sample_intervals, stmt.time_intervals
        if len(samples) != len(times):
            raise QueryError(
                "SAMPLEINTERVAL and TIMEINTERVAL must have the same "
                f"number of levels ({len(samples)} vs {len(times)})"
            )
        for i in range(len(samples)):
            if times[i] <= 0 or samples[i] <= 0:
                raise QueryError("downsample intervals must be positive")
            if times[i] >= samples[i]:
                raise QueryError(
                    f"TIMEINTERVAL {_fmt_duration(times[i])} must be finer "
                    f"than SAMPLEINTERVAL {_fmt_duration(samples[i])}"
                )
            if i and (samples[i] <= samples[i - 1] or times[i] <= times[i - 1]):
                raise QueryError("downsample levels must be ascending")
        if stmt.ttl_ns and samples and stmt.ttl_ns < samples[-1]:
            raise QueryError("TTL must cover the last SAMPLEINTERVAL")
        for tname, agg in stmt.type_aggs.items():
            allowed = self._DOWNSAMPLE_AGGS.get(tname)
            if allowed is None:
                raise QueryError(f"unknown downsample field type: {tname!r}")
            if agg not in allowed:
                raise QueryError(
                    f"downsample aggregate {agg!r} is not supported for "
                    f"{tname} fields (one of: {', '.join(sorted(allowed))})"
                )
            aggmod.get(agg)  # registry sanity; allowlist is a subset
        self._check_fsm_db(tgt)
        if self.meta_store is not None:
            fsm_db = self.meta_store.fsm.databases[tgt]
            if stmt.rp not in fsm_db.get("rps", {}):
                raise QueryError(f"retention policy not found: {tgt}.{stmt.rp}")
            if stmt.rp in fsm_db.get("downsample", {}):
                raise QueryError(f"downsample already exists on {tgt}.{stmt.rp}")
        else:
            d = self.engine.databases.get(tgt)
            if d is None:
                raise QueryError(f"database not found: {tgt}")
            if stmt.rp not in d.rps:
                raise QueryError(f"retention policy not found: {tgt}.{stmt.rp}")
            if d.downsample.get(stmt.rp):
                raise QueryError(f"downsample already exists on {tgt}.{stmt.rp}")
        policies = [
            DownsamplePolicy(samples[i], times[i], dict(stmt.type_aggs))
            for i in range(len(samples))
        ]
        cmd = {"op": "add_downsample", "db": tgt, "rp": stmt.rp,
               "ttl_ns": stmt.ttl_ns,
               "policies": [p.to_json() for p in policies]}
        if not self._replicate_ddl(cmd):
            self.engine.set_downsample_policies(tgt, stmt.rp, policies,
                                                ttl_ns=stmt.ttl_ns)
        return {}

    def _show_cluster(self) -> dict:
        """Reference: SHOW CLUSTER (meta/data node roster with status)."""
        rows = []
        if self.meta_store is None:
            rows.append(["local", "", "meta,data", "leader", ""])
        else:
            leader = self.meta_store.leader_hint()
            members = self.meta_store.meta_members()
            for nid in sorted(members):
                status = "leader" if nid == leader else "follower"
                rows.append([nid, members[nid], "meta", status, ""])
            health = getattr(self.router, "health", {}) if self.router else {}
            shared = getattr(self.router, "shared_health", {}) if self.router else {}
            down_since = getattr(self.router, "down_since", {}) if self.router else {}
            for nid, info in sorted(self.meta_store.fsm.nodes.items()):
                status = "registered"
                # quorum view (exchange_health) wins over the purely local
                # probe: one coordinator's broken route must not show a
                # healthy node as down
                if nid in shared:
                    status = "up" if shared[nid] else "down"
                elif nid in health:
                    status = "up" if health[nid] else "down"
                since = down_since.get(nid)
                rows.append([nid, info.get("addr", ""),
                             info.get("role", "data"), status,
                             cond.format_rfc3339(int(since * 1e9)) if since else ""])
        return {"series": [_series("cluster", None,
                                   ["id", "addr", "role", "status", "down_since"],
                                   rows)]}

    def _show_downsamples(self, stmt, db: str) -> dict:
        tgt = stmt.database or db
        d = self.engine.databases.get(tgt)
        if d is None:
            raise QueryError(f"database not found: {tgt}")
        rows = []
        for rp in sorted(d.downsample):
            for p in d.downsample[rp]:
                aggs = ",".join(f"{t}({a})" for t, a in sorted(p.field_aggs.items()))
                rows.append([rp, aggs, _fmt_duration(p.age_ns),
                             _fmt_duration(p.every_ns)])
        series = _series(tgt, None,
                         ["rpName", "aggs", "sampleInterval", "timeInterval"],
                         rows)
        return {"series": [series]}

    def _check_fsm_db(self, name: str) -> None:
        """Validate db existence against the FSM BEFORE proposing a
        db-scoped command: the FSM silently ignores an unknown db, which
        would persist a junk entry. Leadership is checked FIRST — a
        lagging follower must redirect, not answer 'not found' from its
        stale FSM (same rule as _user_ddl)."""
        if self.meta_store is None:
            return
        self._require_leader()
        if name not in self.meta_store.fsm.databases:
            raise QueryError(f"database not found: {name}")

    def _require_leader(self) -> None:
        if self.meta_store is not None and not self.meta_store.is_leader():
            leader = self.meta_store.leader_hint() or "unknown"
            raise QueryError(
                f"not the meta leader; retry against node {leader!r}"
            )

    def _require_user(self, name: str) -> None:
        from opengemini_tpu.meta.users import AuthError

        if name not in self.users.users:
            raise AuthError(f"user not found: {name}")

    def _user_ddl(self, validate_fn, cmd_fn) -> bool:
        """Replicated user DDL: leadership first (a stale follower must
        redirect, not answer from its lagging local store), then
        validation + propose under one lock (check-then-propose races
        across HTTP threads would silently overwrite credentials).
        Returns False when not clustered (caller runs the local path)."""
        if self.meta_store is None:
            return False
        with self._user_ddl_lock:
            self._require_leader()
            validate_fn()
            if not self.meta_store.propose_and_wait(cmd_fn()):
                raise QueryError("meta proposal failed (no quorum?)")
        return True

    # -- entry --------------------------------------------------------------

    def execute(
        self, text: str, db: str = "", now_ns: int | None = None,
        read_only: bool = False, user=None,
    ) -> dict:
        """read_only=True (HTTP GET) rejects mutating statements — influx
        1.x requires POST for anything but SELECT/SHOW. `user` is the
        authenticated user when auth is enabled (privilege checks)."""
        if now_ns is None:
            now_ns = _time.time_ns()
        try:
            stmts = parse(text)
        except ValueError as e:
            return {"results": [{"statement_id": 0, "error": f"error parsing query: {e}"}]}
        STATS.incr("executor", "queries")
        qid = TRACKER.register(text, db)
        try:
            return self._execute_statements(stmts, db, now_ns, read_only, user)
        finally:
            TRACKER.unregister(qid)

    def _execute_statements(self, stmts, db, now_ns, read_only, user) -> dict:
        results = []
        for i, stmt in enumerate(stmts):
            try:
                # a killed query must not run its REMAINING statements
                # either (the next one might be destructive DDL)
                TRACKER.check()
                if read_only and not _is_readonly(stmt):
                    raise QueryError(
                        f"{type(stmt).__name__} queries must be sent via POST"
                    )
                if self.auth_enabled:
                    if len(self.users) == 0:
                        # bootstrap: ONLY creating the first admin is open
                        if not (isinstance(stmt, ast.CreateUser) and stmt.admin):
                            raise _AuthError(
                                "create an admin user first: CREATE USER <name> "
                                "WITH PASSWORD '<pw>' WITH ALL PRIVILEGES"
                            )
                    else:
                        self._authorize(stmt, user, db)
                if self.engine.read_disabled and isinstance(
                    stmt, (ast.SelectStatement, ast.ExplainStatement)
                ):
                    raise QueryError("reads are disabled (syscontrol)")
                res = self.execute_statement(stmt, db, now_ns, user=user)
            except (
                QueryError, cond.ConditionError, KeyError, ValueError,
                re.error, FieldTypeConflict, WriteError, QueryKilled,
            ) as e:
                # _AuthError deliberately NOT caught: authorization failures
                # must surface as HTTP 401/403, not statement errors in a 200
                res = {"error": str(e)}
            res["statement_id"] = i
            results.append(res)
        return {"results": results}

    def _authorize(self, stmt, user, db: str) -> None:
        """Privilege checks (reference: httpd auth + meta user privileges).
        READ for selects/shows, WRITE for SELECT INTO, admin for DDL and
        user management; SET PASSWORD allowed for self."""
        from opengemini_tpu.meta.users import AuthError

        if user is None:
            raise AuthError("authorization required")
        if user.admin:
            return
        if isinstance(stmt, ast.SetPassword) and stmt.name == user.name:
            return
        if isinstance(stmt, ast.ShowDatabases):
            return  # any authenticated user; rows are filtered to
            # authorized dbs in execute_statement (influx semantics)
        select = None
        if isinstance(stmt, ast.ExplainStatement):
            select = stmt.select
        elif isinstance(stmt, ast.SelectStatement):
            select = stmt
        elif isinstance(stmt, ast.UnionStatement):
            for sel in stmt.selects:
                self._authorize(sel, user, db)
            return
        if select is not None:
            # READ must hold on EVERY source database — including
            # per-source overrides (FROM "otherdb"..m) and subquery inner
            # sources — not just the request's db param; WRITE likewise on
            # the INTO target's own database.
            for sdb in sorted(self._select_source_dbs(select, db)):
                if not user.can("READ", sdb):
                    raise AuthError(f"user {user.name!r} lacks READ on {sdb!r}")
            # checked on the SELECT itself whether it arrived bare or
            # wrapped in EXPLAIN [ANALYZE] — analyze executes the write
            if select.into is not None:
                tdb = select.into.database or db
                if not user.can("WRITE", tdb):
                    raise AuthError(f"user {user.name!r} lacks WRITE on {tdb!r}")
            return
        if isinstance(
            stmt,
            (ast.ShowMeasurements, ast.ShowTagKeys, ast.ShowTagValues,
             ast.ShowFieldKeys, ast.ShowSeries, ast.ShowRetentionPolicies,
             ast.ShowContinuousQueries, ast.ShowMeasurementCardinality,
             ast.ShowSeriesCardinality, ast.ShowSeriesExactCardinality),
        ):
            if user.can("READ", getattr(stmt, "database", "") or db):
                return
            raise AuthError(f"user {user.name!r} lacks READ on {db!r}")
        raise AuthError(f"user {user.name!r} is not authorized (admin required)")

    @staticmethod
    def _select_source_dbs(select, default_db: str) -> set:
        """Every database a SELECT reads from, recursing into subqueries."""
        dbs = set()

        seen: set[int] = set()

        def walk(s):
            if s is None or id(s) in seen:
                return
            seen.add(id(s))
            if isinstance(s, ast.UnionStatement):
                for sel in s.selects:
                    walk(sel)
                return
            if not s.sources:
                dbs.add(default_db)
            for src in s.sources:
                walk_src(src, s)
            walk_cond(s.condition)

        def walk_src(src, owner):
            if isinstance(src, ast.SubQuery):
                walk(src.stmt)
            elif isinstance(src, ast.JoinSource):
                walk_src(src.left, owner)
                walk_src(src.right, owner)
            elif owner.ctes and src.name in owner.ctes:
                walk(owner.ctes[src.name])
            else:
                dbs.add(src.database or default_db)

        def walk_cond(e):
            if e is None:
                return
            if isinstance(e, ast.InSubquery):
                walk(e.stmt)
            elif isinstance(e, ast.BinaryExpr):
                walk_cond(e.lhs)
                walk_cond(e.rhs)
            elif isinstance(e, (ast.ParenExpr, ast.UnaryExpr)):
                walk_cond(e.expr)

        walk(select)
        return dbs

    def execute_statement(self, stmt, db: str, now_ns: int, user=None) -> dict:
        if isinstance(stmt, ast.SelectStatement):
            STATS.incr("executor", "selects")
            res = self._select(stmt, db, now_ns)
            if not stmt.ascending and res.get("series"):
                # ORDER BY time DESC reverses the SERIES order too
                # (reference: Null_Aggregate desc cases expect the
                # lexicographically-last tagset first). Applied HERE, at
                # the statement boundary — _select recurses for
                # subqueries/CTEs and must not double-reverse
                res = dict(res, series=list(reversed(res["series"])))
            return res
        if isinstance(stmt, ast.UnionStatement):
            from opengemini_tpu.query import join as joinmod

            STATS.incr("executor", "selects")
            return joinmod.execute_union(self, stmt, db, now_ns)
        if isinstance(stmt, ast.ExplainStatement):
            return self._explain(stmt, db, now_ns)
        if isinstance(stmt, ast.ShowDatabases):
            names = self.engine.database_names()
            if self.auth_enabled and user is not None and not user.admin:
                names = [n for n in names if user.privileges.get(n)]
            rows = [[name] for name in names]
            return _series_result("databases", None, ["name"], rows)
        if isinstance(stmt, ast.ShowMeasurements):
            return self._show_measurements(stmt, db)
        if isinstance(stmt, ast.ShowTagKeys):
            return self._show_tag_keys(stmt, db)
        if isinstance(stmt, ast.ShowTagValues):
            return self._show_tag_values(stmt, db)
        if isinstance(stmt, ast.ShowFieldKeys):
            return self._show_field_keys(stmt, db)
        if isinstance(stmt, ast.ShowSeries):
            return self._show_series(stmt, db)
        if isinstance(stmt, ast.ShowSeriesExactCardinality):
            return self._show_series_exact_cardinality(stmt, db)
        if isinstance(stmt, ast.CreateMeasurement):
            # schema-on-write engine: accept and record nothing (see parser)
            return {}
        if isinstance(stmt, ast.ShowRetentionPolicies):
            return self._show_rps(stmt, db)
        if isinstance(stmt, ast.CreateDatabase):
            if not self._replicate_ddl({"op": "create_database", "name": stmt.name}):
                self.engine.create_database(stmt.name)
            if stmt.has_rp_clause:
                rp_name = stmt.rp_name or "autogen"
                cmd = {
                    "op": "create_rp", "db": stmt.name, "name": rp_name,
                    "duration_ns": stmt.duration_ns,
                    "shard_duration_ns": stmt.shard_duration_ns,
                    "default": True,
                }
                if not self._replicate_ddl(cmd):
                    self.engine.create_retention_policy(
                        stmt.name, rp_name, stmt.duration_ns,
                        stmt.shard_duration_ns, default=True,
                    )
            return {}
        if isinstance(stmt, ast.DropDatabase):
            if not self._replicate_ddl({"op": "drop_database", "name": stmt.name}):
                self.engine.drop_database(stmt.name)
            return {}
        if isinstance(stmt, ast.CreateRetentionPolicy):
            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            cmd = {
                "op": "create_rp", "db": tgt, "name": stmt.name,
                "duration_ns": stmt.duration_ns,
                "shard_duration_ns": stmt.shard_duration_ns,
                "default": stmt.default,
            }
            if not self._replicate_ddl(cmd):
                self.engine.create_retention_policy(
                    tgt, stmt.name, stmt.duration_ns,
                    stmt.shard_duration_ns, stmt.default,
                )
            return {}
        if isinstance(stmt, ast.DropRetentionPolicy):
            cmd = {"op": "drop_rp", "db": stmt.database or db, "name": stmt.name}
            if not self._replicate_ddl(cmd):
                self.engine.drop_retention_policy(stmt.database or db, stmt.name)
            return {}
        if isinstance(stmt, ast.CreateContinuousQuery):
            from opengemini_tpu.storage.engine import ContinuousQuery

            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            cq = ContinuousQuery(
                stmt.name, stmt.select_text,
                stmt.resample_every_ns, stmt.resample_for_ns,
            )
            if not self._replicate_ddl({"op": "create_cq", "db": tgt,
                                        "cq": cq.to_json()}):
                self.engine.create_continuous_query(tgt, cq)
            return {}
        if isinstance(stmt, ast.DropContinuousQuery):
            tgt = stmt.database or db
            if not self._replicate_ddl({"op": "drop_cq", "db": tgt,
                                        "name": stmt.name}):
                self.engine.drop_continuous_query(tgt, stmt.name)
            return {}
        if isinstance(stmt, ast.ShowContinuousQueries):
            series = []
            for name in sorted(self.engine.databases):
                d = self.engine.databases[name]
                rows = [[cq.name, cq.select_text] for cq in d.continuous_queries.values()]
                series.append(_series(name, None, ["name", "query"], rows))
            return {"series": series} if series else {}
        if isinstance(stmt, ast.CreateStream):
            from opengemini_tpu.services.stream import validate_stream_select
            from opengemini_tpu.storage.engine import StreamTask

            try:
                validate_stream_select(stmt.select)
            except ValueError as e:
                raise QueryError(str(e)) from None
            self._check_fsm_db(db)
            task = StreamTask(stmt.name, stmt.select_text, stmt.delay_ns)
            if not self._replicate_ddl({"op": "create_stream", "db": db,
                                        "task": task.to_json()}):
                self.engine.create_stream(db, task)
            return {}
        if isinstance(stmt, ast.DropStream):
            if not self._replicate_ddl({"op": "drop_stream", "db": db,
                                        "name": stmt.name}):
                self.engine.drop_stream(db, stmt.name)
            return {}
        if isinstance(stmt, ast.CreateSubscription):
            from opengemini_tpu.services.subscriber import Subscription

            if not stmt.destinations:
                raise QueryError("subscription requires at least one destination")
            for dest in stmt.destinations:
                if not dest.startswith(("http://", "https://")):
                    raise QueryError(
                        f"subscription destination must be an http(s) URL: {dest!r}"
                    )
            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            sub = Subscription(stmt.name, stmt.mode, stmt.destinations)
            if not self._replicate_ddl({"op": "create_subscription", "db": tgt,
                                        "sub": sub.to_json()}):
                self.engine.create_subscription(tgt, sub)
            return {}
        if isinstance(stmt, ast.CreateDownsample):
            return self._create_downsample(stmt, db)
        if isinstance(stmt, ast.DropDownsample):
            tgt = stmt.database or db
            cmd = {"op": "drop_downsample", "db": tgt, "rp": stmt.rp or None}
            if not self._replicate_ddl(cmd):
                self.engine.drop_downsample_policies(tgt, stmt.rp or None)
            return {}
        if isinstance(stmt, ast.ShowDownsamples):
            return self._show_downsamples(stmt, db)
        if isinstance(stmt, ast.ShowCluster):
            return self._show_cluster()
        if isinstance(stmt, ast.DropSubscription):
            tgt = stmt.database or db
            if not self._replicate_ddl({"op": "drop_subscription", "db": tgt,
                                        "name": stmt.name}):
                self.engine.drop_subscription(tgt, stmt.name)
            return {}
        if isinstance(stmt, ast.ShowSubscriptions):
            series = []
            for name in sorted(self.engine.databases):
                d = self.engine.databases[name]
                rows = [
                    [s.name, s.mode, ", ".join(s.destinations)]
                    for s in d.subscriptions.values()
                ]
                series.append(
                    _series(name, None, ["name", "mode", "destinations"], rows)
                )
            return {"series": series} if series else {}
        if isinstance(stmt, ast.ShowQueries):
            rows = [
                [q["qid"], q["query"], q["database"],
                 f"{q['duration_ms']}ms", q["status"]]
                for q in TRACKER.snapshot()
            ]
            return _series_result(
                "", None, ["qid", "query", "database", "duration", "status"], rows
            )
        if isinstance(stmt, ast.KillQuery):
            if not TRACKER.kill(stmt.qid):
                raise QueryError(f"no such query: {stmt.qid}")
            return {}
        if isinstance(stmt, ast.ShowShards):
            rows = []
            for (sdb, rp, start), sh in sorted(self.engine._shards.items()):
                rows.append([
                    sdb, rp, start, sh.tmin, sh.tmax, len(sh._files),
                    "cold" if os.path.islink(sh.path) else "hot",
                ])
            return _series_result(
                "shards", None,
                ["database", "retention_policy", "shard_group", "start_time",
                 "end_time", "files", "tier"],
                rows,
            )
        if isinstance(stmt, ast.ShowStats):
            series = []
            for module, vals in sorted(STATS.snapshot().items()):
                rows = [[k, v] for k, v in sorted(vals.items())]
                series.append(_series(module, None, ["statistic", "value"], rows))
            return {"series": series} if series else {}
        if isinstance(stmt, ast.ShowDiagnostics):
            import platform
            import sys as _sys

            import jax as _jax

            from opengemini_tpu import __version__

            rows = [
                ["version", __version__],
                ["python", _sys.version.split()[0]],
                ["jax", _jax.__version__],
                ["backend", _jax.default_backend()],
                ["devices", str(len(_jax.devices()))],
                ["platform", platform.platform()],
                ["data_dir", self.engine.root],
            ]
            return _series_result("system", None, ["name", "value"], rows)
        if isinstance(stmt, ast.ShowStreams):
            series = []
            for name in sorted(self.engine.databases):
                d = self.engine.databases[name]
                rows = [[s.name, s.select_text] for s in d.streams.values()]
                series.append(_series(name, None, ["name", "query"], rows))
            return {"series": series} if series else {}
        if isinstance(stmt, ast.DropMeasurement):
            # mark + deferred purge (reference MarkMeasurementDelete):
            # SELECT hides it now; SHOW SERIES keeps the series until the
            # retention tick (or a rewrite of the name) purges
            self.engine.mark_measurement_delete(db, stmt.name)
            return {}
        if isinstance(stmt, (ast.DeleteSeries, ast.DropSeries)):
            return self._delete(stmt, db, now_ns)
        if isinstance(stmt, ast.CreateUser):
            def _validate_create():
                from opengemini_tpu.meta.users import AuthError

                if stmt.name in self.users.users:
                    raise AuthError(f"user already exists: {stmt.name}")

            def _cmd_create():
                from opengemini_tpu.meta.users import UserStore

                salt, pw_hash = UserStore.make_credentials(stmt.password)
                return {"op": "create_user", "name": stmt.name,
                        "salt": salt, "hash": pw_hash, "admin": stmt.admin}

            if not self._user_ddl(_validate_create, _cmd_create):
                self.users.create(stmt.name, stmt.password, stmt.admin)
            return {}
        if isinstance(stmt, ast.DropUser):
            if not self._user_ddl(
                lambda: self._require_user(stmt.name),
                lambda: {"op": "drop_user", "name": stmt.name},
            ):
                self.users.drop(stmt.name)
            return {}
        if isinstance(stmt, ast.SetPassword):
            def _cmd_setpw():
                from opengemini_tpu.meta.users import UserStore

                salt, pw_hash = UserStore.make_credentials(stmt.password)
                return {"op": "set_password", "name": stmt.name,
                        "salt": salt, "hash": pw_hash}

            if not self._user_ddl(lambda: self._require_user(stmt.name), _cmd_setpw):
                self.users.set_password(stmt.name, stmt.password)
            return {}
        if isinstance(stmt, ast.GrantStatement):
            admin_grant = not stmt.database and stmt.privilege == "ALL"
            cmd = (
                {"op": "grant_admin", "user": stmt.user, "admin": True}
                if admin_grant
                else {"op": "grant", "user": stmt.user, "db": stmt.database,
                      "privilege": stmt.privilege}
            )
            if not self._user_ddl(lambda: self._require_user(stmt.user), lambda: cmd):
                if admin_grant:
                    self.users.grant_admin(stmt.user)
                else:
                    self.users.grant(stmt.user, stmt.database, stmt.privilege)
            return {}
        if isinstance(stmt, ast.RevokeStatement):
            admin_revoke = not stmt.database and stmt.privilege == "ALL"
            cmd = (
                {"op": "grant_admin", "user": stmt.user, "admin": False}
                if admin_revoke
                else {"op": "revoke", "user": stmt.user, "db": stmt.database}
            )
            if not self._user_ddl(lambda: self._require_user(stmt.user), lambda: cmd):
                if admin_revoke:
                    self.users.grant_admin(stmt.user, admin=False)
                else:
                    self.users.revoke(stmt.user, stmt.database)
            return {}
        if isinstance(stmt, ast.ShowUsers):
            rows = [[u.name, u.admin] for u in self.users.users.values()]
            return _series_result("", None, ["user", "admin"], sorted(rows))
        if isinstance(stmt, ast.ShowGrants):
            u = self.users.users.get(stmt.user)
            if u is None:
                raise QueryError(f"user not found: {stmt.user}")
            rows = [[db_, p] for db_, p in sorted(u.privileges.items())]
            return _series_result("", None, ["database", "privilege"], rows)
        if isinstance(stmt, ast.ShowMeasurementCardinality):
            names: set[str] = set()
            cdb = stmt.database or db
            for sh in self._all_shards_db(cdb):
                names.update(
                    m for m in sh.measurements() if self._visible(cdb, m))
            return _series_result("", None, ["count"], [[len(names)]])
        if isinstance(stmt, ast.ShowSeriesCardinality):
            from opengemini_tpu.ingest.line_protocol import series_key

            # one row per shard-group time range (reference output shape:
            # startTime/endTime/count, coordinator show-executor)
            by_range: dict[tuple[int, int], set] = {}
            for sh in self._all_shards_db(stmt.database or db):
                bucket = by_range.setdefault((sh.tmin, sh.tmax), set())
                for m, tags in sh.index.iter_series_entries():
                    bucket.add(series_key(m, tags))
            rows = [
                [cond.format_rfc3339(lo), cond.format_rfc3339(hi), len(keys)]
                for (lo, hi), keys in sorted(by_range.items())
                if keys
            ]
            if not rows:
                return {}
            return _series_result("", None, ["startTime", "endTime", "count"], rows)
        raise QueryError(f"unsupported statement: {type(stmt).__name__}")

    def _delete(self, stmt, db: str, now_ns: int) -> dict:
        """DELETE FROM m WHERE ... (time range + tag filters) and
        DROP SERIES FROM m WHERE ... (whole series).
        Reference: deleteSeries / dropSeries statement executors."""
        if not stmt.measurement:
            raise QueryError("DELETE/DROP SERIES requires FROM <measurement>")
        is_drop_series = isinstance(stmt, ast.DropSeries)
        shards = self._all_shards_db(db)
        # tag keys unioned ACROSS shards (like _scan_context) — a shard
        # without the measurement must not re-classify tags as fields,
        # which would error mid-way with earlier shards already deleted
        tag_keys: set[str] = set()
        for sh in shards:
            tag_keys.update(sh.index.tag_keys(stmt.measurement))
        sc = cond.split(stmt.condition, tag_keys, now_ns)
        if sc.has_row_filter:
            raise QueryError("DELETE conditions may only reference time and tags")
        has_time = sc.tmin != cond.MIN_TIME or sc.tmax != cond.MAX_TIME
        if is_drop_series and has_time:
            # influx rejects time bounds here rather than over-deleting
            raise QueryError("DROP SERIES does not support time conditions")
        for sh in shards:
            sids = (
                cond.eval_tag_expr(sc.tag_expr, sh.index, stmt.measurement)
                if sc.tag_expr is not None
                else None
            )
            if sids is not None and not sids:
                continue
            if is_drop_series or not has_time:
                sh.delete_data(stmt.measurement, sids)
            else:
                sh.delete_data(
                    stmt.measurement, sids,
                    None if sc.tmin == cond.MIN_TIME else sc.tmin,
                    None if sc.tmax == cond.MAX_TIME else sc.tmax,
                )
        return {}

    # -- SELECT -------------------------------------------------------------

    def _explain(self, stmt: ast.ExplainStatement, db: str, now_ns: int) -> dict:
        """EXPLAIN [ANALYZE] SELECT (reference:
        executeExplainAnalyzeStatement, statement_executor.go:943)."""
        sel = stmt.select
        if stmt.analyze:
            trace = tracing.Trace("EXPLAIN ANALYZE")
            self._select(sel, db, now_ns, trace=trace)
            trace.finish()
            lines = trace.render()
            return _series_result(
                "", None, ["EXPLAIN ANALYZE"], [[line] for line in lines]
            )
        # EXPLAIN: describe the plan without executing (same validation
        # as _select so the output never lies about a missing database)
        lines = []
        path = {
            "raw": "RAW SCAN (host merge)",
            "device": "DEVICE SEGMENTED REDUCTION (jit plan template)",
            "host": "HOST FUNCTION PIPELINE",
        }[_classify_select(sel)]
        for src in sel.sources:
            if isinstance(src, ast.SubQuery):
                raise QueryError("subqueries are not supported yet")
            src_db = src.database or db
            if not src_db:
                raise QueryError("database name required")
            if src_db not in self.engine.databases:
                raise QueryError(f"database not found: {src_db}")
            names = self._resolve_measurements(src, src_db)
            for mst in names:
                ctx = self._scan_context(sel, src_db, src.rp or None, mst, now_ns)
                lines.append(f"QUERY PLAN for {mst}: {path}")
                if ctx is None:
                    lines.append("    no matching shards/series")
                    continue
                lines.append(f"    shards: {len(ctx.shards)}")
                lines.append(f"    series: {len(ctx.scan_plan)}")
                lines.append(f"    groups: {len(ctx.group_keys)}  windows: {ctx.W}")
                lines.append(
                    f"    time range: [{ctx.tmin}, {ctx.tmax})  "
                    f"segments: {len(ctx.group_keys) * ctx.W}"
                )
        return _series_result("", None, ["QUERY PLAN"], [[line] for line in lines])

    def _select(self, stmt: ast.SelectStatement, db: str, now_ns: int,
                trace=tracing.NOOP) -> dict:
        stmt = self._rewrite_in_subqueries(stmt, db, now_ns)
        if stmt is None:
            return {}  # IN (empty subquery result): no rows can match
        if len(stmt.fields) == 1:
            only = _strip_expr(stmt.fields[0].expr)
            if isinstance(only, ast.Call) and only.name == "compare":
                return self._select_compare(stmt, only, db, now_ns)
            from opengemini_tpu.query import tablefunc as tfmod

            if isinstance(only, ast.Call) and only.name in tfmod.TABLE_FUNCTIONS:
                return self._select_table_function(stmt, only, db, now_ns)
        # constant (string-literal) columns: allowed only WITH an alias
        # and only alongside at least one variable field (reference
        # TestServer_Query_Constant_Column; error text matches)
        n_const = 0
        for f in stmt.fields:
            if isinstance(_strip_expr(f.expr), ast.StringLiteral):
                if not f.alias:
                    raise QueryError("field must contain at least one variable")
                n_const += 1
        if n_const == len(stmt.fields):
            return {}  # only constants: empty result, no error
        multi = self._multi_source_plan(stmt, db)
        if multi == "rewrite":
            # aggregates over multiple sources run on the UNION of rows
            # (reference: count(age) FROM mst,mst1 = one combined count,
            # TestServer_Query_MultiMeasurements) — rewrite as the same
            # select over a raw SELECT * subquery spanning every source
            import copy as _copy

            inner = ast.SelectStatement(
                fields=[ast.Field(expr=ast.Wildcard())],
                sources=list(stmt.sources),
                ctes=stmt.ctes,
            )
            outer = _copy.copy(stmt)
            outer.sources = [ast.SubQuery(inner)]
            return self._select(outer, db, now_ns, trace)
        all_series = []
        for src in stmt.sources:
            if isinstance(src, ast.JoinSource):
                from opengemini_tpu.query import join as joinmod

                all_series.extend(
                    joinmod.select_join(self, stmt, src, db, now_ns)
                )
                continue
            if (isinstance(src, ast.Measurement) and stmt.ctes
                    and src.name in stmt.ctes):
                all_series.extend(
                    self._select_cte(stmt, src, db, now_ns, trace)
                )
                continue
            if isinstance(src, ast.SubQuery):
                all_series.extend(
                    self._select_from_subquery(stmt, src, db, now_ns, trace)
                )
                continue
            src_db = src.database or db
            if not src_db:
                raise QueryError("database name required")
            if src_db not in self.engine.databases:
                raise QueryError(f"database not found: {src_db}")
            names = self._resolve_measurements(src, src_db)
            for mst in names:
                with trace.span(f"select: {mst}"):
                    all_series.extend(
                        self._select_measurement(
                            stmt, src_db, src.rp or None, mst, now_ns, trace
                        )
                    )
        if multi == "merge":
            all_series = _merge_multi_source(all_series, stmt)
        # SLIMIT/SOFFSET over series
        if stmt.soffset:
            all_series = all_series[stmt.soffset :]
        if stmt.slimit:
            all_series = all_series[: stmt.slimit]
        if stmt.into is not None:
            written = self._write_into(stmt.into, db, all_series)
            return _series_result("result", None, ["time", "written"], [[0, written]])
        if not all_series:
            return {}
        return {"series": all_series}

    def _multi_source_plan(self, stmt, db: str) -> str | None:
        """How a multi-source FROM combines (reference
        TestServer_Query_MultiMeasurements: sources UNION into one series
        named 'mst,mst1'):
          - None: single effective source (or joins/CTEs — their own
            machinery), no combining
          - 'merge': raw projection — evaluate per source, merge output
            series by tagset (name-joined, column-unioned, rows coalesced)
          - 'rewrite': aggregates — re-run as agg over a raw SELECT *
            subquery so the aggregation sees the UNION of rows
        """
        srcs = stmt.sources
        if any(isinstance(s, ast.JoinSource) for s in srcs):
            return None
        if any(isinstance(s, ast.Measurement) and stmt.ctes
               and s.name in stmt.ctes for s in srcs):
            return None
        n_effective = 0
        for s in srcs:
            if isinstance(s, ast.SubQuery):
                n_effective += 1
            elif isinstance(s, ast.Measurement):
                if s.regex:
                    try:
                        n_effective += len(
                            self._resolve_measurements(s, s.database or db)
                        )
                    except Exception:  # noqa: BLE001 — resolution errors surface later
                        n_effective += 1
                else:
                    n_effective += 1
        if n_effective <= 1:
            return None
        if _classify_select(stmt) == "raw":
            return "merge"
        if len(srcs) <= 1:
            # a single regex source with aggregates keeps per-measurement
            # series (influx semantics); only EXPLICIT multi-source
            # aggregates union their rows
            return None
        # already inside the rewrite's own inner (SELECT * is raw) can't
        # reach here; anything aggregating combines via the union rewrite
        return "rewrite"

    def _select_cte(self, stmt, src: ast.Measurement, db: str, now_ns: int,
                    trace=tracing.NOOP) -> list[dict]:
        """FROM <cte-name>: execute the WITH binding as a subquery, with
        cycle detection (reference error text: CTE_Query expectations)."""
        name = src.name
        active = getattr(self._cte_state, "active", None)
        if active is None:
            active = self._cte_state.active = set()
        if name in active:
            raise QueryError(
                f"Unsupported feature: recursive call to itself {name}")
        active.add(name)
        try:
            sub = ast.SubQuery(stmt.ctes[name], alias=src.alias or name)
            return self._select_from_subquery(stmt, sub, db, now_ns, trace)
        finally:
            active.discard(name)

    def _rewrite_in_subqueries(self, stmt, db: str, now_ns: int):
        """Replace `<ref> IN (SELECT ...)` predicates with OR-chains of
        equalities against the subquery's first output column.  Returns
        None when an IN set is empty (the predicate can never match)."""
        if stmt.condition is None or not _has_in_subquery(stmt.condition):
            return stmt
        import copy

        empty = []

        def resolve(e, under_or=False):
            if isinstance(e, ast.InSubquery):
                # CTE refs inside the IN-subquery resolve with cycle checks
                res = self._select(e.stmt, db, now_ns)
                values = []
                seen = set()
                for s in res.get("series", []):
                    for row in s.get("values", []):
                        if len(row) < 2 or row[1] is None:
                            continue
                        if row[1] not in seen:
                            seen.add(row[1])
                            values.append(row[1])
                if not values:
                    if under_or:
                        # an always-false leaf under OR must not erase the
                        # other branch; no representable false leaf exists
                        # in the condition machinery yet
                        raise QueryError(
                            "IN (empty subquery result) under OR is not supported")
                    empty.append(True)
                    return e
                out = None
                for v in values:
                    if isinstance(v, bool):
                        lit = ast.BooleanLiteral(v)
                    elif isinstance(v, (int,)):
                        lit = ast.IntegerLiteral(v)
                    elif isinstance(v, float):
                        lit = ast.NumberLiteral(v)
                    else:
                        lit = ast.StringLiteral(str(v))
                    eq = ast.BinaryExpr("=", e.ref, lit)
                    out = eq if out is None else ast.BinaryExpr("OR", out, eq)
                return out
            if isinstance(e, ast.BinaryExpr):
                sub_or = under_or or e.op.upper() == "OR"
                return ast.BinaryExpr(
                    e.op, resolve(e.lhs, sub_or), resolve(e.rhs, sub_or))
            if isinstance(e, ast.ParenExpr):
                return ast.ParenExpr(resolve(e.expr, under_or))
            if isinstance(e, ast.UnaryExpr):
                return ast.UnaryExpr(e.op, resolve(e.expr, True))
            return e

        new_cond = resolve(stmt.condition)
        if empty:
            return None
        stmt = copy.copy(stmt)
        stmt.condition = new_cond
        return stmt

    def _select_compare(self, stmt, call, db: str, now_ns: int) -> dict:
        """compare(ref, off...): evaluate the source over the WHERE range
        and over each range shifted back by `off` seconds (or a duration),
        align rows by (tags, time+off), and emit ref1..refN plus
        ref1/refK ratio columns (reference: openGemini compare UDF,
        TestServer_Query_Compare_Functions)."""
        import copy as _copy

        if len(call.args) < 2:
            raise QueryError(
                "invalid number of arguments for compare, expected more "
                f"than one arguments, got {len(call.args)}")
        ref_e = _strip_expr(call.args[0])
        if not isinstance(ref_e, ast.VarRef):
            raise QueryError("compare() first argument must be a column")
        ref = ref_e.name
        offsets = []
        for a in call.args[1:]:
            v = _call_param_value(a)
            # bare integers are seconds; durations come in as ns
            offsets.append(int(v) * NS if isinstance(v, int) and
                           not isinstance(_strip_expr(a), ast.DurationLiteral)
                           else int(v))
        if not stmt.sources:
            raise QueryError("compare() requires a FROM source")
        src = stmt.sources[0]
        if isinstance(src, ast.SubQuery):
            inner = src.stmt
        elif isinstance(src, ast.Measurement):
            # raw field compare: first(field) over the range
            inner = ast.SelectStatement(
                fields=[ast.Field(ast.Call("first", (ast.VarRef(ref),)),
                                  alias=ref)],
                sources=[src],
            )
            inner.ctes = stmt.ctes
        else:
            raise QueryError("compare() source must be a measurement or subquery")

        sc = cond.split(stmt.condition, set(), now_ns)
        if sc.tmin == cond.MIN_TIME or sc.tmax == cond.MAX_TIME:
            raise QueryError("compare() requires an explicit time range")

        runs = []
        for off in [0] + offsets:
            bound = ast.BinaryExpr(
                "AND",
                ast.BinaryExpr(">=", ast.VarRef("time"),
                               ast.IntegerLiteral(sc.tmin - off)),
                ast.BinaryExpr("<", ast.VarRef("time"),
                               ast.IntegerLiteral(sc.tmax - off)),
            )
            run_stmt = ast.SelectStatement(
                fields=[ast.Field(ast.VarRef(ref))],
                sources=[ast.SubQuery(_copy.copy(inner))],
                condition=bound,
                group_by_all_tags=True,
            )
            run_stmt.ctes = stmt.ctes
            res = self._select(run_stmt, db, now_ns)
            data: dict[tuple, dict[int, object]] = {}
            name = "compare"
            for ser in res.get("series", []):
                name = ser.get("name", name)
                key = tuple(sorted((ser.get("tags") or {}).items()))
                bucket = data.setdefault(key, {})
                ci = ser["columns"].index(ref) if ref in ser["columns"] else 1
                for row in ser["values"]:
                    if row[ci] is not None:
                        bucket[row[0] + off] = row[ci]
            runs.append((name, data))

        src_name = runs[0][0] if runs else "compare"
        all_keys = sorted({k for _n, d in runs for k in d})
        k_runs = len(runs)
        columns = (["time"] + [f"{ref}{i+1}" for i in range(k_runs)]
                   + [f"{ref}1/{ref}{i+1}" for i in range(1, k_runs)])
        out_series = []
        for key in all_keys:
            times = sorted({t for _n, d in runs for t in d.get(key, {})})
            rows = []
            for t in times:
                vals = [d.get(key, {}).get(t) for _n, d in runs]
                ratios = []
                for i in range(1, k_runs):
                    a, b = vals[0], vals[i]
                    ratios.append(
                        a / b if a is not None and b not in (None, 0) else None)
                rows.append([t] + vals + ratios)
            if not rows:
                continue
            series = {"name": src_name, "columns": columns, "values": rows}
            if key:
                series["tags"] = dict(key)
            out_series.append(series)
        return {"series": out_series} if out_series else {}

    def _project_union(self, stmt, inner_res) -> list[dict] | None:
        """Raw column projection over a union subquery result; returns None
        when the outer statement needs real execution (aggregates, WHERE,
        grouping) and must fall back to materialization."""
        if (stmt.condition is not None or stmt.group_by_tags
                or stmt.group_by_all_tags or stmt.group_by_time):
            return None
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if not isinstance(e, (ast.VarRef, ast.Wildcard)):
                return None
        series = inner_res.get("series", [])
        if not series:
            return []
        src = series[0]
        cols_in = src["columns"]
        names, idxs = [], []
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Wildcard):
                for i, c in enumerate(cols_in[1:], start=1):
                    names.append(c)
                    idxs.append(i)
            else:
                if e.name.lower() == "time":
                    continue  # always column 0
                names.append(f.alias or e.name)
                idxs.append(cols_in.index(e.name) if e.name in cols_in else -1)
        rows = [
            [row[0]] + [row[i] if i >= 0 else None for i in idxs]
            for row in src["values"]
        ]
        if not stmt.ascending:
            rows.reverse()
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit:
            rows = rows[: stmt.limit]
        return [{"name": src["name"], "columns": ["time"] + names, "values": rows}]

    def _project_dimensioned(self, stmt, series_list: list[dict],
                             dims: list[str], name: str):
        """Bare projection over a dimensioned subquery: one output series,
        dim tags as leading columns, inner rows (incl. all-null ones) in
        series order. Returns None when the outer needs real execution."""
        if (stmt.condition is not None or stmt.group_by_tags
                or stmt.group_by_all_tags or stmt.group_by_time
                or not series_list):
            return None
        for f in stmt.fields:
            if not isinstance(_strip_expr(f.expr), (ast.VarRef, ast.Wildcard)):
                return None
        cols_in = series_list[0]["columns"]
        names, sources = [], []  # source: ("dim", key) | ("col", idx)
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Wildcard):
                for d in dims:
                    names.append(d)
                    sources.append(("dim", d))
                for i, c in enumerate(cols_in[1:], start=1):
                    names.append(c)
                    sources.append(("col", i))
            elif e.name.lower() == "time":
                continue
            elif e.name in dims:
                names.append(f.alias or e.name)
                sources.append(("dim", e.name))
            else:
                names.append(f.alias or e.name)
                sources.append(
                    ("col", cols_in.index(e.name))
                    if e.name in cols_in else ("col", -1))
        rows = []
        for s in series_list:
            tags = s.get("tags", {})
            for row in s["values"]:
                out = [row[0]]
                for kind, ref in sources:
                    if kind == "dim":
                        out.append(tags.get(ref))
                    else:
                        out.append(row[ref] if ref >= 0 else None)
                rows.append(out)
        if not stmt.ascending:
            rows.reverse()
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit:
            rows = rows[: stmt.limit]
        return [{"name": name, "columns": ["time"] + names, "values": rows}]

    def _write_into(self, target: ast.Measurement, db: str, series_list: list[dict]) -> int:
        """SELECT INTO: write result rows into the target measurement
        (reference: into clause handling in statement_executor.go). Rows go
        through the structured write path (WAL'd, schema-checked) — never
        through line-protocol text, so arbitrary tag/field content is safe."""
        tgt_db = target.database or db
        if tgt_db not in self.engine.databases:
            raise QueryError(f"database not found: {tgt_db}")
        points = []
        for series in series_list:
            tags = tuple(sorted(series.get("tags", {}).items()))
            cols = series["columns"][1:]
            for row in series["values"]:
                t, vals = row[0], row[1:]
                fields = {}
                for name, v in zip(cols, vals):
                    if v is None:
                        continue
                    if isinstance(v, bool):
                        fields[name] = (FieldType.BOOL, v)
                    elif isinstance(v, int):
                        fields[name] = (FieldType.INT, v)
                    elif isinstance(v, float):
                        fields[name] = (FieldType.FLOAT, v)
                    else:
                        fields[name] = (FieldType.STRING, str(v))
                if fields:
                    points.append((target.name, tags, t, fields))
        if not points:
            return 0
        if self.router is not None:
            # route INTO results by shard-group owner like any other write:
            # result rows written only-locally would duplicate across nodes
            # (every copy double-counts in merged scans)
            from opengemini_tpu.parallel.cluster import RemoteScanError

            try:
                return self.router.routed_write(
                    tgt_db, target.rp or None, points)
            except (OSError, RemoteScanError) as e:
                raise QueryError(f"INTO forward failed: {e}") from e
        return self.engine.write_rows(tgt_db, points, rp=target.rp or None)

    def _select_from_subquery(self, stmt, src: ast.SubQuery, db: str,
                              now_ns: int, trace=tracing.NOOP) -> list[dict]:
        """FROM (SELECT ...): the inner result materializes into a
        throw-away engine (tags stay tags, columns become fields), then the
        outer statement runs against it. Reference: subquery builders in
        engine/executor/select.go; correctness-first materialization here,
        streaming later."""
        import copy  # noqa: F811 — local import for the materializer
        import tempfile

        from opengemini_tpu.storage.engine import Engine as _Engine

        inner = src.stmt
        inner_has_wild = False
        if isinstance(inner, ast.SelectStatement):
            inner_has_wild = any(
                isinstance(_strip_expr(f.expr), ast.Wildcard)
                or _call_wildcard_inner(_strip_expr(f.expr)) is not None
                for f in inner.fields
            )
            if _classify_select(inner) == "raw" and not (
                inner.group_by_tags or inner.group_by_all_tags
            ):
                # influx propagates series tags through subqueries: a raw
                # inner select must emit per-series output, never one
                # merged series
                inner = copy.copy(inner)
                inner.group_by_all_tags = True
            elif (
                stmt.group_by_tags
                and not inner.group_by_tags
                and not inner.group_by_all_tags
            ):
                # influx subqueries INHERIT the outer GROUP BY dimensions:
                # an inner call (top/agg) computes per outer group and its
                # output series carry those tags
                # (TestServer_SubQuery_Top_Min#0)
                inner = copy.copy(inner)
                inner.group_by_tags = list(stmt.group_by_tags)
        # push the outer time range into the inner select so the inner scan
        # (and the materialization below) covers only the needed window
        if isinstance(inner, ast.UnionStatement):
            pass  # union bodies materialize whole (no time pushdown yet)
        else:
            try:
                sc_outer = cond.split(stmt.condition, set(), now_ns)
                if sc_outer.tmin != cond.MIN_TIME or sc_outer.tmax != cond.MAX_TIME:
                    bound = ast.BinaryExpr(
                        "AND",
                        ast.BinaryExpr(">=", ast.VarRef("time"),
                                       ast.IntegerLiteral(sc_outer.tmin)),
                        ast.BinaryExpr("<", ast.VarRef("time"),
                                       ast.IntegerLiteral(sc_outer.tmax)),
                    )
                    inner = copy.copy(inner)
                    inner.condition = (
                        bound if inner.condition is None
                        else ast.BinaryExpr("AND", inner.condition, bound)
                    )
            except cond.ConditionError:
                pass  # un-splittable outer condition: no pushdown
        with trace.span("subquery"):
            if isinstance(inner, ast.UnionStatement):
                from opengemini_tpu.query import join as joinmod

                inner_res = joinmod.execute_union(self, inner, db, now_ns)
                # a raw projection over a union must NOT round-trip through
                # the point materializer: union rows legitimately repeat
                # (series, time) pairs, which the engine would LWW-dedup
                proj = self._project_union(stmt, inner_res)
                if proj is not None:
                    return proj
            else:
                inner_res = self._select(inner, db, now_ns, trace)
        series_list = inner_res.get("series", [])
        if (
            not isinstance(inner, ast.UnionStatement)
            and len(series_list) == 1
            and not series_list[0].get("tags")
        ):
            # single untagged inner series + bare outer projection: project
            # directly so all-null computed rows survive (the materializer
            # cannot represent a row whose only field is null —
            # TestServer_Query_SubqueryMath#0)
            proj = self._project_union(stmt, inner_res)
            if proj is not None:
                return proj
        if (
            not isinstance(inner, ast.UnionStatement)
            and isinstance(src.stmt, ast.SelectStatement)
            and src.stmt.group_by_tags
        ):
            # dimensioned inner (explicit GROUP BY tags): a bare outer
            # projection flattens series into one with the dims as columns,
            # null rows preserved (TestServer_Query_Sliding_Window #8/#9)
            proj = self._project_dimensioned(
                stmt, series_list, list(src.stmt.group_by_tags),
                _inner_source_name(inner))
            if proj is not None:
                return proj
        mst_name = _inner_source_name(inner)
        with tempfile.TemporaryDirectory(prefix="ogtpu-sub-") as tmp:
            tmp_engine = _Engine(tmp, sync_wal=False)
            try:
                tmp_engine.create_database("sub")
                # points at the same (tags, time) MERGE their fields —
                # multi-source inners legitimately emit one row per source
                # at the same timestamp with disjoint columns, and the
                # engine's point-level LWW would otherwise drop all but
                # the last (TestServer_Query_MultiMeasurements#4/#5)
                by_key: dict[tuple, dict] = {}
                key_order: list[tuple] = []
                for series in series_list:
                    tags = tuple(sorted(series.get("tags", {}).items()))
                    cols = series["columns"][1:]
                    for row in series["values"]:
                        fields = {}
                        for name, v in zip(cols, row[1:]):
                            if v is None:
                                continue
                            if isinstance(v, bool):
                                fields[name] = (FieldType.BOOL, v)
                            elif isinstance(v, int):
                                fields[name] = (FieldType.INT, v)
                            elif isinstance(v, float):
                                fields[name] = (FieldType.FLOAT, v)
                            else:
                                fields[name] = (FieldType.STRING, str(v))
                        if fields:
                            pkey = (tags, row[0])
                            got = by_key.get(pkey)
                            if got is None:
                                by_key[pkey] = fields
                                key_order.append(pkey)
                            else:
                                got.update(fields)
                points = [
                    (mst_name, tags, t, by_key[(tags, t)])
                    for tags, t in key_order
                ]
                if points:
                    tmp_engine.write_rows("sub", points)
                outer = copy.copy(stmt)
                outer.sources = [ast.Measurement(name=mst_name)]
                outer.into = None  # INTO applies once, in the caller
                # the source is now a materialized measurement: it must not
                # re-resolve as a CTE name against the throw-away engine
                outer.ctes = None
                # influx wildcard-over-subquery expands to the inner's
                # ORIGINAL output columns: explicit inner fields stay
                # fields-only; an inner wildcard (bare or inside a call)
                # lets the outer wildcard inline propagated tags. Inner
                # EXPLICIT GROUP BY tags are output dimensions — the outer
                # wildcard includes them as columns
                # (TestServer_Query_SubqueryForLogicalOptimize#5)
                outer._from_subquery = not inner_has_wild
                if isinstance(src.stmt, ast.SelectStatement):
                    outer._subquery_dims = list(src.stmt.group_by_tags)
                # a flattenable plain-projection inner (bare field renames,
                # no grouping) donates its explicit time bounds to the
                # outer statement — the reference's subquery flattening
                # makes the outer render window start at the inner tmin
                # (SubqueryForLogicalOptimize#2); non-flattenable inners
                # (computed projections) keep epoch-0 rendering (#4)
                if (
                    isinstance(src.stmt, ast.SelectStatement)
                    and src.stmt.fields
                    and all(isinstance(_strip_expr(f.expr), ast.VarRef)
                            for f in src.stmt.fields)
                    and not src.stmt.group_by_tags
                    and not src.stmt.group_by_all_tags
                    and src.stmt.group_by_time is None
                    and src.stmt.condition is not None
                ):
                    try:
                        sc_in = cond.split(src.stmt.condition, set(), now_ns)
                        sc_out = cond.split(stmt.condition, set(), now_ns)
                        if (
                            sc_out.tmin == cond.MIN_TIME
                            and sc_out.tmax == cond.MAX_TIME
                            and (sc_in.tmin != cond.MIN_TIME
                                 or sc_in.tmax != cond.MAX_TIME)
                        ):
                            bound = ast.BinaryExpr(
                                "AND",
                                ast.BinaryExpr(
                                    ">=", ast.VarRef("time"),
                                    ast.IntegerLiteral(sc_in.tmin)),
                                ast.BinaryExpr(
                                    "<", ast.VarRef("time"),
                                    ast.IntegerLiteral(sc_in.tmax)),
                            )
                            outer.condition = (
                                bound if outer.condition is None
                                else ast.BinaryExpr(
                                    "AND", outer.condition, bound)
                            )
                    except cond.ConditionError:
                        pass
                sub_ex = Executor(tmp_engine, users=self.users)
                res = sub_ex._select(outer, "sub", now_ns, trace)
                return res.get("series", [])
            finally:
                tmp_engine.close()

    def _resolve_measurements(self, src: ast.Measurement, db: str) -> list[str]:
        if src.name:
            return [src.name]
        rx = re.compile(src.regex)
        shards = self.engine.shards_for_range(db, src.rp or None, cond.MIN_TIME, cond.MAX_TIME)
        names = set()
        for sh in shards:
            for m in sh.measurements():
                if rx.search(m):
                    names.add(m)
        if self.router is not None:
            try:
                remote = self.router.remote_measurements(db, src.rp or None)
            except Exception as e:  # noqa: BLE001
                raise QueryError(str(e)) from e
            names.update(m for m in remote if rx.search(m))
        return sorted(names)

    def _measurement_schema(self, db, rp, mst) -> dict:
        schema: dict = {}
        for sh in self.engine.shards_for_range(db, rp, cond.MIN_TIME, cond.MAX_TIME):
            schema.update(sh.schema(mst))
        return schema

    def _select_measurement(self, stmt, db, rp, mst, now_ns, trace=tracing.NOOP) -> list[dict]:
        if _has_call_wildcard(stmt):
            stmt = _expand_call_wildcards(
                stmt, self._measurement_schema(db, rp, mst)
            )
        # percentile_approx: answered from chunk histogram sketches
        if len(stmt.fields) == 1:
            only = _strip_expr(stmt.fields[0].expr)
            if isinstance(only, ast.Call) and only.name == "percentile_approx":
                return self._select_percentile_approx(
                    stmt, db, rp, mst, now_ns, only
                )
        aux_plan = _selector_aux_plan(stmt)
        if aux_plan is not None:
            return self._select_selector_aux(stmt, db, rp, mst, now_ns, aux_plan)
        kind = _classify_select(stmt)
        if kind == "device" and _needs_string_host_path(
            stmt, lambda: self._measurement_schema(db, rp, mst)
        ):
            # first/last/etc on STRING fields: the device batch layout is
            # numeric; the host path computes them exactly
            kind = "host"
        if kind == "raw":
            return self._select_raw(stmt, db, rp, mst, now_ns)
        if kind == "device":
            return self._select_agg(
                stmt, db, rp, mst, now_ns, _collect_calls(stmt.fields), trace
            )
        return self._select_host(stmt, db, rp, mst, now_ns)

    # -- shared scan planning ----------------------------------------------

    def _all_shards_with_remote(self, db, rp, mst, condition, now_ns,
                                remote_mode="raw"):
        """Local shards + remote representation from peer data nodes
        (when clustered routing is on). remote_mode:
          "raw"  — RemoteShard row proxies (full column exchange);
          "meta" — one MetaShard carrying remote tag keys / schema /
                   extent only; the rows stay put and arrive later as
                   per-(group, window) partials (aggregate pushdown).
        Returns (shards, live_node_list | None)."""
        shards = self.engine.shards_for_range(db, rp, cond.MIN_TIME, cond.MAX_TIME)
        live = None
        if self.router is not None:
            from opengemini_tpu.parallel.cluster import MetaShard

            pre = cond.split(condition, set(), now_ns)
            try:
                if remote_mode == "meta":
                    meta, live = self.router.select_meta(
                        db, rp, mst, pre.tmin, pre.tmax
                    )
                    remote = []
                    if meta is not None and meta["dmin"] is not None:
                        remote = [MetaShard(
                            mst, meta["tag_keys"], meta["schema"],
                            meta["dmin"], meta["dmax"],
                        )]
                else:
                    remote, live = self.router.scan_shards(
                        db, rp, mst, pre.tmin, pre.tmax
                    )
            except Exception as e:  # noqa: BLE001 — partial data = wrong data
                raise QueryError(str(e)) from e
            if self.router.rf > 1:
                # replicated groups: keep only those WE are primary for
                # among the live set; replicas held here would double-count
                shards = [
                    sh for sh in shards
                    if self.router.is_primary(db, rp, sh.tmin, live)
                ]
            shards = shards + remote
        return shards, live

    def _scan_context(self, stmt, db, rp, mst, now_ns, remote_mode="raw"):
        """Shared prologue of every select path: schema/tag keys, WHERE
        split, shard mapping, data-driven range clamp, window grid, group
        construction (reference: the Prepare + MapShards steps,
        SURVEY.md §3.2). Returns None when nothing matches."""
        if self.engine.is_measurement_dropped(db, mst):
            return None  # mark-deleted: hidden from SELECT pre-purge
        shards_all, live = self._all_shards_with_remote(
            db, rp, mst, stmt.condition, now_ns, remote_mode
        )
        tag_keys: set[str] = set()
        schema: dict[str, FieldType] = {}
        for sh in shards_all:
            tag_keys.update(sh.index.tag_keys(mst))
            schema.update(sh.schema(mst))
        if not schema and stmt.group_by_all_tags:
            raise QueryError("measurement not found")  # see _select_raw
        sc = cond.split(stmt.condition, tag_keys, now_ns)
        tmin, tmax = sc.tmin, sc.tmax
        explicit_tmin = tmin != cond.MIN_TIME
        explicit_tmax = tmax != cond.MAX_TIME
        shards = [sh for sh in shards_all if sh.tmax > tmin and sh.tmin < tmax]
        if not shards:
            return None
        # data-driven clamp of an unbounded range (influx uses epoch 0/now)
        if not explicit_tmin or not explicit_tmax:
            dmin, dmax = _data_time_range(shards, mst)
            if dmin is None:
                return None
            if not explicit_tmin:
                tmin = dmin
            if not explicit_tmax:
                tmax = dmax + 1
        if tmax <= tmin:
            return None
        group_time = stmt.group_by_time
        if group_time:
            aligned = int(winmod.window_start(tmin, group_time.every_ns, group_time.offset_ns))
            every = group_time.every_ns
            if not explicit_tmax and stmt.limit and stmt.ascending:
                # unbounded upper + LIMIT: the reference iterates windows
                # to now(); emitting exactly offset+limit windows from the
                # data start is equivalent and bounded
                want = stmt.offset + stmt.limit
                tmax = max(tmax, min(now_ns, aligned + want * every))
            W = winmod.num_windows(tmin, tmax, every, group_time.offset_ns)
            if W > MAX_SELECT_BUCKETS:
                raise QueryError(
                    f"GROUP BY time({every}ns) would create {W} buckets "
                    f"(max {MAX_SELECT_BUCKETS})"
                )
        else:
            # output timestamp of whole-range aggregates: the explicit WHERE
            # lower bound, else epoch 0 (influx semantics; the data-driven
            # clamp above must not leak into result rows)
            aligned = tmin if explicit_tmin else 0
            W = 1
        group_tags = self._group_tags(stmt, shards, mst)
        # ordered group keys + per-(shard, sid) membership
        gid_of: dict[tuple, int] = {}
        group_keys: list[tuple] = []
        scan_plan = []  # (shard, sid, gid)
        # GROUP BY time emits fill rows even for series with zero matching
        # rows — pruning those series would change the emitted series set,
        # so the index only prunes un-windowed scans
        match_terms = (
            [] if group_time else cond.conjunctive_match_terms(sc.field_expr)
        )
        # /*+ full_series|specific_series */: the WHERE identifies whole
        # series — evaluate mixed tag/field trees at the series level and
        # skip their per-row filter (reference: hybrid store reader hints)
        hinted = bool({"full_series", "specific_series"}
                      & set(getattr(stmt, "hints", ())))
        exact_tags = (
            cond.exact_series_tags(stmt.condition, tag_keys)
            if "full_series" in getattr(stmt, "hints", ()) else None
        ) or None  # no tag equalities -> the hint pins nothing
        for sh in shards:
            sids = cond.eval_tag_expr(sc.tag_expr, sh.index, mst)
            if sc.mixed_expr is not None:
                if hinted:
                    sids &= cond.series_only_sids(
                        sc.mixed_expr, sh.index, mst, sc.tag_keys)
                else:
                    sids &= cond.tag_superset_sids(
                        sc.mixed_expr, sh.index, mst, sc.tag_keys)
            if exact_tags is not None:
                sids = {s for s in sids
                        if sh.index.tags_of(s) == exact_tags}
            sids = _prune_text_sids(sh, mst, sids, match_terms)
            for sid in sorted(sids):
                tags = sh.index.tags_of(sid)
                key = tuple(tags.get(k, "") for k in group_tags)
                gid = gid_of.get(key)
                if gid is None:
                    gid = len(group_keys)
                    gid_of[key] = gid
                    group_keys.append(key)
                scan_plan.append((sh, sid, gid))
        if hinted:
            sc.mixed_series_level = True  # consumed at the series level
        if not scan_plan and not (remote_mode == "meta" and live is not None):
            # clustered "meta" scans proceed with an empty local plan:
            # the groups may exist only as remote partials
            return None
        return ScanContext(
            sc, shards, tmin, tmax, schema, tag_keys, group_time, aligned, W,
            group_tags, group_keys, scan_plan, live,
        )

    # -- aggregate path -----------------------------------------------------

    def _select_agg(self, stmt, db, rp, mst, now_ns, calls, trace=tracing.NOOP) -> list[dict]:
        from opengemini_tpu.query import partials as pmod

        # resolve agg specs + fields (before planning: the set decides
        # whether remote data arrives as partials or raw columns)
        aggs = []  # (out_name, spec, params, field_name)
        for f in stmt.fields:
            for call in _calls_in(f.expr):
                spec, params, field_name = _resolve_call(call)
                aggs.append((call, spec, params, field_name))

        pushdown = (
            self.router is not None
            # getattr: duck-typed router stubs without the full surface
            # keep the raw column-exchange path
            and getattr(self.router, "has_peers", lambda: False)()
            and all(
                spec.name in pmod.MERGEABLE
                or spec.name in pmod.MULTISET_MERGEABLE
                for _c, spec, _p, _f in aggs
            )
            and not any(f.lower() == "time" for _c, _s, _p, f in aggs)
        )
        attempts = max(self.router.rf, 1) if pushdown else 1
        for attempt in range(attempts):
            try:
                return self._select_agg_run(
                    stmt, db, rp, mst, now_ns, aggs, pushdown, trace
                )
            except pcluster.PartialsUnavailable:
                # a live peer cannot serve partials (e.g. rolling
                # upgrade): the raw column exchange still works
                return self._select_agg_run(
                    stmt, db, rp, mst, now_ns, aggs, False, trace
                )
            except pcluster.PartialsRetry as e:
                # a peer died mid-query: primary ownership shifted, the
                # whole plan (live set, local primary filter) is stale
                if attempt == attempts - 1:
                    raise QueryError(str(e)) from e
        raise AssertionError("unreachable")

    def _select_agg_run(self, stmt, db, rp, mst, now_ns, aggs, pushdown,
                        trace=tracing.NOOP) -> list[dict]:
        from opengemini_tpu.query import partials as pmod

        with trace.span("map_shards") as sp:
            ctx = self._scan_context(
                stmt, db, rp, mst, now_ns,
                remote_mode="meta" if pushdown else "raw",
            )
            if ctx is not None:
                sp.add_field("shards", len(ctx.shards))
                sp.add_field("series", len(ctx.scan_plan))
                sp.add_field("groups x windows", f"{len(ctx.group_keys)} x {ctx.W}")
        if ctx is None:
            return []
        sc, shards = ctx.sc, ctx.shards
        tmin, tmax = ctx.tmin, ctx.tmax
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W
        group_tags, group_keys, scan_plan = ctx.group_tags, ctx.group_keys, ctx.scan_plan
        schema = ctx.schema

        num_groups = len(group_keys)
        num_segments = num_groups * W

        # aggregates over the `time` pseudo-field (count/first/last/min/max
        # of row timestamps) are computed host-side from scanned row times
        time_aggs = [a for a in aggs if a[3].lower() == "time"]
        for _c, spec, _p, _f in time_aggs:
            if spec.name not in ("count", "first", "last", "min", "max"):
                raise QueryError(f"{spec.name}(time) is not supported")
        aggs = [a for a in aggs if a[3].lower() != "time"]

        needed_fields = sorted({a[3] for a in aggs})
        field_filter_fields = sorted(cond.row_filter_refs(sc))
        read_fields = sorted(set(needed_fields) | set(field_filter_fields))
        if time_aggs and not read_fields:
            read_fields = None  # time-only aggregates: read every field

        dtype = templates.compute_dtype()
        per_field_aggs: dict[str, list] = {}
        for _call, spec, _params, fname in aggs:
            per_field_aggs.setdefault(fname, []).append(spec.name)
        grid_ctx = (W, group_time.every_ns) if group_time else None
        batches: dict[str, object] = {
            f: pick_batch(schema, per_field_aggs[f], f, dtype, grid_ctx)
            for f in needed_fields
        }

        # incremental result cache (reference inc_agg_transform +
        # lib/resultcache): GROUP BY time() windows whose shards took no
        # writes since the last execution are served from cached
        # (value, count) cells; only the stale hull is scanned/computed
        cache_plan = None
        if (
            group_time is not None
            and W >= 1
            and self.router is None
            and ctx.live is None
            and not time_aggs
            and len(ctx.group_keys) <= 20_000  # cache growth gate
            and W <= 16_384  # > _MAX_WINDOWS would evict itself every run
            and all(hasattr(sh, "data_version") for sh in shards)
        ):
            from opengemini_tpu.query import resultcache as rcache

            fp = rcache.fingerprint(
                db, rp, mst, sc, group_time, group_tags,
                stmt.group_by_all_tags,
                [(spec.name, params, fname)
                 for _c, spec, params, fname in aggs],
            )
            cache_plan = rcache.CachePlan(
                self._inc_cache, fp, shards, aligned,
                group_time.every_ns, W, len(aggs), tmin, tmax)
        full_hit = cache_plan is not None and not cache_plan.scan_ranges
        scan_ranges = [(tmin, tmax)]
        if cache_plan is not None and cache_plan.scan_ranges:
            # disjoint stale runs: a now()-relative dashboard query scans
            # only its partial edge windows + actually-written windows
            scan_ranges = [
                (max(tmin, lo), min(tmax, hi))
                for lo, hi in cache_plan.scan_ranges
            ]

        # string fields only support count on the device path (reference
        # supports first/last/distinct on strings — host path, later round)
        for call, spec, params, field_name in aggs:
            if schema.get(field_name) == FieldType.STRING and spec.name != "count":
                raise QueryError(
                    f"{spec.name}() is not supported on string field {field_name!r}"
                )
        # selector ordering uses an int32 (hi, lo) split of rel ns; guard the
        # 2^61 ns (~73 year) cliff explicitly rather than wrapping silently
        if tmax - aligned >= (1 << 61):
            raise QueryError("time range too large (over ~73 years) for aggregation")

        # pre-aggregation fast path (reference: immutable/pre_aggregation.go
        # block skipping, SURVEY.md §7 'before device transfer'): for
        # full-range count/sum/mean with no field filter, chunks wholly
        # inside the range contribute their stored (count, sum) WITHOUT
        # being decoded or transferred. Safe only when the series' sources
        # cannot overlap (no memtable rows in range, non-overlapping chunks).
        pre_eligible = (
            not group_time
            and not time_aggs
            and not sc.has_row_filter
            and all(spec.name in ("count", "sum", "mean") for _c, spec, _p, _f in aggs)
            # remote proxies carry no chunk metadata: full decode for them
            and all(getattr(sh, "supports_preagg", False) for sh in shards)
        )
        # pre-agg accumulators: int64 for INT fields (stored vsum values are
        # exact python ints), float64 otherwise
        def _pre_dtype(f):
            return np.int64 if schema.get(f) == FieldType.INT else np.float64

        pre_count = (
            {f: np.zeros(num_segments, np.int64) for f in needed_fields}
            if pre_eligible else {}
        )
        pre_sum = (
            {f: np.zeros(num_segments, _pre_dtype(f)) for f in needed_fields}
            if pre_eligible else {}
        )
        sum_fields = {f for _c, spec, _p, f in aggs if spec.name != "count"}
        pre_used = False

        rows_scanned = 0
        time_segs: list[np.ndarray] = []
        time_vals: list[np.ndarray] = []

        def _scan_record(rec, seg, sids=None):
            if time_aggs:
                m = fmask if fmask is not None else slice(None)
                time_segs.append(seg[m])
                time_vals.append(rec.times[m])
            _add_record_to_batches(
                rec, seg, aligned, needed_fields, batches, dtype, fmask,
                sids=sids,
            )

        with trace.span("scan") as scan_span:
            # batched multi-series path: one bulk decode per shard when
            # many series are scanned (packed colstore chunks decode once
            # for all their series; kills the per-sid Python loop that
            # dominated config #5 — BASELINE.md round-2 profile)
            remaining_plan = [] if full_hit else scan_plan
            if not pre_eligible and not full_hit:
                by_shard: dict[int, tuple] = {}
                for sh, sid, gid in scan_plan:
                    by_shard.setdefault(id(sh), (sh, []))[1].append((sid, gid))
                remaining_plan = []
                for sh, pairs in by_shard.values():
                    if len(pairs) < 64 or not hasattr(sh, "read_series_bulk"):
                        remaining_plan.extend(
                            (sh, sid, gid) for sid, gid in pairs)
                        continue
                    TRACKER.check()
                    sid_list = np.asarray([p[0] for p in pairs], np.int64)
                    gid_list = np.asarray([p[1] for p in pairs], np.int64)
                    o = np.argsort(sid_list)
                    sid_sorted, gid_sorted = sid_list[o], gid_list[o]
                    for rlo, rhi in scan_ranges:
                        sid_arr, rec = sh.read_series_bulk(
                            mst, sid_sorted, rlo, rhi, fields=read_fields)
                        if len(rec) == 0:
                            continue
                        rows_scanned += len(rec)
                        fmask = (
                            cond.eval_row_filter(sc, rec, sid_arr=sid_arr,
                                                 index=sh.index)
                            if sc.has_row_filter
                            else None
                        )
                        gid_rows = gid_sorted[
                            np.searchsorted(sid_sorted, sid_arr)]
                        if group_time:
                            widx, _ = winmod.window_index(
                                rec.times, tmin, group_time.every_ns,
                                group_time.offset_ns)
                            seg = (gid_rows * W + widx.astype(np.int64)
                                   ).astype(np.int32)
                        else:
                            seg = gid_rows.astype(np.int32)
                        _scan_record(rec, seg, sids=sid_arr)
            for sh, sid, gid in remaining_plan:
                TRACKER.check()  # KILL QUERY cancellation point
                if pre_eligible:
                    handled, got_rows = self._scan_preagg(
                        sh, mst, sid, gid, tmin, tmax, needed_fields,
                        batches, pre_count, pre_sum, dtype, aligned, sum_fields,
                    )
                    if handled:
                        pre_used = True
                        rows_scanned += got_rows
                        continue
                for rlo, rhi in scan_ranges:
                    rec = sh.read_series(mst, sid, rlo, rhi,
                                         fields=read_fields)
                    if len(rec) == 0:
                        continue
                    rows_scanned += len(rec)
                    fmask = (
                        cond.eval_row_filter(
                            sc, rec, tags=sh.index.tags_of(sid))
                        if sc.has_row_filter
                        else None
                    )
                    if group_time:
                        widx, _ = winmod.window_index(
                            rec.times, tmin, group_time.every_ns,
                            group_time.offset_ns)
                        seg = (gid * W + widx.astype(np.int64)
                               ).astype(np.int32)
                    else:
                        seg = np.full(len(rec), gid, dtype=np.int32)
                    _scan_record(rec, seg, sids=sid)
            scan_span.add_field("rows", rows_scanned)
        STATS.incr("executor", "rows_scanned", rows_scanned)

        # run aggregates on device
        agg_results = {}  # id(call) -> (values, sel, counts)
        with trace.span("device_compute") as sp:
            for call, spec, params, field_name in aggs:
                if full_hit:
                    # every window served from cache: no scan, no device
                    dt = (np.int64 if isinstance(
                        batches[field_name], ragged.IntExactBatch)
                        and spec.name in ("sum", "count") else np.float64)
                    agg_results[id(call)] = (
                        np.zeros(num_segments, dt), None,
                        np.zeros(num_segments, np.int64), spec,
                        field_name, None)
                    continue
                out, sel, counts = batches[field_name].run(spec, num_segments, params)
                if pre_used:
                    # combine device partials with pre-agg contributions
                    pc = pre_count[field_name]
                    ps = pre_sum[field_name]
                    if spec.name == "count":
                        out = out + pc
                    elif spec.name == "sum":
                        out = out + ps
                    else:  # mean = (dev_sum + pre_sum) / (dev_cnt + pre_cnt)
                        dev_sum, _s, _c = batches[field_name].run(
                            aggmod.get("sum"), num_segments
                        )
                        total_c = counts + pc
                        out = (dev_sum + ps) / np.maximum(total_c, 1)
                    counts = counts + pc.astype(counts.dtype)
                agg_results[id(call)] = (out, sel, counts, spec, field_name, None)
            if time_aggs:
                import dataclasses as _dc

                seg_all = (
                    np.concatenate(time_segs) if time_segs
                    else np.empty(0, np.int32)
                )
                t_all = (
                    np.concatenate(time_vals) if time_vals
                    else np.empty(0, np.int64)
                )
                tcounts = np.bincount(seg_all, minlength=num_segments).astype(np.int64)
            for call, spec, _params, _f in time_aggs:
                if spec.name == "count":
                    tout = tcounts
                elif spec.name in ("last", "max"):
                    tout = np.full(num_segments, np.iinfo(np.int64).min, np.int64)
                    np.maximum.at(tout, seg_all, t_all)
                else:  # first/min
                    tout = np.full(num_segments, np.iinfo(np.int64).max, np.int64)
                    np.minimum.at(tout, seg_all, t_all)
                spec2 = _dc.replace(spec, int_output=True)
                agg_results[id(call)] = (tout, None, tcounts, spec2, "time", tout)
            sp.add_field("aggregates", len(aggs))
            sp.add_field("segments", num_segments)
            sp.add_field(
                "batch_rows", {f: b.n for f, b in batches.items()}
            )
            STATS.incr("executor", "device_batches", len(aggs))

        has_remote_data = any(
            isinstance(sh, pcluster.MetaShard) for sh in shards
        )
        if pushdown and ctx.live is not None and has_remote_data:
            # aggregate pushdown: peers computed the same grid over their
            # shards; merge their O(groups x windows) partial arrays
            # (reference: rpc_transform partial agg + merge_transform)
            from opengemini_tpu.sql import astjson

            with trace.span("remote_partials") as sp:
                req = {
                    "db": db, "rp": rp, "mst": mst,
                    "tmin": tmin, "tmax": tmax, "aligned": aligned,
                    "every_ns": group_time.every_ns if group_time else 0,
                    "offset_ns": group_time.offset_ns if group_time else 0,
                    "W": W, "group_tags": group_tags,
                    "aggs": per_field_aggs,
                    "tag_expr": astjson.to_json(sc.tag_expr),
                    "field_expr": astjson.to_json(sc.field_expr),
                    "mixed_expr": astjson.to_json(sc.mixed_expr),
                    "mixed_series_level": sc.mixed_series_level,
                }
                peer_docs = self.router.select_partials(req, ctx.live)
                if peer_docs:
                    pmod.merge_remote_partials(
                        agg_results, aggs, batches, group_keys, W,
                        peer_docs, group_tags,
                    )
                sp.add_field("peers", len(peer_docs))

        if cache_plan is not None:
            with trace.span("inc_cache"):
                group_keys = cache_plan.merge(agg_results, aggs, group_keys)
        with trace.span("render"):
            return self._render_agg(
                stmt, mst, group_tags, group_keys, aligned, W, agg_results,
                batches, schema, tmin,
            )

    def _scan_preagg(
        self, sh, mst, sid, gid, tmin, tmax, needed_fields,
        batches, pre_count, pre_sum, dtype, aligned, sum_fields,
    ) -> tuple[bool, int]:
        """Try the pre-agg path for one series. Returns (handled, rows):
        handled=False -> caller does the normal decode+batch scan. No side
        effects until the whole series validates."""
        needs_merge, srcs = _series_needs_merged_decode(sh, mst, sid, tmin, tmax)
        if needs_merge:
            return False, 0  # dedup required: decode via read_series
        if not srcs:
            return True, 0  # nothing in range at all
        # validate: every fully-covered chunk must carry a sum for fields
        # that need one (bool/string columns store count-only pre-agg)
        contrib: list[tuple[str, int, float | None]] = []
        full_rows = 0
        partials = []
        for r, c in srcs:
            if tmin <= c.tmin and c.tmax < tmax:
                for fname in needed_fields:
                    loc = c.cols.get(fname)
                    if loc is None:
                        continue
                    pre = loc["pre"]
                    if not pre.count:
                        continue
                    if fname in sum_fields and pre.vsum is None:
                        return False, 0
                    contrib.append((fname, pre.count, pre.vsum))
                full_rows += c.rows
            else:
                partials.append((r, c))
        for fname, cnt, vsum in contrib:
            pre_count[fname][gid] += cnt
            if vsum is not None:
                pre_sum[fname][gid] += vsum
        rows = full_rows
        for r, c in partials:
            rec = r.read_chunk(mst, c, needed_fields).slice_time(tmin, tmax)
            if not len(rec):
                continue
            rows += len(rec)
            seg = np.full(len(rec), gid, dtype=np.int32)
            _add_record_to_batches(
                rec, seg, aligned, needed_fields, batches, dtype, None,
                sids=sid,
            )
        return True, rows

    def _group_tags(self, stmt, shards, mst) -> list[str]:
        if stmt.group_by_all_tags:
            keys: set[str] = set()
            for sh in shards:
                keys.update(sh.index.tag_keys(mst))
            return sorted(keys)
        return list(stmt.group_by_tags)

    def _render_agg(
        self, stmt, mst, group_tags, group_keys, aligned, W, agg_results,
        batches, schema, tmin,
    ) -> list[dict]:
        group_time = stmt.group_by_time
        every = group_time.every_ns if group_time else 0

        columns = ["time"]
        col_exprs = []
        used_names: dict[str, int] = {}
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.VarRef) and e.name.lower() == "time":
                continue  # explicit `time` is always column 0
            name = f.alias or _default_field_name(f.expr)
            k = used_names.get(name, 0)
            used_names[name] = k + 1
            if k:
                name = f"{name}_{k}"
            columns.append(name)
            col_exprs.append(f.expr)

        # selector fast path: a single selector call (bare, or wrapped in
        # scalar math like `max(rx) * 1`), no GROUP BY time -> result time
        # is the selected point's own timestamp (reference
        # TestServer_Query_Aggregates_Math#2)
        single_selector = None
        if not group_time and len(col_exprs) == 1:
            calls = _calls_in(col_exprs[0])
            if len(calls) == 1:
                entry = agg_results.get(id(calls[0]))
                if entry and entry[3].is_selector:
                    single_selector = entry

        host_times = (
            batches[single_selector[4]].host_times()
            if single_selector is not None and single_selector[5] is None
            else None
        )
        out_series = []
        order = sorted(range(len(group_keys)), key=lambda g: group_keys[g])
        for g in order:
            key = group_keys[g]
            rows = []
            for w in range(W):
                seg = g * W + w
                t_out = aligned + w * every if group_time else (aligned if aligned else 0)
                vals = []
                any_present = False
                for expr in col_exprs:
                    v, present = _eval_output_expr(expr, agg_results, seg, schema)
                    any_present = any_present or present
                    vals.append(v)
                if single_selector is not None:
                    out, sel, counts, spec, fname, times_abs = single_selector
                    if counts[seg] > 0:
                        t_out = (
                            int(times_abs[seg]) if times_abs is not None
                            else int(host_times[sel[seg]])
                        )
                rows.append((t_out, vals, any_present))
            if not any(p for _t, _v, p in rows):
                # zero matching points in the whole range: no series at
                # all, regardless of fill (TestServer_Query_Fill#2)
                continue
            count_idx = tuple(
                i for i, e in enumerate(col_exprs)
                if isinstance(_strip_expr(e), ast.Call)
                and _strip_expr(e).name in ("count", "count_distinct")
            )
            rows = _apply_fill(rows, stmt, columns, count_idx)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset :]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {
                "name": mst,
                "columns": columns,
                "values": [[t] + v for t, v, _p in rows],
            }
            if group_tags:
                series["tags"] = dict(zip(group_tags, key))
            out_series.append(series)
        return out_series

    # -- percentile_approx (chunk-histogram sketches) ------------------------

    def _select_percentile_approx(self, stmt, db, rp, mst, now_ns, call) -> list[dict]:
        """percentile_approx(field, q): served from the per-chunk histogram
        sketches in TSF pre-agg metadata — covered chunks contribute their
        histograms with NO data decode (reference: OGSketch, persisted).
        Memtable rows, partially-covered and histogram-less chunks decode
        and bin exactly. Error: within one chunk-histogram bin width
        (chunk_range/32) for sketch-served mass, one global bin width
        (range/256) for directly-binned rows."""
        from opengemini_tpu.query.sketch import HistSketch

        if stmt.group_by_time is not None:
            raise QueryError("percentile_approx() does not support GROUP BY time yet")
        if len(call.args) != 2:
            raise QueryError("percentile_approx() takes (field, q)")
        fld = _strip_expr(call.args[0])
        if not isinstance(fld, ast.VarRef):
            raise QueryError("percentile_approx() field must be a field name")
        qv = float(_call_param_value(call.args[1]))
        if not (0 <= qv <= 100):
            raise QueryError("percentile_approx() q must be between 0 and 100")
        fname = fld.name
        ctx = self._scan_context(stmt, db, rp, mst, now_ns)
        if ctx is None:
            return []
        if ctx.schema.get(fname) not in (FieldType.FLOAT, FieldType.INT):
            raise QueryError("percentile_approx() requires a numeric field")
        if ctx.sc.has_row_filter:
            raise QueryError("percentile_approx() does not support field filters")
        tmin, tmax = ctx.tmin, ctx.tmax

        # pass 1: per group, chunk hists (zero decode) or decoded values;
        # any dedup risk (overlapping chunks / memtable rows) falls the
        # whole series back to the merged read_series view
        plans: dict[int, list] = {}  # gid -> [(kind, payload)]
        bounds: dict[int, list] = {}

        def _add_vals(gid, vals):
            vals = vals[np.isfinite(vals)]  # nan/inf points never bin
            if not len(vals):
                return
            plans.setdefault(gid, []).append(("values", vals))
            b = bounds.setdefault(gid, [np.inf, -np.inf])
            b[0] = min(b[0], float(vals.min()))
            b[1] = max(b[1], float(vals.max()))

        for sh, sid, gid in ctx.scan_plan:
            TRACKER.check()  # KILL QUERY cancellation point
            needs_merge, srcs = _series_needs_merged_decode(sh, mst, sid, tmin, tmax)
            if needs_merge:
                rec = sh.read_series(mst, sid, tmin, tmax, fields=[fname])
                col = rec.columns.get(fname)
                if col is not None and len(rec):
                    _add_vals(gid, col.values[col.valid].astype(np.float64))
                continue
            for r, c in srcs:
                loc = c.cols.get(fname)
                pre = loc["pre"] if loc else None
                covered = tmin <= c.tmin and c.tmax < tmax
                if covered and pre is not None and pre.count and pre.hist is not None:
                    plans.setdefault(gid, []).append(("hist", pre))
                    b = bounds.setdefault(gid, [np.inf, -np.inf])
                    b[0] = min(b[0], pre.vmin)
                    b[1] = max(b[1], pre.vmax)
                else:
                    rec = r.read_chunk(mst, c, [fname]).slice_time(tmin, tmax)
                    col = rec.columns.get(fname)
                    if col is not None and len(rec):
                        _add_vals(gid, col.values[col.valid].astype(np.float64))

        name = stmt.fields[0].alias or "percentile_approx"
        out_series = []
        order = sorted(range(len(ctx.group_keys)), key=lambda g: ctx.group_keys[g])
        t0 = ctx.aligned if ctx.aligned else 0
        for g in order:
            entries = plans.get(g)
            if not entries:
                continue
            lo, hi = bounds[g]
            sk = HistSketch(lo, hi)
            for kind, payload in entries:
                if kind == "hist":
                    sk.add_chunk_hist(payload.vmin, payload.vmax, payload.hist)
                else:
                    sk.add_values(payload)
            v = sk.percentile(qv)
            if v is None:
                continue
            rows = [[t0, v]]
            if not stmt.ascending:
                rows.reverse()
            rows = rows[stmt.offset :]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {"name": mst, "columns": ["time", name], "values": rows}
            if ctx.group_tags:
                series["tags"] = dict(zip(ctx.group_tags, ctx.group_keys[g]))
            out_series.append(series)
        return out_series

    # -- selector + auxiliary columns (host path) ----------------------------

    def _select_selector_aux(self, stmt, db, rp, mst, now_ns, plan) -> list[dict]:
        """One selector call + bare/arithmetic auxiliary columns: the
        selector picks rows, aux columns are read from the selected rows
        (reference: aux fields in the cursor iterators, call iterator
        top/bottom transforms).  time = the selected point's timestamp,
        except 1-row selectors under GROUP BY time, which emit the window
        start (matching the reference's output tables)."""
        sel_call, aux_fields = plan
        sel_name = sel_call.name
        sel_field = _strip_expr(sel_call.args[0]).name
        n_rows = 1
        if sel_name in ("top", "bottom"):
            if len(sel_call.args) != 2:
                raise QueryError(f"{sel_name}() takes (field, N)")
            n_rows = int(_call_param_value(sel_call.args[1]))
            if n_rows <= 0:
                raise QueryError(f"{sel_name}() N must be positive")
        pctl = None
        if sel_name == "percentile":
            if len(sel_call.args) != 2:
                raise QueryError("percentile() takes (field, p)")
            pctl = float(_call_param_value(sel_call.args[1]))

        ctx = self._scan_context(stmt, db, rp, mst, now_ns)
        if ctx is None:
            return []
        sc, schema = ctx.sc, ctx.schema
        tmin, tmax = ctx.tmin, ctx.tmax
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W
        every = group_time.every_ns if group_time else 0

        if (schema.get(sel_field) == FieldType.STRING
                and sel_name not in ("first", "last")):
            raise QueryError(
                f"{sel_name}() is not supported on string field {sel_field!r}")

        # output columns: drop explicit bare `time` refs (always col 0)
        columns = ["time"]
        col_plans = []  # ("sel",) | ("aux", expr)
        used_names: dict[str, int] = {}
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.VarRef) and e.name.lower() == "time":
                continue
            name = f.alias or _default_field_name(e)
            k = used_names.get(name, 0)
            used_names[name] = k + 1
            if k:
                name = f"{name}_{k}"
            columns.append(name)
            if isinstance(e, ast.Call):
                col_plans.append(("sel",))
            else:
                col_plans.append(("aux", e))

        aux_field_names = [n for n in aux_fields if n in schema]
        read_fields = sorted({sel_field, *aux_field_names}
                             | cond.row_filter_refs(sc))

        groups: dict[int, list] = {}
        for sh, sid, gid in ctx.scan_plan:
            groups.setdefault(gid, []).append((sh, sid))

        out_series = []
        for gid in sorted(groups, key=lambda g: ctx.group_keys[g]):
            key = ctx.group_keys[gid]
            # gather rows of every member series: time, selector value,
            # aux field columns, per-row tag values
            t_list, v_list = [], []
            aux_cols: dict[str, list] = {n: [] for n in aux_field_names}
            aux_valid: dict[str, list] = {n: [] for n in aux_field_names}
            tag_cols: dict[str, list] = {}
            tag_names = {
                n for n in aux_fields if n not in schema
            }
            for n in tag_names:
                tag_cols[n] = []
            for sh, sid in groups[gid]:
                TRACKER.check()
                rec = sh.read_series(mst, sid, tmin, tmax, fields=read_fields)
                col = rec.columns.get(sel_field)
                if col is None or len(rec) == 0:
                    continue
                m = col.valid.copy()
                if sc.has_row_filter:
                    m &= cond.eval_row_filter(sc, rec,
                                              tags=sh.index.tags_of(sid))
                if not m.any():
                    continue
                t_list.append(rec.times[m])
                v_list.append(col.values[m])
                nsel = int(m.sum())
                for n in aux_field_names:
                    ac = rec.columns.get(n)
                    if ac is None:
                        aux_cols[n].append(np.full(nsel, np.nan))
                        aux_valid[n].append(np.zeros(nsel, bool))
                    else:
                        aux_cols[n].append(np.asarray(ac.values)[m])
                        aux_valid[n].append(np.asarray(ac.valid)[m])
                _, tags = sh.index.series_entry(sid)
                tagd = dict(tags)
                for n in tag_names:
                    tag_cols[n].append([tagd.get(n)] * nsel)
            if not t_list:
                continue
            t = np.concatenate(t_list)
            v = np.concatenate(v_list)
            order = np.argsort(t, kind="stable")
            t, v = t[order], v[order]
            aux_arr = {
                n: (np.concatenate(aux_cols[n])[order],
                    np.concatenate(aux_valid[n])[order])
                for n in aux_field_names
            }
            tag_arr = {
                n: [x for chunk in tag_cols[n] for x in chunk]
                for n in tag_names
            }
            for n, vals in tag_arr.items():
                tag_arr[n] = [vals[i] for i in order]

            if group_time:
                bounds = np.searchsorted(
                    t, [aligned + w * every for w in range(W + 1)]
                )
                windows = [
                    (aligned + w * every, slice(bounds[w], bounds[w + 1]))
                    for w in range(W)
                ]
            else:
                windows = [(aligned, slice(None))]

            rows = []
            for t_out, sl in windows:
                tw, vw = t[sl], v[sl]
                base = sl.start or 0
                if len(vw) == 0:
                    if n_rows == 1 and sel_name not in ("top", "bottom"):
                        rows.append((t_out, [None] * (len(columns) - 1), False))
                    continue
                idxs = _selector_pick(sel_name, tw, vw, n_rows, pctl)
                for i in idxs:
                    ri = base + int(i)
                    vals = []
                    for cp in col_plans:
                        if cp[0] == "sel":
                            vals.append(_render_cell(
                                v[ri], schema.get(sel_field), sel_name))
                        else:
                            vals.append(_eval_aux_expr(
                                cp[1], ri, aux_arr, tag_arr, schema))
                    t_row = (
                        t_out
                        if (group_time and n_rows == 1
                            and sel_name not in ("top", "bottom"))
                        else int(t[ri])
                    )
                    rows.append((t_row, vals, True))
            if n_rows == 1 and sel_name not in ("top", "bottom"):
                rows = _apply_fill(rows, stmt, columns)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset:]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {
                "name": mst,
                "columns": columns,
                "values": [[tr] + vv for tr, vv, _p in rows],
            }
            if ctx.group_tags:
                series["tags"] = dict(zip(ctx.group_tags, key))
            out_series.append(series)
        return out_series

    def _select_top_companions(self, stmt, ctx, multi_plan, mst) -> list[dict]:
        """top()/bottom() with companion projections: select rows by the
        call, then evaluate every other projection against the SELECTED
        source rows (wildcards expand to fields+tags; scalar math follows
        the raw-path null rules). Reference: the reference's top/bottom
        transform keeps auxiliary columns from the winning rows
        (TestServer_Query_For_BugList#2, TestServer_SubQuery_Top_Min#0)."""
        sel_name, call_name, sel_field, params = multi_plan
        sc, schema, tag_keys = ctx.sc, ctx.schema, ctx.tag_keys
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W

        cols = []  # (output name, spec)
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Call):
                cols.append((f.alias or _default_field_name(e), ("top",)))
            elif isinstance(e, ast.Wildcard):
                for n in sorted(set(schema) | tag_keys):
                    if n in schema:
                        cols.append((n, ("field", n)))
                    else:
                        cols.append((n, ("tag", n)))
            elif isinstance(e, ast.VarRef):
                kind = ("tag", e.name) if e.name in tag_keys and \
                    e.name not in schema else ("field", e.name)
                cols.append((f.alias or e.name, kind))
            else:
                cols.append((f.alias or _default_field_name(f.expr),
                             ("expr", e)))
        need_fields = {sel_field}
        for _n, spec in cols:
            if spec[0] == "field":
                need_fields.add(spec[1])
            elif spec[0] == "expr":
                need_fields |= _scalar_refs(spec[1])
        read_fields = sorted((need_fields | cond.row_filter_refs(sc))
                             & set(schema))

        groups: dict[tuple, list] = {}
        for sh, sid, gid in ctx.scan_plan:
            groups.setdefault(ctx.group_keys[gid], []).append((sh, sid))

        out_series = []
        for key in sorted(groups):
            times_l, topv_l, rowcols_l, tags_l = [], [], [], []
            for sh, sid in groups[key]:
                TRACKER.check()
                rec = sh.read_series(mst, sid, ctx.tmin, ctx.tmax,
                                     fields=read_fields)
                col = rec.columns.get(sel_field)
                if col is None or len(rec) == 0:
                    continue
                m = col.valid.copy()
                if sc.has_row_filter:
                    m &= cond.eval_row_filter(
                        sc, rec, tags=sh.index.tags_of(sid))
                if not m.any():
                    continue
                times_l.append(rec.times[m])
                topv_l.append(col.values[m].astype(np.float64))
                per = {}
                for fname in read_fields:
                    c2 = rec.columns.get(fname)
                    if c2 is not None:
                        per[fname] = (c2.values[m], c2.valid[m], c2.ftype)
                rowcols_l.append(per)
                tags_l.append((sh.index.tags_of(sid), int(m.sum())))
            if not times_l:
                continue
            t = np.concatenate(times_l)
            v = np.concatenate(topv_l)
            src_i = np.concatenate([
                np.full(n, i, np.int32)
                for i, (_tg, n) in enumerate(tags_l)
            ])
            off_i = np.concatenate([
                np.arange(n, dtype=np.int64) for _tg, n in tags_l
            ])
            order = np.argsort(t, kind="stable")
            t, v, src_i, off_i = t[order], v[order], src_i[order], off_i[order]

            def window_bounds():
                if not group_time:
                    return [slice(None)]
                bs = np.searchsorted(
                    t, [aligned + w * group_time.every_ns for w in range(W + 1)])
                return [slice(bs[w], bs[w + 1]) for w in range(W)]

            def row_value(spec, si, oi):
                per = rowcols_l[si]
                if spec[0] == "tag":
                    return tags_l[si][0].get(spec[1])
                if spec[0] == "field":
                    got = per.get(spec[1])
                    if got is None or not got[1][oi]:
                        return None
                    return _pyval(got[0][oi], got[2])
                return _eval_scalar_row(spec[1], per, tags_l[si][0], oi)

            rows = []
            for sl in window_bounds():
                idx = fnmod.select_top_bottom_idx(
                    call_name, t[sl], v[sl], params)
                base = sl.start or 0
                for i in idx:
                    gi = base + int(i)
                    row = [int(t[gi])]
                    for _n, spec in cols:
                        if spec[0] == "top":
                            row.append(_pyval(v[gi], schema.get(sel_field)))
                        else:
                            row.append(
                                row_value(spec, int(src_i[gi]), int(off_i[gi])))
                    rows.append(row)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset:]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {"name": mst, "columns": ["time"] + [n for n, _s in cols],
                      "values": rows}
            if ctx.group_tags:
                series["tags"] = dict(zip(ctx.group_tags, key))
            out_series.append(series)
        return out_series

    # -- host function path (transforms, mode/integral/top/bottom/...) ------

    def _select_host(self, stmt, db, rp, mst, now_ns) -> list[dict]:
        """General host path for calls outside the device aggregate set
        (reference: sql-side transform processors, SURVEY.md §2.3)."""
        ctx = self._scan_context(stmt, db, rp, mst, now_ns)
        if ctx is None:
            return []
        sc, schema = ctx.sc, ctx.schema
        tmin, tmax = ctx.tmin, ctx.tmax
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W
        group_tags = ctx.group_tags
        if group_time:
            window_times = [aligned + w * group_time.every_ns for w in range(W)]
        else:
            window_times = [aligned]
        groups: dict[tuple, list] = {}
        for sh, sid, gid in ctx.scan_plan:
            groups.setdefault(ctx.group_keys[gid], []).append((sh, sid))

        # top/bottom with companion columns (wildcards, fields, math):
        # detected before plan resolution — companions are not calls
        if len(stmt.fields) > 1:
            tb = [
                _strip_expr(f.expr) for f in stmt.fields
                if isinstance(_strip_expr(f.expr), ast.Call)
                and _strip_expr(f.expr).name.lower() in ("top", "bottom")
            ]
            if len(tb) == 1 and all(
                not isinstance(_strip_expr(f.expr), ast.Call)
                or _strip_expr(f.expr) is tb[0]
                for f in stmt.fields
            ):
                e = tb[0]
                _kind, call_name, field, params, _inner = _resolve_host_call(
                    e, group_time)
                name = next(
                    (f.alias for f in stmt.fields
                     if _strip_expr(f.expr) is e and f.alias),
                    _default_field_name(e))
                return self._select_top_companions(
                    stmt, ctx, (name, call_name, field, params), mst)

        # resolve output columns
        plans = []  # (name, kind, call_name, field, params, inner_agg|None)
        multi_plan = None
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if not isinstance(e, ast.Call):
                raise QueryError(
                    "expressions mixing functions and math are not supported "
                    "in the host function path yet"
                )
            name = f.alias or _default_field_name(e)
            kind, call_name, field, params, inner = _resolve_host_call(e, group_time)
            _check_host_field_type(
                inner[0] if kind == "sliding" and inner else call_name,
                field, schema)
            if kind == "multi":
                if len(stmt.fields) > 1:
                    raise QueryError(f"{call_name}() must be the only field")
                multi_plan = (name, call_name, field, params)
            else:
                plans.append((name, kind, call_name, field, params, inner))

        out_series = []
        for key in sorted(groups):
            rows_by_field: dict[str, tuple[np.ndarray, np.ndarray]] = {}

            def field_rows(fname: str):
                got = rows_by_field.get(fname)
                if got is not None:
                    return got
                ts_list, vs_list = [], []
                for sh, sid in groups[key]:
                    TRACKER.check()  # KILL QUERY cancellation point
                    rec = sh.read_series(
                        mst, sid, tmin, tmax,
                        fields=[fname] + sorted(cond.row_filter_refs(sc)))
                    col = rec.columns.get(fname)
                    if col is None or len(rec) == 0:
                        continue
                    m = col.valid.copy()
                    if sc.has_row_filter:
                        m &= cond.eval_row_filter(
                            sc, rec, tags=sh.index.tags_of(sid))
                    ts_list.append(rec.times[m])
                    vs_list.append(col.values[m])
                if not ts_list:
                    got = (np.empty(0, np.int64), np.empty(0))
                else:
                    t = np.concatenate(ts_list)
                    v = np.concatenate(vs_list)
                    order = np.argsort(t, kind="stable")
                    got = (t[order], v[order])
                rows_by_field[fname] = got
                return got

            def window_slices(t: np.ndarray):
                if not group_time:
                    return [(window_times[0], slice(None))]
                bounds = np.searchsorted(
                    t, [aligned + w * group_time.every_ns for w in range(W + 1)]
                )
                return [
                    (window_times[w], slice(bounds[w], bounds[w + 1]))
                    for w in range(W)
                ]

            if multi_plan is not None:
                name, call_name, fname, params = multi_plan
                t, v = field_rows(fname)
                rows = []
                for wt, sl in window_slices(t):
                    for rt, rv in fnmod.multi_row(call_name, t[sl], v[sl], params):
                        rows.append([rt if rt is not None else wt, rv])
                if not stmt.ascending:
                    rows.reverse()
                if stmt.offset:
                    rows = rows[stmt.offset :]
                if stmt.limit:
                    rows = rows[: stmt.limit]
                if not rows:
                    continue
                series = {"name": mst, "columns": ["time", name], "values": rows}
                if group_tags:
                    series["tags"] = dict(zip(group_tags, key))
                out_series.append(series)
                continue

            # single raw transform: emit rows directly — dict keying would
            # collapse rows when two series in the group share a timestamp
            if len(plans) == 1 and plans[0][1] == "transform_raw":
                name, _kind, call_name, fname, params, _inner = plans[0]
                t, v = field_rows(fname)
                if not stmt.ascending:
                    # ORDER BY time DESC: the transform runs over the
                    # DESC-ordered sequence (reference Null_Aggregate desc
                    # difference cases — sign and row times follow the
                    # reversed walk, not a reversed asc result)
                    t_out, v_out = fnmod.transform(
                        call_name, t[::-1], v[::-1], params
                    )
                else:
                    t_out, v_out = fnmod.transform(call_name, t, v, params)
                rows = [
                    (int(tt), [fnmod.py_value(vv)], True)
                    for tt, vv in zip(t_out, v_out)
                ]
                if stmt.offset:
                    rows = rows[stmt.offset :]
                if stmt.limit:
                    rows = rows[: stmt.limit]
                if not rows:
                    continue
                series = {
                    "name": mst,
                    "columns": ["time", name],
                    "values": [[t0] + vv for t0, vv, _p in rows],
                }
                if group_tags:
                    series["tags"] = dict(zip(group_tags, key))
                out_series.append(series)
                continue

            col_maps: list[dict] = []  # per plan: {time: value}
            has_plain_agg = False
            sliding_grid: list | None = None
            for name, kind, call_name, fname, params, inner in plans:
                t, v = field_rows(fname)
                if kind == "agg":
                    has_plain_agg = True
                    m: dict = {}
                    for wt, sl in window_slices(t):
                        val, sel_t = fnmod.host_agg(call_name, t[sl], v[sl], params)
                        if val is not None:
                            m[wt] = (val, sel_t)
                    col_maps.append(m)
                elif kind == "sliding":
                    n = int(params[0])
                    slices = window_slices(t)
                    m = {}
                    sliding_grid = [wt for wt, _sl in slices[: max(len(slices) - n + 1, 0)]]
                    for i in range(0, len(slices) - n + 1):
                        lo = slices[i][1].start or 0
                        hi = slices[i + n - 1][1].stop
                        val, _sel = fnmod.host_agg(
                            inner[0], t[lo:hi], v[lo:hi], inner[1])
                        if val is not None:
                            m[slices[i][0]] = (val, None)
                    col_maps.append(m)
                elif kind == "transform_raw":
                    t_out, v_out = fnmod.transform(call_name, t, v, params)
                    col_maps.append({int(tt): (vv.item() if hasattr(vv, "item") else vv, None)
                                     for tt, vv in zip(t_out, v_out)})
                else:  # transform over inner aggregate windows
                    seq_t, seq_v = [], []
                    for wt, sl in window_slices(t):
                        val, _sel = fnmod.host_agg(inner[0], t[sl], v[sl], inner[1])
                        if val is not None:
                            seq_t.append(wt)
                            seq_v.append(val)
                    t_out, v_out = fnmod.transform(
                        call_name, np.asarray(seq_t, np.int64), np.asarray(seq_v), params
                    )
                    col_maps.append({int(tt): (float(vv), None) for tt, vv in zip(t_out, v_out)})

            if has_plain_agg and group_time:
                # transforms may emit times outside the window grid
                # (holt_winters forecasts) — union them in, never drop
                extra = {t for m in col_maps for t in m} - set(window_times)
                base_times = sorted(set(window_times) | extra)
            elif sliding_grid is not None:
                # sliding windows emit every output slot; empties fill null
                base_times = sliding_grid
            else:
                seen = sorted({t for m in col_maps for t in m})
                base_times = seen
            rows = []
            for bt in base_times:
                vals = []
                present = False
                for m in col_maps:
                    entry = m.get(bt)
                    if entry is None:
                        vals.append(None)
                    else:
                        vals.append(entry[0])
                        present = True
                # single bare selector-time semantics
                t_render = bt
                if len(plans) == 1 and not group_time:
                    entry = col_maps[0].get(bt)
                    if entry and entry[1] is not None:
                        t_render = entry[1]
                rows.append((t_render, vals, present))
            rows = _apply_fill(rows, stmt, ["time"] + [p[0] for p in plans])
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset :]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {
                "name": mst,
                "columns": ["time"] + [p[0] for p in plans],
                "values": [[t] + v for t, v, _p in rows],
            }
            if group_tags:
                series["tags"] = dict(zip(group_tags, key))
            out_series.append(series)
        return out_series

    # -- raw path -----------------------------------------------------------

    def _select_table_function(self, stmt, call, db: str, now_ns: int) -> dict:
        """SELECT <table_function>('<params json>') FROM m WHERE time ...
        (reference: LogicalTableFunction, logic_plan.go:3863; the one
        production operator is rca, table_function_factory.go:26). The
        measurement's raw rows in the time range are the function input;
        the result is one row holding the output graph as JSON."""
        from opengemini_tpu.query import tablefunc as tfmod

        if len(call.args) != 1:
            raise QueryError(f"{call.name}() takes one string argument")
        arg = _strip_expr(call.args[0])
        if not isinstance(arg, ast.StringLiteral):
            raise QueryError(f"{call.name}() parameter must be a quoted string")
        import dataclasses

        raw_stmt = dataclasses.replace(
            stmt, fields=[ast.Field(expr=ast.Wildcard())],
            group_by_all_tags=True, limit=0, offset=0,
        )
        rows: list[dict] = []
        for src in stmt.sources:
            if not isinstance(src, ast.Measurement):
                raise QueryError(f"{call.name}() requires a measurement source")
            src_db = src.database or db
            for series in self._select_raw(raw_stmt, src_db, src.rp or None,
                                           src.name, now_ns):
                tags = series.get("tags") or {}
                cols = series["columns"]
                for vals in series["values"]:
                    row = dict(tags)
                    for c, v in zip(cols, vals):
                        if v is not None:
                            row[c] = v
                    rows.append(row)
        try:
            graph = tfmod.TABLE_FUNCTIONS[call.name](rows, arg.val)
        except tfmod.TableFunctionError as e:
            raise QueryError(str(e)) from None
        name = stmt.sources[0].name if stmt.sources else call.name
        import json as _json

        return {"series": [_series(name, None, [call.name],
                                   [[_json.dumps(graph, sort_keys=True)]])]}

    def _select_raw(self, stmt, db, rp, mst, now_ns) -> list[dict]:
        if self.engine.is_measurement_dropped(db, mst):
            return []  # mark-deleted: hidden from SELECT pre-purge
        shards_all, _live = self._all_shards_with_remote(
            db, rp, mst, stmt.condition, now_ns
        )
        tag_keys: set[str] = set()
        schema: dict[str, FieldType] = {}
        for sh in shards_all:
            tag_keys.update(sh.index.tag_keys(mst))
            schema.update(sh.schema(mst))
        if not schema:
            if stmt.group_by_all_tags:
                # GROUP BY * requires the measurement's tag keys from
                # meta — a missing measurement is an error there, not an
                # empty result (reference meta.Measurement ->
                # ErrMeasurementNotFound; TestServer_Query_Where_Fields)
                raise QueryError("measurement not found")
            return []
        sc = cond.split(stmt.condition, tag_keys, now_ns)
        shards = [sh for sh in shards_all if sh.tmax > sc.tmin and sh.tmin < sc.tmax]
        if not shards:
            return []

        # output columns: * expands to fields + tags, except tags consumed
        # by GROUP BY (explicit or *), which surface in the series tags dict
        # (influx wildcard semantics)
        if stmt.group_by_all_tags:
            grouped_tags = tag_keys
        elif getattr(stmt, "_from_subquery", False):
            # inner EXPLICIT group-by tags are subquery output dimensions:
            # the outer wildcard lists them as columns
            grouped_tags = tag_keys - set(getattr(stmt, "_subquery_dims", ()))
        else:
            grouped_tags = set(stmt.group_by_tags)
        names: list[tuple] = []  # (output name, kind, payload)
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Wildcard):
                names.extend(
                    (n, "ref", n)
                    for n in sorted(set(schema) | (tag_keys - grouped_tags))
                )
            elif isinstance(e, ast.StringLiteral):
                # constant column (validated to carry an alias upstream)
                names.append(
                    (f.alias or _default_field_name(f.expr), "const", e.val))
            elif (
                isinstance(e, (ast.BinaryExpr, ast.UnaryExpr))
                and not _calls_in(e)
            ):
                # scalar field math (`f1 + f2 + f3`, `100 - age`): null
                # unless every referenced field is present on the row;
                # rows where ANY referenced field exists still emit
                # (reference TestServer_Query_SubqueryMath)
                names.append(
                    (f.alias or _default_field_name(f.expr), "expr", e))
            else:
                src_name = e.name if isinstance(e, ast.VarRef) else ""
                names.append(
                    (f.alias or _default_field_name(f.expr), "ref", src_name))
        # duplicate output names get _N suffixes, all columns kept —
        # `SELECT value, * FROM m` yields value, ..., value_1 (influx
        # duplicate-column naming; TestServer_Query_Wildcards#4). const/
        # expr lookups key by the FINAL (suffixed) name so colliding
        # aliases stay wired to their own payloads.
        used: dict[str, int] = {}
        out_cols = []  # (final name, source ref)
        const_cols: dict[str, str] = {}  # final name -> literal value
        expr_cols: dict[str, object] = {}  # final name -> scalar expr AST
        for n, kind, payload in names:
            k = used.get(n, 0)
            used[n] = k + 1
            final = f"{n}_{k}" if k else n
            if kind == "const":
                const_cols[final] = payload
                out_cols.append((final, final))
            elif kind == "expr":
                expr_cols[final] = payload
                out_cols.append((final, final))
            else:
                out_cols.append((final, payload or n))
        columns = ["time"] + [n for n, _s in out_cols]
        src_of = {n: s_ for n, s_ in out_cols}

        group_tags = self._group_tags(stmt, shards, mst)
        groups: dict[tuple, list] = {}
        match_terms = cond.conjunctive_match_terms(sc.field_expr)
        hinted = bool({"full_series", "specific_series"}
                      & set(getattr(stmt, "hints", ())))
        exact_tags = (
            cond.exact_series_tags(stmt.condition, tag_keys)
            if "full_series" in getattr(stmt, "hints", ()) else None
        ) or None  # no tag equalities -> the hint pins nothing
        for sh in shards:
            sids = cond.eval_tag_expr(sc.tag_expr, sh.index, mst)
            if sc.mixed_expr is not None:
                if hinted:
                    sids &= cond.series_only_sids(
                        sc.mixed_expr, sh.index, mst, sc.tag_keys)
                else:
                    sids &= cond.tag_superset_sids(
                        sc.mixed_expr, sh.index, mst, sc.tag_keys)
            if exact_tags is not None:
                sids = {s for s in sids
                        if sh.index.tags_of(s) == exact_tags}
            sids = _prune_text_sids(sh, mst, sids, match_terms)
            for sid in sorted(sids):
                tags = sh.index.tags_of(sid)
                key = tuple(tags.get(k, "") for k in group_tags)
                groups.setdefault(key, []).append((sh, sid, tags))
        if hinted:
            sc.mixed_series_level = True  # consumed at the series level

        # project only needed columns: selected fields + filter refs +
        # scalar-math operand fields
        filter_refs = cond.row_filter_refs(sc)
        expr_refs: set[str] = set()
        for e in expr_cols.values():
            expr_refs |= _scalar_refs(e)
        read_fields = sorted(
            ({src_of[c] for c in columns[1:] if src_of[c] in schema}
             | set(filter_refs) | expr_refs) & set(schema)
        )
        # tag-only selects (e.g. SELECT "name" FROM m, openGemini
        # semantics): a row exists wherever ANY field is set, so read
        # every field for presence
        tag_only = not read_fields and any(
            src_of[c] in tag_keys for c in columns[1:])
        if tag_only:
            read_fields = None
        out_series = []
        for key in sorted(groups):
            rows: list[list] = []
            for sh, sid, tags in groups[key]:
                TRACKER.check()  # KILL QUERY cancellation point
                rec = sh.read_series(mst, sid, sc.tmin, sc.tmax, fields=read_fields)
                if len(rec) == 0:
                    continue
                fmask = (
                    cond.eval_row_filter(sc, rec, tags=tags)
                    if sc.has_row_filter
                    else np.ones(len(rec), dtype=bool)
                )
                # a raw row is emitted if any selected *field* is present
                # (tag-only selects: any field at all)
                present = np.zeros(len(rec), dtype=bool)
                col_arrays = []
                for name in columns[1:]:
                    if name in const_cols:
                        col_arrays.append((None, None, const_cols[name]))
                        continue
                    ref = src_of[name]
                    if ref in expr_cols:
                        vals, valid, touched = _eval_scalar_cols(
                            expr_cols[ref], rec)
                        col_arrays.append((vals, valid, FieldType.FLOAT))
                        present |= touched
                        continue
                    col = rec.columns.get(ref)
                    if col is not None:
                        col_arrays.append((col.values, col.valid, col.ftype))
                        present |= col.valid
                    elif ref in tags:
                        col_arrays.append((None, None, tags[ref]))
                    else:
                        col_arrays.append((None, None, None))
                if tag_only:
                    for col in rec.columns.values():
                        present |= col.valid
                sel = np.nonzero(fmask & present)[0]
                for i in sel:
                    row = [int(rec.times[i])]
                    for values, valid, extra in col_arrays:
                        if values is None:
                            row.append(extra if isinstance(extra, str) else None)
                        elif valid[i]:
                            row.append(_pyval(values[i], extra))
                        else:
                            row.append(None)
                    rows.append(row)
            if not rows:
                continue
            if getattr(stmt, "_subquery_dims", None) and not group_tags:
                # ungrouped select over a dimensioned subquery keeps the
                # inner series order (rows appended per-series, ascending
                # within each — reference SubqueryForLogicalOptimize#5)
                if not stmt.ascending:
                    rows.reverse()
            else:
                rows.sort(key=lambda r: r[0], reverse=not stmt.ascending)
            series = {"name": mst, "columns": columns, "values": rows}
            if group_tags:
                series["tags"] = dict(zip(group_tags, key))
            out_series.append(series)
        if stmt.offset or stmt.limit:
            # LIMIT/OFFSET apply GLOBALLY over the time-merged row stream,
            # not per series (reference TestServer_Query_LimitAndOffset:
            # `group by tennant limit 1` returns one row total); series
            # left empty by the slice are omitted entirely
            flat = []
            for si, s in enumerate(out_series):
                flat.extend((row[0], si, row) for row in s["values"])
            flat.sort(key=lambda e: (e[0], e[1]), reverse=not stmt.ascending)
            if stmt.offset:
                flat = flat[stmt.offset:]
            if stmt.limit:
                flat = flat[: stmt.limit]
            kept: dict[int, list] = {}
            for _t, si, row in flat:
                kept.setdefault(si, []).append(row)
            out_series = [
                dict(s, values=kept[si])
                for si, s in enumerate(out_series)
                if si in kept
            ]
        return out_series

    # -- SHOW ---------------------------------------------------------------

    def _all_shards_db(self, db: str):
        return self.engine.shards_for_range(db, None, cond.MIN_TIME, cond.MAX_TIME)

    def _visible(self, db: str, mst: str) -> bool:
        """False for mark-deleted measurements (hidden from SELECT and
        metadata SHOWs; SHOW SERIES intentionally still lists their series
        until the purge — reference TestServer_Query_ShowSeries)."""
        return not self.engine.is_measurement_dropped(db, mst)

    def _show_measurements(self, stmt, db) -> dict:
        db = stmt.database or db
        names: set[str] = set()
        for sh in self._all_shards_db(db):
            names.update(m for m in sh.measurements() if self._visible(db, m))
        if self.router is not None:
            try:
                names.update(self.router.remote_measurements(db, None))
            except Exception as e:  # noqa: BLE001
                raise QueryError(str(e)) from e
        if stmt.regex:
            rx = re.compile(stmt.regex)
            names = {n for n in names if rx.search(n)}
        if not names:
            return {}
        return _series_result("measurements", None, ["name"], [[n] for n in sorted(names)])

    @staticmethod
    def _mst_match(stmt, mst: str) -> bool:
        if stmt.measurement:
            return mst == stmt.measurement
        if getattr(stmt, "measurement_regex", ""):
            return re.search(stmt.measurement_regex, mst) is not None
        return True

    @staticmethod
    def _matching_sids(sh, mst: str, condition) -> set[int]:
        """Series of `mst` in shard `sh` matching the tag predicates of
        `condition`.  Time predicates are ignored (SHOW metadata statements
        filter series, not points); predicates on keys that are not tags of
        the measurement match NOTHING — `WHERE value = 'x'` over series
        metadata is vacuously false, matching the reference's behavior
        (coordinator show-executor tag-filter rewrite)."""
        sids = sh.index.series_ids(mst)
        if condition is not None:
            tag_keys = set(sh.index.tag_keys(mst))
            sc = cond.split(condition, tag_keys, 0)
            if sc.has_row_filter:
                return set()
            if sc.tag_expr is not None:
                sids = sids & cond.eval_tag_expr(sc.tag_expr, sh.index, mst)
        return sids

    def _show_tag_keys(self, stmt, db) -> dict:
        db = stmt.database or db
        per_mst: dict[str, set] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst) or not self._visible(db, mst):
                    continue
                if stmt.condition is not None:
                    for sid in self._matching_sids(sh, mst, stmt.condition):
                        _, tags = sh.index.series_entry(sid)
                        per_mst.setdefault(mst, set()).update(k for k, _ in tags)
                else:
                    per_mst.setdefault(mst, set()).update(sh.index.tag_keys(mst))
        series = [
            _series(m, None, ["tagKey"], [[k] for k in sorted(keys)])
            for m, keys in sorted(per_mst.items())
            if keys
        ]
        return {"series": series} if series else {}

    def _show_tag_values(self, stmt, db) -> dict:
        db = stmt.database or db
        key_rx = re.compile(stmt.key_regex) if stmt.key_regex else None
        per_mst: dict[str, set] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst) or not self._visible(db, mst):
                    continue
                wanted = [
                    k for k in sh.index.tag_keys(mst)
                    if (k in stmt.keys) or (key_rx is not None and key_rx.search(k))
                ]
                if not wanted:
                    continue
                if stmt.condition is None:
                    # no series filter: direct inverted-index lookup, never
                    # an O(series) walk (1M-series measurements)
                    bucket = per_mst.setdefault(mst, set())
                    for k in wanted:
                        for v in sh.index.tag_values(mst, k):
                            bucket.add((k, v))
                    continue
                for sid in self._matching_sids(sh, mst, stmt.condition):
                    _, tags = sh.index.series_entry(sid)
                    for k, v in tags:
                        if k in wanted:
                            per_mst.setdefault(mst, set()).add((k, v))
        series = []
        for mst, pairs in sorted(per_mst.items()):
            uniq = sorted(pairs, reverse=stmt.order_desc)
            if stmt.offset:
                uniq = uniq[stmt.offset:]
            if stmt.limit:
                uniq = uniq[:stmt.limit]
            if uniq:
                series.append(
                    _series(mst, None, ["key", "value"], [list(p) for p in uniq]))
        return {"series": series} if series else {}

    def _show_field_keys(self, stmt, db) -> dict:
        db = stmt.database or db
        per_mst: dict[str, dict] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst) or not self._visible(db, mst):
                    continue
                per_mst.setdefault(mst, {}).update(sh.schema(mst))
        type_names = {
            FieldType.FLOAT: "float",
            FieldType.INT: "integer",
            FieldType.BOOL: "boolean",
            FieldType.STRING: "string",
        }
        series = []
        for mst, sch in sorted(per_mst.items()):
            rows = [[k, type_names[t]] for k, t in sorted(sch.items())]
            series.append(_series(mst, None, ["fieldKey", "fieldType"], rows))
        return {"series": series} if series else {}

    def _show_series(self, stmt, db) -> dict:
        from opengemini_tpu.ingest.line_protocol import series_key

        db = stmt.database or db
        keys: set[str] = set()
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst):
                    continue
                for sid in self._matching_sids(sh, mst, stmt.condition):
                    m, tags = sh.index.series_entry(sid)
                    keys.add(series_key(m, tags))
        if not keys:
            return {}
        return _series_result("", None, ["key"], [[k] for k in sorted(keys)])

    def _show_series_exact_cardinality(self, stmt, db) -> dict:
        """Per-measurement exact distinct-series count (reference:
        ShowSeriesCardinalityStatement with EXACT, executor.go)."""
        from opengemini_tpu.ingest.line_protocol import series_key

        db = stmt.database or db
        per_mst: dict[str, set] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst):
                    continue
                bucket = per_mst.setdefault(mst, set())
                for sid in self._matching_sids(sh, mst, stmt.condition):
                    m, tags = sh.index.series_entry(sid)
                    bucket.add(series_key(m, tags))
        series = [
            _series(m, None, ["count"], [[len(keys)]])
            for m, keys in sorted(per_mst.items())
            if keys
        ]
        return {"series": series} if series else {}

    def _show_rps(self, stmt, db) -> dict:
        db = stmt.database or db
        d = self.engine.databases.get(db)
        if d is None:
            raise QueryError(f"database not found: {db}")
        rows = []
        for rp in d.rps.values():
            rows.append(
                [
                    rp.name,
                    _fmt_duration(rp.duration_ns),
                    _fmt_duration(rp.shard_duration_ns),
                    1,
                    rp.name == d.default_rp,
                ]
            )
        return _series_result(
            "", None,
            ["name", "duration", "shardGroupDuration", "replicaN", "default"],
            rows,
        )


# -- helpers -----------------------------------------------------------------


def _prune_text_sids(sh, mst, sids, match_terms):
    """Intersect candidate series with the persisted text index for every
    conjunctive match() term (reference: logstore token-index pruning).
    Conservative: memtable rows are unindexed so live-memtable series
    always survive; shards without the index (or RemoteShard proxies)
    prune nothing."""
    if not match_terms or not sids:
        return sids
    lookup = getattr(sh, "text_match_sids", None)
    if lookup is None:
        return sids
    mem_sids = sh.mem.sids_for(mst)
    for fld, tok in match_terms:
        got = lookup(mst, fld, tok)
        if got is None:
            return sids  # a pre-sidecar file: cannot prune safely
        sids = sids & (got | mem_sids)
        if not sids:
            break
    return sids


def _series_needs_merged_decode(sh, mst, sid, tmin, tmax):
    """Dedup-risk check shared by the pre-agg and sketch fast paths: a
    series needs the merged read_series view when memtable rows overlap
    the range or its chunks overlap each other (last-write-wins dedup).
    Returns (needs_merge, chunk_sources)."""
    if not getattr(sh, "supports_preagg", False):
        # remote proxies expose no chunk metadata: always take the merged
        # read_series view (returning (False, []) here would silently
        # DROP the remote data from the fast paths)
        return True, None
    mem_rec = sh.mem.record_for(sid)
    if mem_rec is not None and len(mem_rec.slice_time(tmin, tmax)):
        return True, None
    srcs = sh.file_chunks(mst, {sid}, tmin, tmax)
    if any(c.packed for _r, c in srcs):
        # packed chunks hold many series: their pre-agg is chunk-wide, so
        # per-series fast paths must take the merged decode
        return True, None
    metas = sorted((c for _r, c in srcs), key=lambda c: c.tmin)
    for a, b in zip(metas, metas[1:]):
        if b.tmin <= a.tmax:
            return True, None
    return False, srcs


def _add_record_to_batches(rec, seg, aligned, needed_fields, batches, dtype,
                           fmask, sids=None):
    """Shared scan step: one record's columns into the per-field device
    batches (string columns become count-only zero payloads; int-exact
    host batches receive the raw int64 values uncast). `sids` (scalar or
    per-row array) carries series identity for the grid batch's
    constant-stride run detection."""
    rel = rec.times - aligned  # int64 ns; (hi, lo)-split on add()
    for fname in needed_fields:
        col = rec.columns.get(fname)
        if col is None:
            continue
        if isinstance(batches[fname], ragged.IntExactBatch):
            vals = col.values  # int64 end-to-end, no float cast
        elif col.ftype == FieldType.STRING:
            vals = np.zeros(len(rec), dtype=dtype)  # count-only path
        else:
            vals = col.values.astype(dtype)
        m = col.valid
        if fmask is not None:
            m = m & fmask
        batches[fname].add(vals, rel, seg, m, rec.times, sids=sids)


def _merge_multi_source(all_series: list[dict], stmt) -> list[dict]:
    """Union the per-source output series of a multi-source raw SELECT
    into combined series per tagset: name = sorted comma-join of source
    names, columns = union (sorted when the projection used a wildcard),
    rows time-ordered. Rows stay distinct even at equal timestamps —
    each source's row keeps its identity (Constant_Column#0); aggregate
    statements union rows upstream via the subquery rewrite instead
    (reference TestServer_Query_MultiMeasurements)."""
    wildcard = any(
        isinstance(_strip_expr(f.expr), ast.Wildcard) for f in stmt.fields
    )
    groups: dict[tuple, dict] = {}
    order: list[tuple] = []
    for s in all_series:
        key = tuple(sorted((s.get("tags") or {}).items()))
        g = groups.get(key)
        if g is None:
            groups[key] = g = {"names": set(), "columns": ["time"],
                               "rows": [], "tags": s.get("tags")}
            order.append(key)
        g["names"].add(s["name"])
        cols = s["columns"]
        for c in cols[1:]:
            if c not in g["columns"]:
                g["columns"].append(c)
        for row in s["values"]:
            g["rows"].append((row[0], dict(zip(cols[1:], row[1:]))))
    out = []
    for key in order:
        g = groups[key]
        if wildcard:
            g["columns"] = ["time"] + sorted(g["columns"][1:])
        g["rows"].sort(key=lambda r: r[0], reverse=not stmt.ascending)
        merged = g["rows"]
        name = ",".join(sorted(g["names"]))
        values = [
            [t] + [cv.get(c) for c in g["columns"][1:]] for t, cv in merged
        ]
        series = {"name": name, "columns": g["columns"], "values": values}
        if g["tags"]:
            series["tags"] = g["tags"]
        out.append(series)
    return out


def _inner_source_name(stmt, _depth: int = 0) -> str:
    """Influx keeps the innermost measurement name for subquery output
    (CTE references resolve to their body's innermost source; a union
    body names itself after its sorted side names)."""
    if _depth > 16:
        return "subquery"
    if isinstance(stmt, ast.UnionStatement):
        parts: set[str] = set()
        for sel in stmt.selects:
            n = _inner_source_name(sel, _depth + 1)
            if n != "subquery":
                parts.update(n.split(","))
        return ",".join(sorted(parts)) if parts else "subquery"
    # multiple sources name the output after the sorted union of their
    # innermost names (reference: "mst,mst1" in TestServer_Query_
    # MultiMeasurements)
    parts2: set[str] = set()
    for src in stmt.sources:
        if isinstance(src, ast.SubQuery):
            n = _inner_source_name(src.stmt, _depth + 1)
        elif isinstance(src, ast.Measurement) and src.name:
            if stmt.ctes and src.name in stmt.ctes:
                n = _inner_source_name(stmt.ctes[src.name], _depth + 1)
            else:
                n = src.name
        else:
            continue
        if n != "subquery":
            parts2.update(n.split(","))
    return ",".join(sorted(parts2)) if parts2 else "subquery"


def _series(name, tags, columns, values):
    s = {"name": name, "columns": columns, "values": values}
    if tags:
        s["tags"] = tags
    if not name:
        del s["name"]
    return s


def _series_result(name, tags, columns, values) -> dict:
    return {"series": [_series(name, tags, columns, values)]}


def _strip_expr(e):
    while isinstance(e, ast.ParenExpr):
        e = e.expr
    return e


def _collect_calls(fields) -> list[ast.Call]:
    out = []
    for f in fields:
        out.extend(_calls_in(f.expr))
    return out


def _eval_scalar_row(e, per: dict, tags: dict, oi: int):
    """One-row scalar-math evaluation over companion columns (`per` maps
    field -> (values, valid, ftype)). None propagates through every op."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        got = per.get(e.name)
        if got is None or not got[1][oi]:
            return None
        try:
            return float(got[0][oi])
        except (TypeError, ValueError):
            return None
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral,
                      ast.DurationLiteral)):
        return float(e.val)
    if isinstance(e, ast.UnaryExpr):
        v = _eval_scalar_row(e.expr, per, tags, oi)
        if v is None:
            return None
        return -v if e.op == "-" else v
    if isinstance(e, ast.BinaryExpr):
        lv = _eval_scalar_row(e.lhs, per, tags, oi)
        rv = _eval_scalar_row(e.rhs, per, tags, oi)
        if lv is None or rv is None:
            return None
        if e.op == "+":
            return lv + rv
        if e.op == "-":
            return lv - rv
        if e.op == "*":
            return lv * rv
        if e.op == "/":
            return lv / rv if rv else None
        if e.op == "%":
            return lv % rv if rv else None
    return None


def _scalar_refs(e) -> set[str]:
    """Field names referenced by a scalar-math projection expression."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        return {e.name}
    if isinstance(e, ast.BinaryExpr):
        return _scalar_refs(e.lhs) | _scalar_refs(e.rhs)
    if isinstance(e, ast.UnaryExpr):
        return _scalar_refs(e.expr)
    return set()


def _eval_scalar_cols(e, rec):
    """Vectorized scalar-math projection over one record.

    Returns (values f64, valid, touched): `valid` requires EVERY operand
    field present (influx null-propagation — `f1 + f2` is null when either
    side is), `touched` is true where ANY referenced field is present (the
    row still emits with a null value, TestServer_Query_SubqueryMath#0).
    """
    n = len(rec)
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        col = rec.columns.get(e.name)
        if col is None or col.ftype == FieldType.STRING:
            z = np.zeros(n, bool)
            return np.zeros(n), z, z.copy()
        vals = np.where(col.valid, col.values.astype(np.float64), 0.0)
        return vals, col.valid.copy(), col.valid.copy()
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral,
                      ast.DurationLiteral)):
        ones = np.ones(n, bool)
        return np.full(n, float(e.val)), ones, np.zeros(n, bool)
    if isinstance(e, ast.UnaryExpr):
        vals, valid, touched = _eval_scalar_cols(e.expr, rec)
        return (-vals if e.op == "-" else vals), valid, touched
    if isinstance(e, ast.BinaryExpr):
        lv, lok, lt = _eval_scalar_cols(e.lhs, rec)
        rv, rok, rt = _eval_scalar_cols(e.rhs, rec)
        valid = lok & rok
        touched = lt | rt
        with np.errstate(all="ignore"):
            if e.op == "+":
                out = lv + rv
            elif e.op == "-":
                out = lv - rv
            elif e.op == "*":
                out = lv * rv
            elif e.op == "/":
                valid = valid & (rv != 0)  # x/0 is null (influx)
                out = np.divide(lv, np.where(rv != 0, rv, 1.0))
            elif e.op == "%":
                valid = valid & (rv != 0)
                out = np.mod(lv, np.where(rv != 0, rv, 1.0))
            else:
                z = np.zeros(n, bool)
                return np.zeros(n), z, touched
        return out, valid, touched
    z = np.zeros(n, bool)
    return np.zeros(n), z, z.copy()


def _calls_in(e) -> list[ast.Call]:
    e = _strip_expr(e)
    if isinstance(e, ast.Call):
        return [e]
    if isinstance(e, ast.BinaryExpr):
        return _calls_in(e.lhs) + _calls_in(e.rhs)
    if isinstance(e, ast.UnaryExpr):
        return _calls_in(e.expr)
    return []


# wildcard-in-call expansion: these functions expand `f(*)` over numeric
# fields only (math is meaningless on strings/bools); everything else
# expands over every field (reference: influxql RewriteFields)
_NUMERIC_ONLY_WILDCARD = {
    "difference", "non_negative_difference", "derivative",
    "non_negative_derivative", "moving_average", "cumulative_sum", "sum",
    "mean", "median", "stddev", "spread", "percentile", "integral",
    "max", "min", "top", "bottom", "sample",
    "rate", "irate", "regr_slope",
}


def _call_wildcard_inner(e):
    """f(*) -> (f, None); f(g(*), ...) -> (f, g). None when no wildcard."""
    if not (isinstance(e, ast.Call) and e.args):
        return None
    a0 = _strip_expr(e.args[0])
    if isinstance(a0, ast.Wildcard):
        return e, None
    if isinstance(a0, ast.Call) and a0.args and isinstance(
            _strip_expr(a0.args[0]), ast.Wildcard):
        return e, a0
    return None


def _has_call_wildcard(stmt) -> bool:
    return any(
        _call_wildcard_inner(_strip_expr(f.expr)) is not None
        for f in stmt.fields
    )


def _expand_call_wildcards(stmt, schema):
    """Rewrite `SELECT f(*) ...` into one call per matching field, each
    aliased `f_<field>` (reference: influxql.RewriteFields wildcard
    expansion)."""
    import copy

    new_fields = []
    for f in stmt.fields:
        e = _strip_expr(f.expr)
        hit = _call_wildcard_inner(e)
        if hit is None:
            new_fields.append(f)
            continue
        outer, inner = hit
        base = _default_field_name(outer)
        type_call = (inner or outer).name
        for fld in sorted(schema):
            ft = schema[fld]
            if type_call in ("max", "min"):
                if ft == FieldType.STRING:
                    continue  # max/min(*): numeric + bool
            elif type_call in _NUMERIC_ONLY_WILDCARD and ft not in (
                    FieldType.FLOAT, FieldType.INT):
                continue
            if inner is None:
                call = ast.Call(
                    outer.name, (ast.VarRef(fld),) + tuple(outer.args[1:]))
            else:
                new_inner = ast.Call(
                    inner.name, (ast.VarRef(fld),) + tuple(inner.args[1:]))
                call = ast.Call(
                    outer.name, (new_inner,) + tuple(outer.args[1:]))
            new_fields.append(ast.Field(call, alias=f"{base}_{fld}"))
    out = copy.copy(stmt)
    out.fields = new_fields
    return out


def _needs_string_host_path(stmt, schema_fn) -> bool:
    """schema_fn is called lazily — the shard-schema sweep only runs when a
    call could actually involve a string field."""
    candidates = []
    for call in _collect_calls(stmt.fields):
        if not call.args or call.name not in _STRING_OK_HOST or call.name == "count":
            continue
        a = _strip_expr(call.args[0])
        if isinstance(a, ast.VarRef):
            candidates.append(a.name)
    if not candidates:
        return False
    schema = schema_fn()
    return any(schema.get(n) == FieldType.STRING for n in candidates)


_AUX_SELECTORS = {"first", "last", "max", "min", "top", "bottom", "percentile"}


def _selector_aux_plan(stmt: ast.SelectStatement):
    """Detect `SELECT <selector>(f, ...), aux...`: exactly one call, a
    selector, with at least one auxiliary (non-call, non-`time`) column.
    Returns (call, aux_field_names) or None."""
    calls = _collect_calls(stmt.fields)
    if len(calls) != 1 or calls[0].name not in _AUX_SELECTORS:
        return None
    call = calls[0]
    if not call.args or not isinstance(_strip_expr(call.args[0]), ast.VarRef):
        return None
    aux_names: list[str] = []
    has_aux = False
    for f in stmt.fields:
        e = _strip_expr(f.expr)
        if isinstance(e, ast.Call):
            continue
        if isinstance(e, ast.VarRef) and e.name.lower() == "time":
            continue
        refs = _collect_varrefs(e)
        if refs is None:
            return None  # something we cannot evaluate per-row
        aux_names.extend(refs)
        has_aux = True
    if not has_aux:
        return None
    return call, sorted(set(aux_names))


def _collect_varrefs(e) -> list[str] | None:
    """Field/tag names referenced by a per-row arithmetic expr, or None
    if the expr contains anything other than refs/literals/arithmetic."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        return [e.name]
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral)):
        return []
    if isinstance(e, ast.UnaryExpr):
        return _collect_varrefs(e.expr)
    if isinstance(e, ast.BinaryExpr):
        l, r = _collect_varrefs(e.lhs), _collect_varrefs(e.rhs)
        if l is None or r is None:
            return None
        return l + r
    return None


def _selector_pick(sel_name: str, tw, vw, n_rows: int, pctl) -> list[int]:
    """Row indices (into the window slice) a selector picks; output order
    is time-ascending for multi-row selectors."""
    if sel_name == "first":
        return [0]
    if sel_name == "last":
        return [len(vw) - 1]
    if sel_name == "max":
        return [int(np.argmax(vw))]
    if sel_name == "min":
        return [int(np.argmin(vw))]
    if sel_name == "percentile":
        order = np.argsort(vw, kind="stable")
        i = int(math.floor(len(vw) * pctl / 100.0 + 0.5)) - 1
        if i < 0 or i >= len(vw):
            return []
        return [int(order[i])]
    # top/bottom: n best by value (ties -> earliest), output time-ascending
    keys = -vw if sel_name == "top" else vw
    order = np.lexsort((np.arange(len(vw)), keys))[:n_rows]
    return sorted(int(i) for i in order)


def _render_cell(v, ftype, call_name: str):
    if ftype == FieldType.STRING:
        return None if v is None else str(v)
    if ftype == FieldType.INT:
        return int(v)
    if ftype == FieldType.BOOL:
        return bool(round(float(v)))
    fv = float(v)
    if math.isnan(fv) or math.isinf(fv):
        return None
    return fv


def _eval_aux_expr(e, ri: int, aux_arr, tag_arr, schema):
    """Evaluate one auxiliary column at selected row `ri`."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        if e.name in aux_arr:
            vals, valid = aux_arr[e.name]
            if not valid[ri]:
                return None
            return _render_cell(vals[ri], schema.get(e.name), "aux")
        if e.name in tag_arr:
            return tag_arr[e.name][ri]
        return None
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral)):
        return e.val
    if isinstance(e, ast.UnaryExpr) and e.op == "-":
        v = _eval_aux_expr(e.expr, ri, aux_arr, tag_arr, schema)
        return None if v is None else -v
    if isinstance(e, ast.BinaryExpr):
        lv = _eval_aux_expr(e.lhs, ri, aux_arr, tag_arr, schema)
        rv = _eval_aux_expr(e.rhs, ri, aux_arr, tag_arr, schema)
        if lv is None or rv is None or isinstance(lv, str) or isinstance(rv, str):
            return None
        try:
            if e.op == "+":
                return lv + rv
            if e.op == "-":
                return lv - rv
            if e.op == "*":
                return lv * rv
            if e.op == "/":
                return lv / rv if rv != 0 else None
            if e.op == "%":
                return lv % rv if rv != 0 else None
        except TypeError:
            return None
    raise QueryError(f"unsupported auxiliary expression: {e}")


def _has_in_subquery(e) -> bool:
    if isinstance(e, ast.InSubquery):
        return True
    if isinstance(e, ast.BinaryExpr):
        return _has_in_subquery(e.lhs) or _has_in_subquery(e.rhs)
    if isinstance(e, (ast.ParenExpr, ast.UnaryExpr)):
        return _has_in_subquery(e.expr)
    return False


def _classify_select(stmt: ast.SelectStatement) -> str:
    """'raw' | 'device' | 'host' — the single source of truth for which
    execution path a SELECT takes (used by execution AND EXPLAIN)."""
    calls = _collect_calls(stmt.fields)
    if not calls:
        return "raw"
    if all(_is_device_call(c) for c in calls):
        return "device"
    return "host"


def _is_device_call(call: ast.Call) -> bool:
    if call.name == "count" and call.args:
        inner = _strip_expr(call.args[0])
        if isinstance(inner, ast.Call) and inner.name == "distinct":
            return True
    if call.name in aggmod.REGISTRY:
        # device aggs take a bare field ref (string fields route to count
        # validation inside _select_agg)
        return bool(call.args) and isinstance(_strip_expr(call.args[0]), ast.VarRef)
    return False


def _call_param_value(arg) -> float | int:
    a = _strip_expr(arg)
    if isinstance(a, ast.UnaryExpr) and a.op == "-":
        return -_call_param_value(a.expr)
    if isinstance(a, ast.IntegerLiteral):
        return a.val
    if isinstance(a, ast.NumberLiteral):
        return a.val
    if isinstance(a, ast.DurationLiteral):
        return a.val_ns
    raise QueryError("function parameter must be a number or duration")


def _call_param_any(arg):
    a = _strip_expr(arg)
    if isinstance(a, ast.StringLiteral):
        return a.val
    return _call_param_value(arg)


def _resolve_host_call(call: ast.Call, group_time):
    """-> (kind, call_name, field, params, inner) where kind is
    'agg' | 'transform_raw' | 'transform_agg' | 'multi' | 'sliding'."""
    name = call.name
    if name == "sliding_window":
        # sliding_window(agg(f), N): agg over N consecutive GROUP BY time
        # windows, emitted at each window start (reference:
        # TestServer_Query_Sliding_Window_Aggregate)
        if len(call.args) != 2:
            raise QueryError("sliding_window() takes (aggregate, N)")
        if group_time is None:
            raise QueryError("sliding_window() requires GROUP BY time(...)")
        inner_e = _strip_expr(call.args[0])
        if not isinstance(inner_e, ast.Call):
            raise QueryError("sliding_window() argument must be an aggregate")
        n = int(_call_param_value(call.args[1]))
        if n < 1:
            raise QueryError("sliding_window() N must be >= 1")
        ikind, iname, ifield, iparams, _ = _resolve_host_call(inner_e, group_time)
        if ikind != "agg":
            raise QueryError("sliding_window() argument must be an aggregate")
        return "sliding", name, ifield, (n,), (iname, iparams)
    if name in fnmod.TRANSFORMS:
        if not call.args:
            raise QueryError(f"{name}() requires an argument")
        inner_e = _strip_expr(call.args[0])
        if name == "difference":
            # difference(f[, 'front'|'behind'|'absolute'])
            params = tuple(_call_param_any(a) for a in call.args[1:])
            if params and params[0] not in ("front", "behind", "absolute"):
                raise QueryError(
                    "difference() mode must be 'front', 'behind' or 'absolute'")
        else:
            params = tuple(_call_param_value(a) for a in call.args[1:])
        _check_host_arity(name, params)
        if isinstance(inner_e, ast.Call):
            if group_time is None:
                raise QueryError(
                    f"{name}() over an aggregate requires GROUP BY time(...)"
                )
            ikind, iname, ifield, iparams, _ = _resolve_host_call(inner_e, group_time)
            if ikind != "agg":
                raise QueryError(f"{name}() argument must be a field or aggregate")
            return "transform_agg", name, ifield, params, (iname, iparams)
        if isinstance(inner_e, ast.VarRef):
            if name.startswith("holt_winters"):
                raise QueryError(
                    "holt_winters() requires an aggregate argument with "
                    "GROUP BY time(...)"
                )
            if group_time is not None:
                raise QueryError(
                    f"{name}() over raw points cannot use GROUP BY time(...) — "
                    "wrap the field in an aggregate"
                )
            return "transform_raw", name, inner_e.name, params, None
        raise QueryError(f"{name}() argument must be a field or aggregate")
    if name in fnmod.MULTI_ROW:
        if not call.args:
            raise QueryError(f"{name}() requires a field argument")
        fld = _strip_expr(call.args[0])
        if not isinstance(fld, ast.VarRef):
            raise QueryError(f"{name}() argument must be a field")
        if name == "detect":
            # detect(field, 'algorithm'[, threshold]): string only in slot 0
            params = []
            for i, a in enumerate(call.args[1:]):
                params.append(_call_param_any(a) if i == 0 else _call_param_value(a))
            params = tuple(params)
            if params and not isinstance(params[0], str):
                raise QueryError("detect() algorithm must be a quoted string")
        else:
            params = tuple(_call_param_value(a) for a in call.args[1:])
        _check_host_arity(name, params)
        return "multi", name, fld.name, params, None
    if name == "count" and call.args and isinstance(_strip_expr(call.args[0]), ast.Call):
        inner = _strip_expr(call.args[0])
        if inner.name == "distinct":
            fld = _strip_expr(inner.args[0])
            return "agg", "count_distinct", fld.name, (), None
    if name in fnmod.HOST_AGGS:
        if not call.args or not isinstance(_strip_expr(call.args[0]), ast.VarRef):
            raise QueryError(f"{name}() requires a field argument")
        params = tuple(_call_param_value(a) for a in call.args[1:])
        _check_host_arity(name, params)
        return "agg", name, _strip_expr(call.args[0]).name, params, None
    raise QueryError(f"unsupported function: {name}")


# (min required params, max allowed params) per host call with parameters
_HOST_ARITY = {
    "percentile": (1, 1),
    "moving_average": (1, 1),
    "top": (1, 1),
    "bottom": (1, 1),
    "sample": (1, 1),
    "distinct": (0, 0),
    "detect": (0, 2),
    "holt_winters": (1, 2),
    "holt_winters_with_fit": (1, 2),
    "difference": (0, 1),
    "non_negative_difference": (0, 0),
    "cumulative_sum": (0, 0),
}


def _check_host_arity(name: str, params: tuple) -> None:
    lo, hi = _HOST_ARITY.get(name, (0, 1))
    if not (lo <= len(params) <= hi):
        raise QueryError(f"{name}() takes {lo + 1} to {hi + 1} arguments")
    if name == "moving_average" and params and int(params[0]) < 1:
        raise QueryError("moving_average() window must be >= 1")
    if name.startswith("holt_winters") and params:
        n = int(params[0])
        if not (1 <= n <= 10_000):
            raise QueryError("holt_winters() N must be between 1 and 10000")
        if len(params) > 1 and not (0 <= int(params[1]) <= 10_000):
            raise QueryError("holt_winters() seasonal period must be 0..10000")


def _resolve_call(call: ast.Call):
    """-> (AggSpec, params, field_name)."""
    name = call.name
    args = call.args
    if name == "count" and args and isinstance(_strip_expr(args[0]), ast.Call):
        inner = _strip_expr(args[0])
        if inner.name == "distinct":
            spec = aggmod.get("count_distinct")
            fld = _call_field(inner)
            return spec, (), fld
    if name == "percentile":
        if len(args) != 2:
            raise QueryError("percentile() takes (field, N)")
        q = _strip_expr(args[1])
        if isinstance(q, (ast.IntegerLiteral, ast.NumberLiteral)):
            qv = float(q.val)
        else:
            raise QueryError("percentile() N must be a number")
        return aggmod.get("percentile"), (qv,), _call_field(call)
    spec = aggmod.get(name)  # KeyError -> surfaced as query error
    return spec, (), _call_field(call)


def _call_field(call: ast.Call) -> str:
    if not call.args:
        raise QueryError(f"{call.name}() requires a field argument")
    a = _strip_expr(call.args[0])
    if isinstance(a, ast.VarRef):
        return a.name
    if isinstance(a, ast.Wildcard):
        raise QueryError(f"{call.name}(*) is not supported yet")
    raise QueryError(f"{call.name}() argument must be a field")


def _default_field_name(e) -> str:
    e = _strip_expr(e)
    if isinstance(e, ast.Call):
        if e.name == "count" and e.args:
            inner = _strip_expr(e.args[0])
            if isinstance(inner, ast.Call) and inner.name == "distinct":
                return "count"
        return e.name
    if isinstance(e, ast.VarRef):
        return e.name
    if isinstance(e, ast.BinaryExpr):
        calls = _calls_in(e)
        if calls:
            return "_".join(c.name for c in calls)
        refs = sorted({r for r in cond.field_filter_refs(e)})
        return "_".join(refs) if refs else "expr"
    return "expr"


def _eval_output_expr(expr, agg_results, seg, schema):
    """Evaluate one output column at segment `seg`. Returns (value, present)."""
    expr = _strip_expr(expr)
    if isinstance(expr, ast.Call):
        entry = agg_results.get(id(expr))
        if entry is None:
            raise QueryError(f"unplanned call {expr.name}")
        out, sel, counts, spec, fname, _times = entry
        if counts[seg] == 0:
            return None, False
        # single-sample stddev renders 0 (reference NewStdDevReduce,
        # engine/executor/agg_func.go, returns 0 with isNil=false for n==1)
        v = out[seg]
        ftype = schema.get(fname)
        if spec.int_output:
            return int(v), True
        if ftype == FieldType.INT and spec.name in ("sum", "min", "max", "first", "last", "spread"):
            # int64-exact path yields integer arrays: never round-trip
            # through float (2^53 cliff)
            if isinstance(v, np.integer):
                return int(v), True
            return int(round(float(v))), True
        if ftype == FieldType.BOOL and spec.name in ("first", "last", "min", "max"):
            return bool(round(float(v))), True
        fv = float(v)
        if math.isnan(fv) or math.isinf(fv):
            return None, True
        return fv, True
    if isinstance(expr, (ast.NumberLiteral, ast.IntegerLiteral)):
        return expr.val, False
    if isinstance(expr, ast.UnaryExpr) and expr.op == "-":
        v, p = _eval_output_expr(expr.expr, agg_results, seg, schema)
        return (None if v is None else -v), p
    if isinstance(expr, ast.BinaryExpr):
        lv, lp = _eval_output_expr(expr.lhs, agg_results, seg, schema)
        rv, rp = _eval_output_expr(expr.rhs, agg_results, seg, schema)
        present = lp or rp
        if lv is None or rv is None:
            return None, present
        try:
            if expr.op == "+":
                return lv + rv, present
            if expr.op == "-":
                return lv - rv, present
            if expr.op == "*":
                return lv * rv, present
            if expr.op == "/":
                return (lv / rv if rv != 0 else None), present
            if expr.op == "%":
                return (lv % rv if rv != 0 else None), present
        except TypeError:
            return None, present
    raise QueryError(f"unsupported output expression: {expr}")


def _apply_fill(rows, stmt, columns, count_idx: tuple = ()):
    """rows: [(t, vals, any_present)] per window, ascending. Influx fill
    semantics (reference: engine/executor fill_transform.go). count_idx:
    value indices holding bare count()/count(distinct) results — under
    the default null fill those render 0 for empty windows
    (TestServer_Query_Fill#6)."""
    fill = stmt.fill_option
    if not stmt.group_by_time:
        return [(t, v, p) for t, v, p in rows if p]
    if fill == "none":
        return [(t, v, p) for t, v, p in rows if p]
    if fill == "null" and count_idx:
        out = []
        for t, vals, p in rows:
            vals = [0 if (i in count_idx and v is None) else v
                    for i, v in enumerate(vals)]
            out.append((t, vals, p))
        rows = out
    if fill == "number":
        out = []
        for t, vals, p in rows:
            vals = [stmt.fill_value if v is None else v for v in vals]
            out.append((t, vals, p))
        return out
    if fill == "previous":
        prev = [None] * (len(columns) - 1)
        out = []
        for t, vals, p in rows:
            vals = [prev[i] if v is None else v for i, v in enumerate(vals)]
            prev = vals
            out.append((t, vals, p))
        return out
    if fill == "linear":
        ncols = len(columns) - 1
        arr = [[v for v in vals] for _t, vals, _p in rows]
        for ci in range(ncols):
            col = [r[ci] for r in arr]
            col = _linear_fill(col)
            for ri, v in enumerate(col):
                arr[ri][ci] = v
        return [(rows[i][0], arr[i], rows[i][2]) for i in range(len(rows))]
    return rows  # "null"


def _linear_fill(col):
    n = len(col)
    known = [i for i, v in enumerate(col) if v is not None]
    if len(known) < 2:
        return col
    out = list(col)
    for a, b in zip(known, known[1:]):
        if b - a > 1:
            va, vb = col[a], col[b]
            for i in range(a + 1, b):
                out[i] = va + (vb - va) * (i - a) / (b - a)
    return out


def _pyval(v, ftype):
    if ftype == FieldType.FLOAT:
        fv = float(v)
        # non-finite floats marshal as JSON null (influx semantics; a bare
        # NaN/Infinity literal is not valid strict JSON and breaks clients)
        return fv if math.isfinite(fv) else None
    if ftype == FieldType.INT:
        return int(v)
    if ftype == FieldType.BOOL:
        return bool(v)
    return v if isinstance(v, str) else str(v)


def _data_time_range(shards, mst):
    dmin = dmax = None
    for sh in shards:
        for r, c in sh.file_chunks(mst):
            dmin = c.tmin if dmin is None else min(dmin, c.tmin)
            dmax = c.tmax if dmax is None else max(dmax, c.tmax)
        if sh.mem.min_time is not None:
            dmin = sh.mem.min_time if dmin is None else min(dmin, sh.mem.min_time)
            dmax = sh.mem.max_time if dmax is None else max(dmax, sh.mem.max_time)
    return dmin, dmax


def _fmt_duration(ns: int) -> str:
    if ns == 0:
        return "0s"
    h, rem = divmod(ns // NS, 3600)
    m, s = divmod(rem, 60)
    return f"{h}h{m}m{s}s"
