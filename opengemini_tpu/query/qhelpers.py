"""Shared helpers for the query executor family: AST utilities, host
scalar evaluation, call resolution, fill/render primitives, and the
QueryError type. Split out of query/executor.py (VERDICT r3 #7) so
the executor modules stay review-able; semantics unchanged.
"""

from __future__ import annotations

import math
import os
import re
import threading as _threading
import time as _time

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.ops import aggregates as aggmod
from opengemini_tpu.parallel import cluster as pcluster
from opengemini_tpu.ops import window as winmod
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query import functions as fnmod
from opengemini_tpu.record import FieldType, FieldTypeConflict
from opengemini_tpu.sql import ast
from opengemini_tpu.meta.users import AuthError as _AuthError
from opengemini_tpu.storage.engine import WriteError
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER, QueryKilled
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.sql.parser import parse

NS = 1_000_000_000
MAX_SELECT_BUCKETS = 1_000_000  # influx max-select-buckets guard


class QueryError(Exception):
    pass


# host calls safe on string columns (python-object values end-to-end)
_STRING_OK_HOST = {"count", "count_distinct", "mode", "first", "last",
                   "distinct", "elapsed", "absent",
                   "median"}  # median(string) renders a null row (influx)


def _check_host_field_type(call_name: str, field: str, schema: dict) -> None:
    if schema.get(field) == FieldType.STRING and call_name not in _STRING_OK_HOST:
        raise QueryError(f"{call_name}() is not supported on string field {field!r}")


def _prune_text_sids(sh, mst, sids, match_terms):
    """Intersect candidate series with the persisted text index for every
    conjunctive match() term (reference: logstore token-index pruning).
    Conservative: memtable rows are unindexed so live-memtable series
    always survive; shards without the index (or RemoteShard proxies)
    prune nothing."""
    if not match_terms or len(sids) == 0:
        return sids
    lookup = getattr(sh, "text_match_sids", None)
    if lookup is None:
        return sids
    # frozen flush snapshots are unindexed like the live memtable: their
    # series must survive pruning too (shard.mem_sids_for spans both)
    mem_sids = sh.mem_sids_for(mst)
    as_arr = isinstance(sids, np.ndarray)
    for fld, tok in match_terms:
        got = lookup(mst, fld, tok)
        if got is None:
            return sids  # a pre-sidecar file: cannot prune safely
        keep = got | mem_sids
        if as_arr:
            # sorted-array candidates (the columnar label path): a
            # membership mask keeps the order, no set round-trip
            mask = np.fromiter((s in keep for s in sids.tolist()),
                               np.bool_, len(sids))
            sids = sids[mask]
        else:
            sids = sids & keep
        if len(sids) == 0:
            break
    return sids



def _shard_mem_overlaps(sh, sid, tmin, tmax) -> bool:
    """Per-series in-memory overlap probe: real shards check frozen
    flush snapshots + live memtable part-by-part (no merge, no lock —
    this runs once per series on the pre-agg/sketch fast paths);
    remote/meta proxies keep their plain `mem.record_for` stand-in."""
    f = getattr(sh, "mem_overlaps_range", None)
    if f is not None:
        return f(sid, tmin, tmax)
    rec = sh.mem.record_for(sid)
    return rec is not None and len(rec.slice_time(tmin, tmax)) > 0


def _shard_mem_time_range(sh):
    """(min, max) of in-memory rows incl. frozen flush snapshots."""
    f = getattr(sh, "mem_time_range", None)
    return f() if f is not None else (sh.mem.min_time, sh.mem.max_time)


def _series_needs_merged_decode(sh, mst, sid, tmin, tmax):
    """Dedup-risk check shared by the pre-agg and sketch fast paths: a
    series needs the merged read_series view when memtable rows overlap
    the range or its chunks overlap each other (last-write-wins dedup).
    Returns (needs_merge, chunk_sources)."""
    if not getattr(sh, "supports_preagg", False):
        # remote proxies expose no chunk metadata: always take the merged
        # read_series view (returning (False, []) here would silently
        # DROP the remote data from the fast paths)
        return True, None
    if _shard_mem_overlaps(sh, sid, tmin, tmax):
        return True, None
    srcs = sh.file_chunks(mst, {sid}, tmin, tmax)
    if any(c.packed for _r, c in srcs):
        # packed chunks hold many series: their pre-agg is chunk-wide, so
        # per-series fast paths must take the merged decode
        return True, None
    metas = sorted((c for _r, c in srcs), key=lambda c: c.tmin)
    for a, b in zip(metas, metas[1:]):
        if b.tmin <= a.tmax:
            return True, None
    return False, srcs



def _add_record_to_batches(rec, seg, aligned, needed_fields, batches, dtype,
                           fmask, sids=None):
    """Shared scan step: one record's columns into the per-field device
    batches (string columns become count-only zero payloads; int-exact
    host batches receive the raw int64 values uncast). `sids` (scalar or
    per-row array) carries series identity for the grid batch's
    constant-stride run detection."""
    rel = rec.times - aligned  # int64 ns; (hi, lo)-split on add()
    for fname in needed_fields:
        col = rec.columns.get(fname)
        if col is None:
            continue
        batch = batches[fname]
        m = col.valid
        if fmask is not None:
            m = m & fmask
        if (getattr(col, "blocks", None) is not None
                and hasattr(batch, "add_encoded")):
            # record.EncodedColumn into a device-decode-capable batch:
            # keep the raw block payloads attached — the grid freeze can
            # ship them to the accelerator and decode fused with the
            # window reduce (ops/device_decode.py).  A column that is
            # ALREADY decoded (colcache host-tier hit, or a row filter
            # touched it) still rides this path: the offload planner
            # (query/offload.py) decides host-vs-device per repeat, and
            # host consumers read the memoized values through
            # _EncodedVals.__array__ — bit-identical either way.
            batch.add_encoded(col, rel, seg, m, rec.times, sids=sids)
            continue
        if isinstance(batch, ragged.IntExactBatch):
            vals = col.values  # int64 end-to-end, no float cast
        elif col.ftype == FieldType.STRING:
            vals = np.zeros(len(rec), dtype=dtype)  # count-only path
        else:
            vals = col.values.astype(dtype)
        batch.add(vals, rel, seg, m, rec.times, sids=sids)



def _merge_multi_source(all_series: list[dict], stmt) -> list[dict]:
    """Union the per-source output series of a multi-source raw SELECT
    into combined series per tagset: name = sorted comma-join of source
    names, columns = union (sorted when the projection used a wildcard),
    rows time-ordered. Rows stay distinct even at equal timestamps —
    each source's row keeps its identity (Constant_Column#0); aggregate
    statements union rows upstream via the subquery rewrite instead
    (reference TestServer_Query_MultiMeasurements)."""
    wildcard = any(
        isinstance(_strip_expr(f.expr), ast.Wildcard) for f in stmt.fields
    )
    groups: dict[tuple, dict] = {}
    order: list[tuple] = []
    for s in all_series:
        key = tuple(sorted((s.get("tags") or {}).items()))
        g = groups.get(key)
        if g is None:
            groups[key] = g = {"names": set(), "columns": ["time"],
                               "rows": [], "tags": s.get("tags")}
            order.append(key)
        g["names"].add(s["name"])
        cols = s["columns"]
        for c in cols[1:]:
            if c not in g["columns"]:
                g["columns"].append(c)
        for row in s["values"]:
            g["rows"].append((row[0], dict(zip(cols[1:], row[1:]))))
    out = []
    for key in order:
        g = groups[key]
        if wildcard:
            g["columns"] = ["time"] + sorted(g["columns"][1:])
        g["rows"].sort(key=lambda r: r[0], reverse=not stmt.ascending)
        merged = g["rows"]
        name = ",".join(sorted(g["names"]))
        values = [
            [t] + [cv.get(c) for c in g["columns"][1:]] for t, cv in merged
        ]
        series = {"name": name, "columns": g["columns"], "values": values}
        if g["tags"]:
            series["tags"] = g["tags"]
        out.append(series)
    return out



def _inner_source_name(stmt, _depth: int = 0) -> str:
    """Influx keeps the innermost measurement name for subquery output
    (CTE references resolve to their body's innermost source; a union
    body names itself after its sorted side names)."""
    if _depth > 16:
        return "subquery"
    if isinstance(stmt, ast.UnionStatement):
        parts: set[str] = set()
        for sel in stmt.selects:
            n = _inner_source_name(sel, _depth + 1)
            if n != "subquery":
                parts.update(n.split(","))
        return ",".join(sorted(parts)) if parts else "subquery"
    # multiple sources name the output after the sorted union of their
    # innermost names (reference: "mst,mst1" in TestServer_Query_
    # MultiMeasurements)
    parts2: set[str] = set()
    for src in stmt.sources:
        if isinstance(src, ast.SubQuery):
            n = _inner_source_name(src.stmt, _depth + 1)
        elif isinstance(src, ast.Measurement) and src.name:
            if stmt.ctes and src.name in stmt.ctes:
                n = _inner_source_name(stmt.ctes[src.name], _depth + 1)
            else:
                n = src.name
        else:
            continue
        if n != "subquery":
            parts2.update(n.split(","))
    return ",".join(sorted(parts2)) if parts2 else "subquery"



def _series(name, tags, columns, values):
    s = {"name": name, "columns": columns, "values": values}
    if tags:
        s["tags"] = tags
    if not name:
        del s["name"]
    return s



def _series_result(name, tags, columns, values) -> dict:
    return {"series": [_series(name, tags, columns, values)]}



def _strip_expr(e):
    while isinstance(e, ast.ParenExpr):
        e = e.expr
    return e



def _collect_calls(fields) -> list[ast.Call]:
    out = []
    for f in fields:
        out.extend(_calls_in(f.expr))
    return out



def _eval_scalar_row(e, per: dict, tags: dict, oi: int):
    """One-row scalar-math evaluation over companion columns (`per` maps
    field -> (values, valid, ftype)). None propagates through every op."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        got = per.get(e.name)
        if got is None or not got[1][oi]:
            return None
        try:
            return float(got[0][oi])
        except (TypeError, ValueError):
            return None
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral,
                      ast.DurationLiteral)):
        return float(e.val)
    if isinstance(e, ast.UnaryExpr):
        v = _eval_scalar_row(e.expr, per, tags, oi)
        if v is None:
            return None
        return -v if e.op == "-" else v
    if isinstance(e, ast.BinaryExpr):
        lv = _eval_scalar_row(e.lhs, per, tags, oi)
        rv = _eval_scalar_row(e.rhs, per, tags, oi)
        if lv is None or rv is None:
            return None
        if e.op == "+":
            return lv + rv
        if e.op == "-":
            return lv - rv
        if e.op == "*":
            return lv * rv
        if e.op == "/":
            return lv / rv if rv else None
        if e.op == "%":
            return lv % rv if rv else None
    return None



def _scalar_refs(e) -> set[str]:
    """Field names referenced by a scalar-math projection expression."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        return {e.name}
    if isinstance(e, ast.BinaryExpr):
        return _scalar_refs(e.lhs) | _scalar_refs(e.rhs)
    if isinstance(e, ast.UnaryExpr):
        return _scalar_refs(e.expr)
    return set()



def _eval_scalar_cols(e, rec):
    """Vectorized scalar-math projection over one record.

    Returns (values f64, valid, touched): `valid` requires EVERY operand
    field present (influx null-propagation — `f1 + f2` is null when either
    side is), `touched` is true where ANY referenced field is present (the
    row still emits with a null value, TestServer_Query_SubqueryMath#0).
    """
    n = len(rec)
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        col = rec.columns.get(e.name)
        if col is None or col.ftype == FieldType.STRING:
            z = np.zeros(n, bool)
            return np.zeros(n), z, z.copy()
        vals = np.where(col.valid, col.values.astype(np.float64), 0.0)
        return vals, col.valid.copy(), col.valid.copy()
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral,
                      ast.DurationLiteral)):
        ones = np.ones(n, bool)
        return np.full(n, float(e.val)), ones, np.zeros(n, bool)
    if isinstance(e, ast.UnaryExpr):
        vals, valid, touched = _eval_scalar_cols(e.expr, rec)
        return (-vals if e.op == "-" else vals), valid, touched
    if isinstance(e, ast.BinaryExpr):
        lv, lok, lt = _eval_scalar_cols(e.lhs, rec)
        rv, rok, rt = _eval_scalar_cols(e.rhs, rec)
        valid = lok & rok
        touched = lt | rt
        with np.errstate(all="ignore"):
            if e.op == "+":
                out = lv + rv
            elif e.op == "-":
                out = lv - rv
            elif e.op == "*":
                out = lv * rv
            elif e.op == "/":
                valid = valid & (rv != 0)  # x/0 is null (influx)
                out = np.divide(lv, np.where(rv != 0, rv, 1.0))
            elif e.op == "%":
                valid = valid & (rv != 0)
                out = np.mod(lv, np.where(rv != 0, rv, 1.0))
            else:
                z = np.zeros(n, bool)
                return np.zeros(n), z, touched
        return out, valid, touched
    z = np.zeros(n, bool)
    return np.zeros(n), z, z.copy()



def _calls_in(e) -> list[ast.Call]:
    e = _strip_expr(e)
    if isinstance(e, ast.Call):
        return [e]
    if isinstance(e, ast.BinaryExpr):
        return _calls_in(e.lhs) + _calls_in(e.rhs)
    if isinstance(e, ast.UnaryExpr):
        return _calls_in(e.expr)
    return []


# wildcard-in-call expansion: these functions expand `f(*)` over numeric
# fields only (math is meaningless on strings/bools); everything else
# expands over every field (reference: influxql RewriteFields)
_NUMERIC_ONLY_WILDCARD = {
    "difference", "non_negative_difference", "derivative",
    "non_negative_derivative", "moving_average", "cumulative_sum", "sum",
    "mean", "median", "stddev", "spread", "percentile",
    "percentile_ogsketch", "integral",
    "max", "min", "top", "bottom", "sample",
    "rate", "irate", "regr_slope",
}



def _call_wildcard_inner(e):
    """f(*) -> (f, None); f(g(*), ...) -> (f, g). None when no wildcard."""
    if not (isinstance(e, ast.Call) and e.args):
        return None
    a0 = _strip_expr(e.args[0])
    if isinstance(a0, ast.Wildcard):
        return e, None
    if isinstance(a0, ast.Call) and a0.args and isinstance(
            _strip_expr(a0.args[0]), ast.Wildcard):
        return e, a0
    return None



def _has_call_wildcard(stmt) -> bool:
    return any(
        _call_wildcard_inner(_strip_expr(f.expr)) is not None
        for f in stmt.fields
    )



def _expand_call_wildcards(stmt, schema):
    """Rewrite `SELECT f(*) ...` into one call per matching field, each
    aliased `f_<field>` (reference: influxql.RewriteFields wildcard
    expansion)."""
    import copy

    new_fields = []
    for f in stmt.fields:
        e = _strip_expr(f.expr)
        hit = _call_wildcard_inner(e)
        if hit is None:
            new_fields.append(f)
            continue
        outer, inner = hit
        base = _default_field_name(outer)
        type_call = (inner or outer).name
        for fld in sorted(schema):
            ft = schema[fld]
            if type_call in ("max", "min"):
                if ft == FieldType.STRING:
                    continue  # max/min(*): numeric + bool
            elif type_call in _NUMERIC_ONLY_WILDCARD and ft not in (
                    FieldType.FLOAT, FieldType.INT):
                continue
            if inner is None:
                call = ast.Call(
                    outer.name, (ast.VarRef(fld),) + tuple(outer.args[1:]))
            else:
                new_inner = ast.Call(
                    inner.name, (ast.VarRef(fld),) + tuple(inner.args[1:]))
                call = ast.Call(
                    outer.name, (new_inner,) + tuple(outer.args[1:]))
            new_fields.append(ast.Field(call, alias=f"{base}_{fld}"))
    out = copy.copy(stmt)
    out.fields = new_fields
    return out



def _needs_string_host_path(stmt, schema_fn) -> bool:
    """schema_fn is called lazily — the shard-schema sweep only runs when a
    call could actually involve a string field."""
    candidates = []
    for call in _collect_calls(stmt.fields):
        if not call.args or call.name not in _STRING_OK_HOST or call.name == "count":
            continue
        a = _strip_expr(call.args[0])
        if isinstance(a, ast.VarRef):
            candidates.append(a.name)
    if not candidates:
        return False
    schema = schema_fn()
    return any(schema.get(n) == FieldType.STRING for n in candidates)


_AUX_SELECTORS = {"first", "last", "max", "min", "top", "bottom", "percentile"}



def _selector_aux_plan(stmt: ast.SelectStatement):
    """Detect `SELECT <selector>(f, ...), aux...`: exactly one call, a
    selector, with at least one auxiliary (non-call, non-`time`) column.
    Returns (call, aux_field_names) or None."""
    calls = _collect_calls(stmt.fields)
    if len(calls) != 1 or calls[0].name not in _AUX_SELECTORS:
        return None
    call = calls[0]
    if not call.args or not isinstance(_strip_expr(call.args[0]), ast.VarRef):
        return None
    aux_names: list[str] = []
    has_aux = False
    for f in stmt.fields:
        e = _strip_expr(f.expr)
        if isinstance(e, ast.Call):
            continue
        if isinstance(e, ast.VarRef) and e.name.lower() == "time":
            continue
        refs = _collect_varrefs(e)
        if refs is None:
            return None  # something we cannot evaluate per-row
        aux_names.extend(refs)
        has_aux = True
    if not has_aux:
        return None
    return call, sorted(set(aux_names))



def _collect_varrefs(e) -> list[str] | None:
    """Field/tag names referenced by a per-row arithmetic expr, or None
    if the expr contains anything other than refs/literals/arithmetic."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        return [e.name]
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral)):
        return []
    if isinstance(e, ast.UnaryExpr):
        return _collect_varrefs(e.expr)
    if isinstance(e, ast.BinaryExpr):
        l, r = _collect_varrefs(e.lhs), _collect_varrefs(e.rhs)
        if l is None or r is None:
            return None
        return l + r
    return None



def _selector_pick(sel_name: str, tw, vw, n_rows: int, pctl) -> list[int]:
    """Row indices (into the window slice) a selector picks; output order
    is time-ascending for multi-row selectors."""
    if sel_name == "first":
        return [0]
    if sel_name == "last":
        return [len(vw) - 1]
    if sel_name == "max":
        return [int(np.argmax(vw))]
    if sel_name == "min":
        return [int(np.argmin(vw))]
    if sel_name == "percentile":
        order = np.argsort(vw, kind="stable")
        i = int(math.floor(len(vw) * pctl / 100.0 + 0.5)) - 1
        if i < 0 or i >= len(vw):
            return []
        return [int(order[i])]
    # top/bottom: n best by value (ties -> earliest), output time-ascending
    keys = -vw if sel_name == "top" else vw
    order = np.lexsort((np.arange(len(vw)), keys))[:n_rows]
    return sorted(int(i) for i in order)



def _render_cell(v, ftype, call_name: str):
    if ftype == FieldType.STRING:
        return None if v is None else str(v)
    if ftype == FieldType.INT:
        return int(v)
    if ftype == FieldType.BOOL:
        return bool(round(float(v)))
    fv = float(v)
    if math.isnan(fv) or math.isinf(fv):
        return None
    return fv



def _eval_aux_expr(e, ri: int, aux_arr, tag_arr, schema):
    """Evaluate one auxiliary column at selected row `ri`."""
    e = _strip_expr(e)
    if isinstance(e, ast.VarRef):
        if e.name in aux_arr:
            vals, valid = aux_arr[e.name]
            if not valid[ri]:
                return None
            return _render_cell(vals[ri], schema.get(e.name), "aux")
        if e.name in tag_arr:
            return tag_arr[e.name][ri]
        return None
    if isinstance(e, (ast.NumberLiteral, ast.IntegerLiteral)):
        return e.val
    if isinstance(e, ast.UnaryExpr) and e.op == "-":
        v = _eval_aux_expr(e.expr, ri, aux_arr, tag_arr, schema)
        return None if v is None else -v
    if isinstance(e, ast.BinaryExpr):
        lv = _eval_aux_expr(e.lhs, ri, aux_arr, tag_arr, schema)
        rv = _eval_aux_expr(e.rhs, ri, aux_arr, tag_arr, schema)
        if lv is None or rv is None or isinstance(lv, str) or isinstance(rv, str):
            return None
        try:
            if e.op == "+":
                return lv + rv
            if e.op == "-":
                return lv - rv
            if e.op == "*":
                return lv * rv
            if e.op == "/":
                return lv / rv if rv != 0 else None
            if e.op == "%":
                return lv % rv if rv != 0 else None
        except TypeError:
            return None
    raise QueryError(f"unsupported auxiliary expression: {e}")



def _has_in_subquery(e) -> bool:
    if isinstance(e, ast.InSubquery):
        return True
    if isinstance(e, ast.BinaryExpr):
        return _has_in_subquery(e.lhs) or _has_in_subquery(e.rhs)
    if isinstance(e, (ast.ParenExpr, ast.UnaryExpr)):
        return _has_in_subquery(e.expr)
    return False



def _classify_select(stmt: ast.SelectStatement) -> str:
    """'raw' | 'device' | 'host' — the single source of truth for which
    execution path a SELECT takes (used by execution AND EXPLAIN)."""
    calls = _collect_calls(stmt.fields)
    if not calls:
        return "raw"
    if all(_is_device_call(c) for c in calls):
        if (stmt.group_by_time is None and len(calls) == 1
                and calls[0].name == "percentile"):
            # a SINGLE bare percentile is a SELECTOR: the row carries
            # the selected sample's own timestamp, which the device
            # kernel does not surface (server_test.go Selectors).
            # Combined with other aggregates the time is epoch anyway —
            # keep the device/pushdown path then.
            return "host"
        return "device"
    return "host"



def _is_device_call(call: ast.Call) -> bool:
    if call.name == "count" and call.args:
        inner = _strip_expr(call.args[0])
        if isinstance(inner, ast.Call) and inner.name == "distinct":
            return True
    if call.name in aggmod.REGISTRY:
        # device aggs take a bare field ref (string fields route to count
        # validation inside _select_agg)
        return bool(call.args) and isinstance(_strip_expr(call.args[0]), ast.VarRef)
    return False



def _call_param_value(arg) -> float | int:
    a = _strip_expr(arg)
    if isinstance(a, ast.UnaryExpr) and a.op == "-":
        return -_call_param_value(a.expr)
    if isinstance(a, ast.IntegerLiteral):
        return a.val
    if isinstance(a, ast.NumberLiteral):
        return a.val
    if isinstance(a, ast.DurationLiteral):
        return a.val_ns
    raise QueryError("function parameter must be a number or duration")



def _call_param_any(arg):
    a = _strip_expr(arg)
    if isinstance(a, ast.StringLiteral):
        return a.val
    return _call_param_value(arg)



def _resolve_host_call(call: ast.Call, group_time):
    """-> (kind, call_name, field, params, inner) where kind is
    'agg' | 'transform_raw' | 'transform_agg' | 'multi' | 'sliding'."""
    name = call.name
    if name == "sliding_window":
        # sliding_window(agg(f), N): agg over N consecutive GROUP BY time
        # windows, emitted at each window start (reference:
        # TestServer_Query_Sliding_Window_Aggregate)
        if len(call.args) != 2:
            raise QueryError("sliding_window() takes (aggregate, N)")
        if group_time is None:
            raise QueryError("sliding_window() requires GROUP BY time(...)")
        inner_e = _strip_expr(call.args[0])
        if not isinstance(inner_e, ast.Call):
            raise QueryError("sliding_window() argument must be an aggregate")
        n = int(_call_param_value(call.args[1]))
        if n < 1:
            raise QueryError("sliding_window() N must be >= 1")
        ikind, iname, ifield, iparams, _ = _resolve_host_call(inner_e, group_time)
        if ikind != "agg":
            raise QueryError("sliding_window() argument must be an aggregate")
        return "sliding", name, ifield, (n,), (iname, iparams)
    if name in fnmod.TRANSFORMS:
        if not call.args:
            raise QueryError(f"{name}() requires an argument")
        inner_e = _strip_expr(call.args[0])
        if name == "difference":
            # difference(f[, 'front'|'behind'|'absolute'])
            params = tuple(_call_param_any(a) for a in call.args[1:])
            if params and params[0] not in ("front", "behind", "absolute"):
                raise QueryError(
                    "difference() mode must be 'front', 'behind' or 'absolute'")
        else:
            params = tuple(_call_param_value(a) for a in call.args[1:])
        _check_host_arity(name, params)
        if isinstance(inner_e, ast.Call):
            if group_time is None:
                raise QueryError(
                    f"{name}() over an aggregate requires GROUP BY time(...)"
                )
            ikind, iname, ifield, iparams, _ = _resolve_host_call(inner_e, group_time)
            if ikind != "agg":
                raise QueryError(f"{name}() argument must be a field or aggregate")
            return "transform_agg", name, ifield, params, (iname, iparams)
        if isinstance(inner_e, ast.VarRef):
            if name.startswith("holt_winters"):
                raise QueryError(
                    "holt_winters() requires an aggregate argument with "
                    "GROUP BY time(...)"
                )
            if group_time is not None:
                raise QueryError(
                    f"{name}() over raw points cannot use GROUP BY time(...) — "
                    "wrap the field in an aggregate"
                )
            return "transform_raw", name, inner_e.name, params, None
        raise QueryError(f"{name}() argument must be a field or aggregate")
    if name in fnmod.MULTI_ROW:
        if not call.args:
            raise QueryError(f"{name}() requires a field argument")
        fld = _strip_expr(call.args[0])
        if not isinstance(fld, ast.VarRef):
            raise QueryError(f"{name}() argument must be a field")
        if name in ("top", "bottom") and len(call.args) > 2:
            # top(field, tag..., N): best N values from DISTINCT tag
            # combinations, one per combination (influx parser.go
            # parseCall top/bottom tag-key form)
            mids = [_strip_expr(a) for a in call.args[1:-1]]
            if all(isinstance(m, ast.VarRef) for m in mids):
                n = int(_call_param_value(call.args[-1]))
                if n < 1:
                    raise QueryError(f"{name}() N must be >= 1")
                return ("multi", name, fld.name,
                        (n, tuple(m.name for m in mids)), None)
        if name == "detect":
            # detect(field, 'algorithm'[, threshold]): string only in slot 0
            params = []
            for i, a in enumerate(call.args[1:]):
                params.append(_call_param_any(a) if i == 0 else _call_param_value(a))
            params = tuple(params)
            if params and not isinstance(params[0], str):
                raise QueryError("detect() algorithm must be a quoted string")
        else:
            params = tuple(_call_param_value(a) for a in call.args[1:])
        _check_host_arity(name, params)
        return "multi", name, fld.name, params, None
    if name == "count" and call.args and isinstance(_strip_expr(call.args[0]), ast.Call):
        inner = _strip_expr(call.args[0])
        if inner.name == "distinct":
            fld = _strip_expr(inner.args[0])
            return "agg", "count_distinct", fld.name, (), None
    if name in fnmod.HOST_AGGS:
        if not call.args or not isinstance(_strip_expr(call.args[0]), ast.VarRef):
            raise QueryError(f"{name}() requires a field argument")
        params = tuple(_call_param_value(a) for a in call.args[1:])
        _check_host_arity(name, params)
        return "agg", name, _strip_expr(call.args[0]).name, params, None
    raise QueryError(f"unsupported function: {name}")


# (min required params, max allowed params) per host call with parameters
_HOST_ARITY = {
    "percentile": (1, 1),
    "percentile_ogsketch": (1, 1),
    "moving_average": (1, 1),
    "top": (1, 1),
    "bottom": (1, 1),
    "sample": (1, 1),
    "distinct": (0, 0),
    "detect": (0, 2),
    "holt_winters": (1, 2),
    "holt_winters_with_fit": (1, 2),
    "difference": (0, 1),
    "non_negative_difference": (0, 0),
    "cumulative_sum": (0, 0),
}



def _check_host_arity(name: str, params: tuple) -> None:
    if name in ("percentile", "percentile_ogsketch") and params:
        q = params[0]
        if not (isinstance(q, (int, float)) and 0 <= q <= 100):
            raise QueryError(f"{name}() N must be between 0 and 100")
    lo, hi = _HOST_ARITY.get(name, (0, 1))
    if not (lo <= len(params) <= hi):
        raise QueryError(f"{name}() takes {lo + 1} to {hi + 1} arguments")
    if name == "moving_average" and params and int(params[0]) < 1:
        raise QueryError("moving_average() window must be >= 1")
    if name.startswith("holt_winters") and params:
        n = int(params[0])
        if not (1 <= n <= 10_000):
            raise QueryError("holt_winters() N must be between 1 and 10000")
        if len(params) > 1 and not (0 <= int(params[1]) <= 10_000):
            raise QueryError("holt_winters() seasonal period must be 0..10000")



def _resolve_call(call: ast.Call):
    """-> (AggSpec, params, field_name)."""
    name = call.name
    args = call.args
    if name == "count" and args and isinstance(_strip_expr(args[0]), ast.Call):
        inner = _strip_expr(args[0])
        if inner.name == "distinct":
            spec = aggmod.get("count_distinct")
            fld = _call_field(inner)
            return spec, (), fld
    if name == "percentile":
        if len(args) != 2:
            raise QueryError("percentile() takes (field, N)")
        q = _strip_expr(args[1])
        if isinstance(q, (ast.IntegerLiteral, ast.NumberLiteral)):
            qv = float(q.val)
        else:
            raise QueryError("percentile() N must be a number")
        return aggmod.get("percentile"), (qv,), _call_field(call)
    spec = aggmod.get(name)  # KeyError -> surfaced as query error
    return spec, (), _call_field(call)



def _call_field(call: ast.Call) -> str:
    if not call.args:
        raise QueryError(f"{call.name}() requires a field argument")
    a = _strip_expr(call.args[0])
    if isinstance(a, ast.VarRef):
        return a.name
    if isinstance(a, ast.Wildcard):
        raise QueryError(f"{call.name}(*) is not supported yet")
    raise QueryError(f"{call.name}() argument must be a field")



def _default_field_name(e) -> str:
    e = _strip_expr(e)
    if isinstance(e, ast.Call):
        if e.name == "count" and e.args:
            inner = _strip_expr(e.args[0])
            if isinstance(inner, ast.Call) and inner.name == "distinct":
                return "count"
        return e.name
    if isinstance(e, ast.VarRef):
        return e.name
    if isinstance(e, ast.BinaryExpr):
        calls = _calls_in(e)
        if calls:
            return "_".join(c.name for c in calls)
        refs = sorted({r for r in cond.field_filter_refs(e)})
        return "_".join(refs) if refs else "expr"
    return "expr"



def _eval_output_expr(expr, agg_results, seg, schema):
    """Evaluate one output column at segment `seg`. Returns (value, present)."""
    expr = _strip_expr(expr)
    if isinstance(expr, ast.Call):
        entry = agg_results.get(id(expr))
        if entry is None:
            raise QueryError(f"unplanned call {expr.name}")
        out, sel, counts, spec, fname, _times = entry
        if counts[seg] == 0:
            return None, False
        # single-sample stddev renders 0 (reference NewStdDevReduce,
        # engine/executor/agg_func.go, returns 0 with isNil=false for n==1)
        v = out[seg]
        ftype = schema.get(fname)
        if spec.int_output:
            return int(v), True
        if ftype == FieldType.INT and spec.name in ("sum", "min", "max", "first", "last", "spread"):
            # int64-exact path yields integer arrays: never round-trip
            # through float (2^53 cliff)
            if isinstance(v, np.integer):
                return int(v), True
            return int(round(float(v))), True
        if ftype == FieldType.BOOL and spec.name in ("first", "last", "min", "max"):
            return bool(round(float(v))), True
        fv = float(v)
        if math.isnan(fv) or math.isinf(fv):
            return None, True
        return fv, True
    if isinstance(expr, (ast.NumberLiteral, ast.IntegerLiteral)):
        return expr.val, False
    if isinstance(expr, ast.UnaryExpr) and expr.op == "-":
        v, p = _eval_output_expr(expr.expr, agg_results, seg, schema)
        return (None if v is None else -v), p
    if isinstance(expr, ast.BinaryExpr):
        lv, lp = _eval_output_expr(expr.lhs, agg_results, seg, schema)
        rv, rp = _eval_output_expr(expr.rhs, agg_results, seg, schema)
        present = lp or rp
        if lv is None or rv is None:
            return None, present
        try:
            if expr.op == "+":
                return lv + rv, present
            if expr.op == "-":
                return lv - rv, present
            if expr.op == "*":
                return lv * rv, present
            if expr.op == "/":
                return (lv / rv if rv != 0 else None), present
            if expr.op == "%":
                return (lv % rv if rv != 0 else None), present
        except TypeError:
            return None, present
    raise QueryError(f"unsupported output expression: {expr}")



def _apply_fill(rows, stmt, columns, count_idx: tuple = ()):
    """rows: [(t, vals, any_present)] per window, ascending. Influx fill
    semantics (reference: engine/executor fill_transform.go). count_idx:
    value indices holding bare count()/count(distinct) results — under
    the default null fill those render 0 for empty windows
    (TestServer_Query_Fill#6)."""
    fill = stmt.fill_option
    if not stmt.group_by_time:
        return [(t, v, p) for t, v, p in rows if p]
    if fill == "none":
        return [(t, v, p) for t, v, p in rows if p]
    if fill == "null" and count_idx:
        out = []
        for t, vals, p in rows:
            vals = [0 if (i in count_idx and v is None) else v
                    for i, v in enumerate(vals)]
            out.append((t, vals, p))
        rows = out
    if fill == "number":
        out = []
        for t, vals, p in rows:
            vals = [stmt.fill_value if v is None else v for v in vals]
            out.append((t, vals, p))
        return out
    if fill == "previous":
        prev = [None] * (len(columns) - 1)
        out = []
        for t, vals, p in rows:
            vals = [prev[i] if v is None else v for i, v in enumerate(vals)]
            prev = vals
            out.append((t, vals, p))
        return out
    if fill == "linear":
        ncols = len(columns) - 1
        arr = [[v for v in vals] for _t, vals, _p in rows]
        for ci in range(ncols):
            col = [r[ci] for r in arr]
            col = _linear_fill(col)
            for ri, v in enumerate(col):
                arr[ri][ci] = v
        return [(rows[i][0], arr[i], rows[i][2]) for i in range(len(rows))]
    return rows  # "null"



def _linear_fill(col):
    n = len(col)
    known = [i for i, v in enumerate(col) if v is not None]
    if len(known) < 2:
        return col
    out = list(col)
    for a, b in zip(known, known[1:]):
        if b - a > 1:
            va, vb = col[a], col[b]
            for i in range(a + 1, b):
                out[i] = va + (vb - va) * (i - a) / (b - a)
    return out



def _pyval(v, ftype):
    if ftype == FieldType.FLOAT:
        fv = float(v)
        # non-finite floats marshal as JSON null (influx semantics; a bare
        # NaN/Infinity literal is not valid strict JSON and breaks clients)
        return fv if math.isfinite(fv) else None
    if ftype == FieldType.INT:
        return int(v)
    if ftype == FieldType.BOOL:
        return bool(v)
    return v if isinstance(v, str) else str(v)



def _data_time_range(shards, mst):
    dmin = dmax = None
    for sh in shards:
        for r, c in sh.file_chunks(mst):
            dmin = c.tmin if dmin is None else min(dmin, c.tmin)
            dmax = c.tmax if dmax is None else max(dmax, c.tmax)
        m_lo, m_hi = _shard_mem_time_range(sh)
        if m_lo is not None:
            dmin = m_lo if dmin is None else min(dmin, m_lo)
            dmax = m_hi if dmax is None else max(dmax, m_hi)
    return dmin, dmax



def _fmt_duration(ns: int) -> str:
    if ns == 0:
        return "0s"
    h, rem = divmod(ns // NS, 3600)
    m, s = divmod(rem, 60)
    return f"{h}h{m}m{s}s"


def estimate_scan_bytes(shards, mst: str, tmin: int, tmax: int,
                        n_fields: int | None) -> int:
    """Estimated decoded working set of a scan, from chunk metadata +
    memtable row counts alone (no decode) — the per-query reservation
    the resource governor charges against its unified ledger before
    scan dispatch (utils/governor.py).  Same 9-bytes-per-cell model as
    scanpool.est_chunk_bytes; remote/duck-typed shards without chunk
    metadata contribute 0 (their bytes live on the peer)."""
    cols = (n_fields if n_fields else 1) + 2
    total_rows = 0
    for sh in shards:
        approx = getattr(sh, "approx_rows", None)
        if approx is None:
            continue
        r, _c = approx(mst, tmin, tmax)
        total_rows += r
    return total_rows * 9 * cols


__all__ = [
    "_prune_text_sids",
    "_series_needs_merged_decode",
    "_add_record_to_batches",
    "_merge_multi_source",
    "_inner_source_name",
    "_series",
    "_series_result",
    "_strip_expr",
    "_collect_calls",
    "_eval_scalar_row",
    "_scalar_refs",
    "_eval_scalar_cols",
    "_calls_in",
    "_call_wildcard_inner",
    "_has_call_wildcard",
    "_expand_call_wildcards",
    "_needs_string_host_path",
    "_selector_aux_plan",
    "_collect_varrefs",
    "_selector_pick",
    "_render_cell",
    "_eval_aux_expr",
    "_has_in_subquery",
    "_classify_select",
    "_is_device_call",
    "_call_param_value",
    "_call_param_any",
    "_resolve_host_call",
    "_check_host_arity",
    "_resolve_call",
    "_call_field",
    "_default_field_name",
    "_eval_output_expr",
    "_apply_fill",
    "_linear_fill",
    "_pyval",
    "_data_time_range",
    "_fmt_duration",
    "estimate_scan_bytes",
    "QueryError",
    "_STRING_OK_HOST",
    "_check_host_field_type",
    "NS",
    "MAX_SELECT_BUCKETS",
]
