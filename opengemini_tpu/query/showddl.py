"""SHOW/DDL/user statement execution (Executor mixin).

The statement dispatch + metadata SHOWs + DDL split out of
query/executor.py (reference analogue: the non-select half of
lifted/influx/coordinator/statement_executor.go).
"""

from __future__ import annotations

import math
import os
import re
import threading as _threading
import time as _time

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.ops import aggregates as aggmod
from opengemini_tpu.parallel import cluster as pcluster
from opengemini_tpu.ops import window as winmod
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query import functions as fnmod
from opengemini_tpu.record import FieldType, FieldTypeConflict
from opengemini_tpu.sql import ast
from opengemini_tpu.meta.users import AuthError as _AuthError
from opengemini_tpu.storage.engine import WriteError, _auto_shard_duration
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER, QueryKilled
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.sql.parser import parse

from opengemini_tpu.query.qhelpers import *  # noqa: F401,F403
from opengemini_tpu.query.qhelpers import (  # noqa: F401
    NS, MAX_SELECT_BUCKETS, QueryError,
)

_MIN_RP_DURATION_NS = 3600 * NS


def _check_rp_min_duration(duration_ns: int | None) -> None:
    """Influx rejects retention durations below 1h (0 = INF is allowed):
    'retention policy duration must be at least 1h0m0s'."""
    if duration_ns and duration_ns < _MIN_RP_DURATION_NS:
        raise QueryError(
            "retention policy duration must be at least 1h0m0s")


class ShowDdlMixin:
    def _replicate_ddl(self, cmd: dict) -> bool:
        """Route a DDL command through the raft meta store when clustered.
        Returns True when handled (leader path; the engine change arrives
        via the FSM listener). Raises on follower (client must redirect)."""
        if self.meta_store is None:
            return False
        self._require_leader()
        if not self.meta_store.propose_and_wait(cmd):
            raise QueryError("meta proposal failed (no quorum?)")
        return True

    # aggregates the downsample rewrite path can actually execute per field
    # type: integers must stay on the exact host int64 path (sum/min/max/
    # first/last) or produce float output (mean/stddev/median); count,
    # count_distinct, spread and percentile would fail at rewrite time for
    # INT fields, and percentile lacks its parameter in every path
    _DOWNSAMPLE_AGGS = {
        "float": {"sum", "count", "mean", "min", "max", "first", "last",
                  "spread", "stddev", "median"},
        "integer": {"sum", "mean", "min", "max", "first", "last",
                    "stddev", "median"},
        "boolean": {"first", "last"},
    }


    def _create_downsample(self, stmt, db: str) -> dict:
        """CREATE DOWNSAMPLE (reference: CreateDownSampleStatement semantics,
        meta downsample policies + engine_downsample.go): level i rewrites
        shards older than SAMPLEINTERVAL[i] at TIMEINTERVAL[i] resolution."""
        from opengemini_tpu.ops import aggregates as aggmod
        from opengemini_tpu.storage.engine import DownsamplePolicy

        tgt = stmt.database or db
        if not stmt.rp:
            raise QueryError("CREATE DOWNSAMPLE requires ON [db.]rp")
        samples, times = stmt.sample_intervals, stmt.time_intervals
        if len(samples) != len(times):
            raise QueryError(
                "SAMPLEINTERVAL and TIMEINTERVAL must have the same "
                f"number of levels ({len(samples)} vs {len(times)})"
            )
        for i in range(len(samples)):
            if times[i] <= 0 or samples[i] <= 0:
                raise QueryError("downsample intervals must be positive")
            if times[i] >= samples[i]:
                raise QueryError(
                    f"TIMEINTERVAL {_fmt_duration(times[i])} must be finer "
                    f"than SAMPLEINTERVAL {_fmt_duration(samples[i])}"
                )
            if i and (samples[i] <= samples[i - 1] or times[i] <= times[i - 1]):
                raise QueryError("downsample levels must be ascending")
        if stmt.ttl_ns and samples and stmt.ttl_ns < samples[-1]:
            raise QueryError("TTL must cover the last SAMPLEINTERVAL")
        for tname, agg in stmt.type_aggs.items():
            allowed = self._DOWNSAMPLE_AGGS.get(tname)
            if allowed is None:
                raise QueryError(f"unknown downsample field type: {tname!r}")
            if agg not in allowed:
                raise QueryError(
                    f"downsample aggregate {agg!r} is not supported for "
                    f"{tname} fields (one of: {', '.join(sorted(allowed))})"
                )
            aggmod.get(agg)  # registry sanity; allowlist is a subset
        self._check_fsm_db(tgt)
        if self.meta_store is not None:
            fsm_db = self.meta_store.fsm.databases[tgt]
            if stmt.rp not in fsm_db.get("rps", {}):
                raise QueryError(f"retention policy not found: {tgt}.{stmt.rp}")
            if stmt.rp in fsm_db.get("downsample", {}):
                raise QueryError(f"downsample already exists on {tgt}.{stmt.rp}")
        else:
            d = self.engine.databases.get(tgt)
            if d is None:
                raise QueryError(f"database not found: {tgt}")
            if stmt.rp not in d.rps:
                raise QueryError(f"retention policy not found: {tgt}.{stmt.rp}")
            if d.downsample.get(stmt.rp):
                raise QueryError(f"downsample already exists on {tgt}.{stmt.rp}")
        policies = [
            DownsamplePolicy(samples[i], times[i], dict(stmt.type_aggs))
            for i in range(len(samples))
        ]
        cmd = {"op": "add_downsample", "db": tgt, "rp": stmt.rp,
               "ttl_ns": stmt.ttl_ns,
               "policies": [p.to_json() for p in policies]}
        if not self._replicate_ddl(cmd):
            self.engine.set_downsample_policies(tgt, stmt.rp, policies,
                                                ttl_ns=stmt.ttl_ns)
        return {}


    def _show_cluster(self) -> dict:
        """Reference: SHOW CLUSTER (meta/data node roster with status)."""
        rows = []
        if self.meta_store is None:
            rows.append(["local", "", "meta,data", "leader", ""])
        else:
            leader = self.meta_store.leader_hint()
            members = self.meta_store.meta_members()
            for nid in sorted(members):
                status = "leader" if nid == leader else "follower"
                rows.append([nid, members[nid], "meta", status, ""])
            health = getattr(self.router, "health", {}) if self.router else {}
            shared = getattr(self.router, "shared_health", {}) if self.router else {}
            down_since = getattr(self.router, "down_since", {}) if self.router else {}
            for nid, info in sorted(self.meta_store.fsm.nodes.items()):
                status = "registered"
                # quorum view (exchange_health) wins over the purely local
                # probe: one coordinator's broken route must not show a
                # healthy node as down
                if nid in shared:
                    status = "up" if shared[nid] else "down"
                elif nid in health:
                    status = "up" if health[nid] else "down"
                since = down_since.get(nid)
                rows.append([nid, info.get("addr", ""),
                             info.get("role", "data"), status,
                             cond.format_rfc3339(int(since * 1e9)) if since else ""])
        return {"series": [_series("cluster", None,
                                   ["id", "addr", "role", "status", "down_since"],
                                   rows)]}


    def _show_downsamples(self, stmt, db: str) -> dict:
        tgt = stmt.database or db
        d = self.engine.databases.get(tgt)
        if d is None:
            raise QueryError(f"database not found: {tgt}")
        rows = []
        for rp in sorted(d.downsample):
            for p in d.downsample[rp]:
                aggs = ",".join(f"{t}({a})" for t, a in sorted(p.field_aggs.items()))
                rows.append([rp, aggs, _fmt_duration(p.age_ns),
                             _fmt_duration(p.every_ns)])
        series = _series(tgt, None,
                         ["rpName", "aggs", "sampleInterval", "timeInterval"],
                         rows)
        return {"series": [series]}


    def _check_fsm_db(self, name: str) -> None:
        """Validate db existence against the FSM BEFORE proposing a
        db-scoped command: the FSM silently ignores an unknown db, which
        would persist a junk entry. Leadership is checked FIRST — a
        lagging follower must redirect, not answer 'not found' from its
        stale FSM (same rule as _user_ddl)."""
        if self.meta_store is None:
            return
        self._require_leader()
        if name not in self.meta_store.fsm.databases:
            raise QueryError(f"database not found: {name}")


    def _require_leader(self) -> None:
        if self.meta_store is not None and not self.meta_store.is_leader():
            leader = self.meta_store.leader_hint() or "unknown"
            raise QueryError(
                f"not the meta leader; retry against node {leader!r}"
            )


    def _require_user(self, name: str) -> None:
        from opengemini_tpu.meta.users import AuthError

        if name not in self.users.users:
            raise AuthError(f"user not found: {name}")


    def _user_ddl(self, validate_fn, cmd_fn) -> bool:
        """Replicated user DDL: leadership first (a stale follower must
        redirect, not answer from its lagging local store), then
        validation + propose under one lock (check-then-propose races
        across HTTP threads would silently overwrite credentials).
        Returns False when not clustered (caller runs the local path)."""
        if self.meta_store is None:
            return False
        with self._user_ddl_lock:
            self._require_leader()
            validate_fn()
            if not self.meta_store.propose_and_wait(cmd_fn()):
                raise QueryError("meta proposal failed (no quorum?)")
        return True

    # -- entry --------------------------------------------------------------


    def execute_statement(self, stmt, db: str, now_ns: int, user=None) -> dict:
        if isinstance(stmt, ast.SelectStatement):
            STATS.incr("executor", "selects")
            res = self._select(stmt, db, now_ns)
            if not stmt.ascending and res.get("series"):
                # ORDER BY time DESC reverses the SERIES order too
                # (reference: Null_Aggregate desc cases expect the
                # lexicographically-last tagset first). Applied HERE, at
                # the statement boundary — _select recurses for
                # subqueries/CTEs and must not double-reverse
                res = dict(res, series=list(reversed(res["series"])))
            return res
        if isinstance(stmt, ast.UnionStatement):
            from opengemini_tpu.query import join as joinmod

            STATS.incr("executor", "selects")
            return joinmod.execute_union(self, stmt, db, now_ns)
        if isinstance(stmt, ast.ExplainStatement):
            return self._explain(stmt, db, now_ns)
        if isinstance(stmt, ast.ShowDatabases):
            names = self.engine.database_names()
            if self.auth_enabled and user is not None and not user.admin:
                names = [n for n in names if user.privileges.get(n)]
            rows = [[name] for name in names]
            return _series_result("databases", None, ["name"], rows)
        if isinstance(stmt, ast.ShowMeasurements):
            return self._show_measurements(stmt, db)
        if isinstance(stmt, ast.ShowTagKeys):
            return self._show_tag_keys(stmt, db)
        if isinstance(stmt, ast.ShowTagValues):
            return self._show_tag_values(stmt, db)
        if isinstance(stmt, ast.ShowFieldKeys):
            return self._show_field_keys(stmt, db)
        if isinstance(stmt, ast.ShowSeries):
            return self._show_series(stmt, db)
        if isinstance(stmt, ast.ShowSeriesExactCardinality):
            return self._show_series_exact_cardinality(stmt, db)
        if isinstance(stmt, ast.CreateMeasurement):
            # schema-on-write engine: accept and record nothing (see parser)
            return {}
        if isinstance(stmt, ast.ShowRetentionPolicies):
            return self._show_rps(stmt, db)
        if isinstance(stmt, ast.CreateDatabase):
            if not self._replicate_ddl({"op": "create_database", "name": stmt.name}):
                self.engine.create_database(stmt.name)
            if stmt.has_rp_clause:
                rp_name = stmt.rp_name or "autogen"
                cmd = {
                    "op": "create_rp", "db": stmt.name, "name": rp_name,
                    "duration_ns": stmt.duration_ns,
                    "shard_duration_ns": stmt.shard_duration_ns,
                    "default": True,
                }
                if not self._replicate_ddl(cmd):
                    self.engine.create_retention_policy(
                        stmt.name, rp_name, stmt.duration_ns,
                        stmt.shard_duration_ns, default=True,
                    )
            return {}
        if isinstance(stmt, ast.DropDatabase):
            if not self._replicate_ddl({"op": "drop_database", "name": stmt.name}):
                self.engine.drop_database(stmt.name)
            return {}
        if isinstance(stmt, ast.CreateRetentionPolicy):
            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            _check_rp_min_duration(stmt.duration_ns)
            cmd = {
                "op": "create_rp", "db": tgt, "name": stmt.name,
                "duration_ns": stmt.duration_ns,
                "shard_duration_ns": stmt.shard_duration_ns,
                "default": stmt.default,
            }
            if not self._replicate_ddl(cmd):
                self.engine.create_retention_policy(
                    tgt, stmt.name, stmt.duration_ns,
                    stmt.shard_duration_ns, stmt.default,
                )
            return {}
        if isinstance(stmt, ast.AlterRetentionPolicy):
            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            _check_rp_min_duration(stmt.duration_ns)
            if self.meta_store is not None:
                # validate against FSM state before proposing: the raft
                # apply path is fire-and-forget, so a bad alter would
                # otherwise succeed silently in a cluster
                fsm_db = self.meta_store.fsm.databases[tgt]
                rp = fsm_db.get("rps", {}).get(stmt.name)
                if rp is None:
                    raise QueryError(
                        f"retention policy not found: {stmt.name}")
                cur_dur = rp.get("duration_ns", 0)
                new_dur = cur_dur if stmt.duration_ns is None \
                    else stmt.duration_ns
                new_sd = stmt.shard_duration_ns
                if new_sd is None:
                    # the FSM stores None when CREATE RP omitted SHARD
                    # DURATION (and autogen has no key) — the engine
                    # auto-computed it; mirror that here
                    new_sd = rp.get("shard_duration_ns") \
                        or _auto_shard_duration(cur_dur)
                elif not new_sd:  # explicit 0 = recompute auto layout
                    new_sd = _auto_shard_duration(new_dur)
                if new_dur and new_dur < new_sd:
                    raise QueryError(
                        "retention policy duration must be greater than "
                        "the shard duration")
            cmd = {
                "op": "alter_rp", "db": tgt, "name": stmt.name,
                "duration_ns": stmt.duration_ns,
                "shard_duration_ns": stmt.shard_duration_ns,
                "default": stmt.default,
            }
            if not self._replicate_ddl(cmd):
                try:
                    self.engine.alter_retention_policy(
                        tgt, stmt.name, stmt.duration_ns,
                        stmt.shard_duration_ns, stmt.default,
                    )
                except ValueError as e:
                    raise QueryError(str(e)) from None
            return {}
        if isinstance(stmt, ast.DropRetentionPolicy):
            cmd = {"op": "drop_rp", "db": stmt.database or db, "name": stmt.name}
            if not self._replicate_ddl(cmd):
                self.engine.drop_retention_policy(stmt.database or db, stmt.name)
            return {}
        if isinstance(stmt, ast.CreateContinuousQuery):
            from opengemini_tpu.storage.engine import ContinuousQuery

            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            cq = ContinuousQuery(
                stmt.name, stmt.select_text,
                stmt.resample_every_ns, stmt.resample_for_ns,
            )
            if not self._replicate_ddl({"op": "create_cq", "db": tgt,
                                        "cq": cq.to_json()}):
                self.engine.create_continuous_query(tgt, cq)
            return {}
        if isinstance(stmt, ast.DropContinuousQuery):
            tgt = stmt.database or db
            if not self._replicate_ddl({"op": "drop_cq", "db": tgt,
                                        "name": stmt.name}):
                self.engine.drop_continuous_query(tgt, stmt.name)
            return {}
        if isinstance(stmt, ast.ShowContinuousQueries):
            series = []
            for name in sorted(self.engine.databases):
                d = self.engine.databases[name]
                rows = [[cq.name, cq.select_text] for cq in d.continuous_queries.values()]
                series.append(_series(name, None, ["name", "query"], rows))
            return {"series": series} if series else {}
        if isinstance(stmt, ast.CreateStream):
            from opengemini_tpu.services.stream import validate_stream_select
            from opengemini_tpu.storage.engine import StreamTask

            try:
                validate_stream_select(stmt.select)
            except ValueError as e:
                raise QueryError(str(e)) from None
            self._check_fsm_db(db)
            task = StreamTask(stmt.name, stmt.select_text, stmt.delay_ns)
            if not self._replicate_ddl({"op": "create_stream", "db": db,
                                        "task": task.to_json()}):
                self.engine.create_stream(db, task)
            return {}
        if isinstance(stmt, ast.DropStream):
            if not self._replicate_ddl({"op": "drop_stream", "db": db,
                                        "name": stmt.name}):
                self.engine.drop_stream(db, stmt.name)
            return {}
        if isinstance(stmt, ast.CreateSubscription):
            from opengemini_tpu.services.subscriber import Subscription

            if not stmt.destinations:
                raise QueryError("subscription requires at least one destination")
            for dest in stmt.destinations:
                if not dest.startswith(("http://", "https://")):
                    raise QueryError(
                        f"subscription destination must be an http(s) URL: {dest!r}"
                    )
            tgt = stmt.database or db
            self._check_fsm_db(tgt)
            sub = Subscription(stmt.name, stmt.mode, stmt.destinations)
            if not self._replicate_ddl({"op": "create_subscription", "db": tgt,
                                        "sub": sub.to_json()}):
                self.engine.create_subscription(tgt, sub)
            return {}
        if isinstance(stmt, ast.CreateDownsample):
            return self._create_downsample(stmt, db)
        if isinstance(stmt, ast.DropDownsample):
            tgt = stmt.database or db
            cmd = {"op": "drop_downsample", "db": tgt, "rp": stmt.rp or None}
            if not self._replicate_ddl(cmd):
                self.engine.drop_downsample_policies(tgt, stmt.rp or None)
            return {}
        if isinstance(stmt, ast.ShowDownsamples):
            return self._show_downsamples(stmt, db)
        if isinstance(stmt, ast.ShowCluster):
            return self._show_cluster()
        if isinstance(stmt, ast.DropSubscription):
            tgt = stmt.database or db
            if not self._replicate_ddl({"op": "drop_subscription", "db": tgt,
                                        "name": stmt.name}):
                self.engine.drop_subscription(tgt, stmt.name)
            return {}
        if isinstance(stmt, ast.ShowSubscriptions):
            series = []
            for name in sorted(self.engine.databases):
                d = self.engine.databases[name]
                rows = [
                    [s.name, s.mode, ", ".join(s.destinations)]
                    for s in d.subscriptions.values()
                ]
                series.append(
                    _series(name, None, ["name", "mode", "destinations"], rows)
                )
            return {"series": series} if series else {}
        if isinstance(stmt, ast.ShowQueries):
            rows = [
                [q["qid"], q["query"], q["database"],
                 f"{q['duration_ms']}ms", q["status"]]
                for q in TRACKER.snapshot()
            ]
            return _series_result(
                "", None, ["qid", "query", "database", "duration", "status"], rows
            )
        if isinstance(stmt, ast.KillQuery):
            if not TRACKER.kill(stmt.qid):
                raise QueryError(f"no such query: {stmt.qid}")
            return {}
        if isinstance(stmt, ast.ShowShards):
            rows = []
            for (sdb, rp, start), sh in sorted(self.engine._shards.items()):
                rows.append([
                    sdb, rp, start, sh.tmin, sh.tmax, len(sh._files),
                    "cold" if os.path.islink(sh.path) else "hot",
                ])
            return _series_result(
                "shards", None,
                ["database", "retention_policy", "shard_group", "start_time",
                 "end_time", "files", "tier"],
                rows,
            )
        if isinstance(stmt, ast.ShowStats):
            series = []
            for module, vals in sorted(STATS.snapshot().items()):
                rows = [[k, v] for k, v in sorted(vals.items())]
                series.append(_series(module, None, ["statistic", "value"], rows))
            return {"series": series} if series else {}
        if isinstance(stmt, ast.ShowDiagnostics):
            import platform
            import sys as _sys

            import jax as _jax

            from opengemini_tpu import __version__

            rows = [
                ["version", __version__],
                ["python", _sys.version.split()[0]],
                ["jax", _jax.__version__],
                ["backend", _jax.default_backend()],
                ["devices", str(len(_jax.devices()))],
                ["platform", platform.platform()],
                ["data_dir", self.engine.root],
            ]
            out = [_series("system", None, ["name", "value"], rows)]
            dr = getattr(self.router, "datarep", None) if self.router else None
            if dr is not None:
                grows = dr.group_status()
                out.append(_series(
                    "replication_groups", None,
                    ["group", "members", "state", "leader", "log_len",
                     "applied"], grows or [["(none yet)", "", "", "", 0, 0]]))
            return {"series": out}
        if isinstance(stmt, ast.ShowStreams):
            series = []
            for name in sorted(self.engine.databases):
                d = self.engine.databases[name]
                rows = [[s.name, s.select_text] for s in d.streams.values()]
                series.append(_series(name, None, ["name", "query"], rows))
            return {"series": series} if series else {}
        if isinstance(stmt, ast.CreateModel):
            # castor fit pipeline: train on the SELECT's output, persist
            # the artifact; detect(field, '<name>') scores against it
            # (reference: services/castor fit flow + model lifecycle)
            from opengemini_tpu.services import castor as _castor

            if stmt.name.lower() in _castor.ALGORITHMS:
                raise QueryError(
                    f"model name {stmt.name!r} shadows a built-in algorithm")
            if stmt.name.lower() in _castor._UDFS:
                raise QueryError(
                    f"model name {stmt.name!r} shadows a loaded UDF")
            if (not stmt.name or "/" in stmt.name
                    or stmt.name.startswith(".")):
                # ModelStore's artifact-name rules, enforced BEFORE the
                # raft proposal: a bad name must never commit to the FSM
                # (every replica's listener would fail forever)
                raise QueryError(f"bad model name {stmt.name!r}")
            res = self._select(stmt.select, db, now_ns)
            vals: list[float] = []
            for series in res.get("series", []):
                for row in series.get("values", []):
                    for v in row[1:]:
                        if isinstance(v, (int, float)) and not isinstance(
                                v, bool):
                            vals.append(float(v))
            try:
                doc = _castor.fit(stmt.algorithm, np.asarray(vals),
                                  stmt.threshold)
            except ValueError as e:
                raise QueryError(str(e)) from e
            doc["name"] = stmt.name
            doc["source"] = stmt.select_text
            # clustered: the fitted artifact replicates through raft like
            # every other DDL (each replica persists it via the FSM
            # listener); single-node saves directly
            if not self._replicate_ddl(
                    {"op": "save_model", "name": stmt.name, "doc": doc}):
                self.engine.models.save(stmt.name, doc)
            return {}
        if isinstance(stmt, ast.ShowModels):
            rows = []
            for name in self.engine.models.names():
                m = self.engine.models.get(name) or {}
                rows.append([
                    name, m.get("algorithm", ""), m.get("threshold"),
                    m.get("trained_rows", 0),
                    cond.format_rfc3339(
                        int(m.get("fitted_at", 0)) * NS),
                ])
            if not rows:
                return {}
            return _series_result(
                "models", None,
                ["name", "algorithm", "threshold", "trainedRows", "fittedAt"],
                rows)
        if isinstance(stmt, ast.DropModel):
            if stmt.name not in self.engine.models.names():
                raise QueryError(f"model not found: {stmt.name}")
            if not self._replicate_ddl({"op": "drop_model",
                                        "name": stmt.name}):
                self.engine.models.drop(stmt.name)
            return {}
        if isinstance(stmt, ast.DropMeasurement):
            # mark + deferred purge (reference MarkMeasurementDelete):
            # SELECT hides it now; SHOW SERIES keeps the series until the
            # retention tick (or a rewrite of the name) purges
            self.engine.mark_measurement_delete(db, stmt.name)
            return {}
        if isinstance(stmt, (ast.DeleteSeries, ast.DropSeries)):
            return self._delete(stmt, db, now_ns)
        if isinstance(stmt, ast.CreateUser):
            def _validate_create():
                from opengemini_tpu.meta.users import AuthError

                if stmt.name in self.users.users:
                    raise AuthError(f"user already exists: {stmt.name}")

            def _cmd_create():
                from opengemini_tpu.meta.users import UserStore

                salt, pw_hash = UserStore.make_credentials(stmt.password)
                return {"op": "create_user", "name": stmt.name,
                        "salt": salt, "hash": pw_hash, "admin": stmt.admin}

            if not self._user_ddl(_validate_create, _cmd_create):
                self.users.create(stmt.name, stmt.password, stmt.admin)
            return {}
        if isinstance(stmt, ast.DropUser):
            if not self._user_ddl(
                lambda: self._require_user(stmt.name),
                lambda: {"op": "drop_user", "name": stmt.name},
            ):
                self.users.drop(stmt.name)
            return {}
        if isinstance(stmt, ast.SetPassword):
            def _cmd_setpw():
                from opengemini_tpu.meta.users import UserStore

                salt, pw_hash = UserStore.make_credentials(stmt.password)
                return {"op": "set_password", "name": stmt.name,
                        "salt": salt, "hash": pw_hash}

            if not self._user_ddl(lambda: self._require_user(stmt.name), _cmd_setpw):
                self.users.set_password(stmt.name, stmt.password)
            return {}
        if isinstance(stmt, ast.GrantStatement):
            admin_grant = not stmt.database and stmt.privilege == "ALL"
            cmd = (
                {"op": "grant_admin", "user": stmt.user, "admin": True}
                if admin_grant
                else {"op": "grant", "user": stmt.user, "db": stmt.database,
                      "privilege": stmt.privilege}
            )
            if not self._user_ddl(lambda: self._require_user(stmt.user), lambda: cmd):
                if admin_grant:
                    self.users.grant_admin(stmt.user)
                else:
                    self.users.grant(stmt.user, stmt.database, stmt.privilege)
            return {}
        if isinstance(stmt, ast.RevokeStatement):
            admin_revoke = not stmt.database and stmt.privilege == "ALL"
            cmd = (
                {"op": "grant_admin", "user": stmt.user, "admin": False}
                if admin_revoke
                else {"op": "revoke", "user": stmt.user, "db": stmt.database}
            )
            if not self._user_ddl(lambda: self._require_user(stmt.user), lambda: cmd):
                if admin_revoke:
                    self.users.grant_admin(stmt.user, admin=False)
                else:
                    self.users.revoke(stmt.user, stmt.database)
            return {}
        if isinstance(stmt, ast.ShowUsers):
            rows = [[u.name, u.admin] for u in self.users.users.values()]
            return _series_result("", None, ["user", "admin"], sorted(rows))
        if isinstance(stmt, ast.ShowGrants):
            u = self.users.users.get(stmt.user)
            if u is None:
                raise QueryError(f"user not found: {stmt.user}")
            rows = [[db_, p] for db_, p in sorted(u.privileges.items())]
            return _series_result("", None, ["database", "privilege"], rows)
        if isinstance(stmt, ast.ShowMeasurementCardinality):
            names: set[str] = set()
            cdb = stmt.database or db
            for sh in self._all_shards_db(cdb):
                names.update(
                    m for m in sh.measurements() if self._visible(cdb, m))
            return _series_result("", None, ["count"], [[len(names)]])
        if isinstance(stmt, ast.ShowSeriesCardinality):
            from opengemini_tpu.ingest.line_protocol import series_key

            # one row per shard-group time range (reference output shape:
            # startTime/endTime/count, coordinator show-executor)
            by_range: dict[tuple[int, int], set] = {}
            for sh in self._all_shards_db(stmt.database or db):
                bucket = by_range.setdefault((sh.tmin, sh.tmax), set())
                for m, tags in sh.index.iter_series_entries():
                    bucket.add(series_key(m, tags))
            rows = [
                [cond.format_rfc3339(lo), cond.format_rfc3339(hi), len(keys)]
                for (lo, hi), keys in sorted(by_range.items())
                if keys
            ]
            if not rows:
                return {}
            return _series_result("", None, ["startTime", "endTime", "count"], rows)
        raise QueryError(f"unsupported statement: {type(stmt).__name__}")


    def _delete(self, stmt, db: str, now_ns: int) -> dict:
        """DELETE FROM m WHERE ... (time range + tag filters) and
        DROP SERIES FROM m WHERE ... (whole series).
        Reference: deleteSeries / dropSeries statement executors."""
        if not stmt.measurement:
            raise QueryError("DELETE/DROP SERIES requires FROM <measurement>")
        is_drop_series = isinstance(stmt, ast.DropSeries)
        shards = self._all_shards_db(db)
        # tag keys unioned ACROSS shards (like _scan_context) — a shard
        # without the measurement must not re-classify tags as fields,
        # which would error mid-way with earlier shards already deleted
        tag_keys: set[str] = set()
        for sh in shards:
            tag_keys.update(sh.index.tag_keys(stmt.measurement))
        sc = cond.split(stmt.condition, tag_keys, now_ns)
        if sc.has_row_filter:
            raise QueryError("DELETE conditions may only reference time and tags")
        has_time = sc.tmin != cond.MIN_TIME or sc.tmax != cond.MAX_TIME
        if is_drop_series and has_time:
            # influx rejects time bounds here rather than over-deleting
            raise QueryError("DROP SERIES does not support time conditions")
        for sh in shards:
            sids = (
                cond.eval_tag_expr(sc.tag_expr, sh.index, stmt.measurement)
                if sc.tag_expr is not None
                else None
            )
            if sids is not None and not sids:
                continue
            if is_drop_series or not has_time:
                sh.delete_data(stmt.measurement, sids)
            else:
                sh.delete_data(
                    stmt.measurement, sids,
                    None if sc.tmin == cond.MIN_TIME else sc.tmin,
                    None if sc.tmax == cond.MAX_TIME else sc.tmax,
                )
        if self.engine.rollup_mgr is not None:
            # re-dirty the deleted span so maintenance re-folds it (and
            # zero-fills series the delete emptied) — a clean-looking
            # rollup window must never serve deleted rows
            self.engine.rollup_mgr.note_delete(
                db, stmt.measurement,
                None if not has_time or sc.tmin == cond.MIN_TIME else sc.tmin,
                None if not has_time or sc.tmax == cond.MAX_TIME else sc.tmax,
            )
        return {}

    # -- SELECT -------------------------------------------------------------


    def _all_shards_db(self, db: str):
        return self.engine.shards_for_range(db, None, cond.MIN_TIME, cond.MAX_TIME)


    def _visible(self, db: str, mst: str) -> bool:
        """False for mark-deleted measurements (hidden from SELECT and
        metadata SHOWs; SHOW SERIES intentionally still lists their series
        until the purge — reference TestServer_Query_ShowSeries)."""
        return not self.engine.is_measurement_dropped(db, mst)


    def _show_measurements(self, stmt, db) -> dict:
        db = stmt.database or db
        names: set[str] = set()
        for sh in self._all_shards_db(db):
            names.update(m for m in sh.measurements() if self._visible(db, m))
        if self.router is not None:
            try:
                names.update(self.router.remote_measurements(db, None))
            except Exception as e:  # noqa: BLE001
                raise QueryError(str(e)) from e
        if stmt.regex:
            rx = re.compile(stmt.regex)
            names = {n for n in names if rx.search(n)}
        if not names:
            return {}
        return _series_result("measurements", None, ["name"], [[n] for n in sorted(names)])


    @staticmethod
    def _mst_match(stmt, mst: str) -> bool:
        if stmt.measurement:
            return mst == stmt.measurement
        if getattr(stmt, "measurement_regex", ""):
            return re.search(stmt.measurement_regex, mst) is not None
        return True


    @staticmethod
    def _matching_sids(sh, mst: str, condition) -> set[int]:
        """Series of `mst` in shard `sh` matching the tag predicates of
        `condition`.  Time predicates are ignored (SHOW metadata statements
        filter series, not points); predicates on keys that are not tags of
        the measurement match NOTHING — `WHERE value = 'x'` over series
        metadata is vacuously false, matching the reference's behavior
        (coordinator show-executor tag-filter rewrite)."""
        sids = sh.index.series_ids(mst)
        if condition is not None:
            tag_keys = set(sh.index.tag_keys(mst))
            sc = cond.split(condition, tag_keys, 0)
            if sc.has_row_filter:
                return set()
            if sc.tag_expr is not None:
                sids = sids & cond.eval_tag_expr(sc.tag_expr, sh.index, mst)
        return sids


    def _show_tag_keys(self, stmt, db) -> dict:
        db = stmt.database or db
        per_mst: dict[str, set] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst) or not self._visible(db, mst):
                    continue
                if stmt.condition is not None:
                    for sid in self._matching_sids(sh, mst, stmt.condition):
                        _, tags = sh.index.series_entry(sid)
                        per_mst.setdefault(mst, set()).update(k for k, _ in tags)
                else:
                    per_mst.setdefault(mst, set()).update(sh.index.tag_keys(mst))
        series = [
            _series(m, None, ["tagKey"], [[k] for k in sorted(keys)])
            for m, keys in sorted(per_mst.items())
            if keys
        ]
        return {"series": series} if series else {}


    @staticmethod
    def _split_value_predicates(expr):
        """Split a SHOW TAG VALUES condition into (series condition,
        [output-value predicates]): influx lets WHERE reference the
        output `value` column (server_test.go ShowTagValues 'with value
        filter'). Only top-level AND conjuncts split; anything else
        stays a series condition."""
        preds: list = []

        def walk(e):
            if isinstance(e, ast.ParenExpr):
                return walk(e.expr)
            if isinstance(e, ast.BinaryExpr):
                if e.op.upper() == "AND":
                    lhs = walk(e.lhs)
                    rhs = walk(e.rhs)
                    if lhs is None:
                        return rhs
                    if rhs is None:
                        return lhs
                    return ast.BinaryExpr("AND", lhs, rhs)
                lv = e.lhs
                if isinstance(lv, ast.ParenExpr):
                    lv = lv.expr
                if (isinstance(lv, ast.VarRef) and lv.name == "value"
                        and e.op in ("=", "!=", "=~", "!~")
                        and isinstance(e.rhs,
                                       (ast.StringLiteral, ast.RegexLiteral))):
                    preds.append((e.op, e.rhs))
                    return None
            return e

        return walk(expr), preds

    @staticmethod
    def _value_pred_ok(v: str, preds) -> bool:
        for op, rhs in preds:
            if op == "=" and v != rhs.val:
                return False
            if op == "!=" and v == rhs.val:
                return False
            if op in ("=~", "!~"):
                hit = re.search(rhs.pattern, v) is not None
                if (op == "=~") != hit:
                    return False
        return True

    def _show_tag_values(self, stmt, db) -> dict:
        db = stmt.database or db
        key_rx = re.compile(stmt.key_regex) if stmt.key_regex else None
        series_cond, value_preds = self._split_value_predicates(
            stmt.condition)
        per_mst: dict[str, set] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst) or not self._visible(db, mst):
                    continue
                wanted = [
                    k for k in sh.index.tag_keys(mst)
                    if (k in stmt.keys) or (key_rx is not None and key_rx.search(k))
                ]
                if not wanted:
                    continue
                if series_cond is None:
                    # no series filter: direct inverted-index lookup, never
                    # an O(series) walk (1M-series measurements)
                    bucket = per_mst.setdefault(mst, set())
                    for k in wanted:
                        for v in sh.index.tag_values(mst, k):
                            bucket.add((k, v))
                    continue
                for sid in self._matching_sids(sh, mst, series_cond):
                    _, tags = sh.index.series_entry(sid)
                    for k, v in tags:
                        if k in wanted:
                            per_mst.setdefault(mst, set()).add((k, v))
        series = []
        for mst, pairs in sorted(per_mst.items()):
            if value_preds:
                pairs = {(k, v) for k, v in pairs
                         if self._value_pred_ok(v, value_preds)}
            uniq = sorted(pairs, reverse=stmt.order_desc)
            if stmt.offset:
                uniq = uniq[stmt.offset:]
            if stmt.limit:
                uniq = uniq[:stmt.limit]
            if uniq:
                series.append(
                    _series(mst, None, ["key", "value"], [list(p) for p in uniq]))
        return {"series": series} if series else {}


    def _show_field_keys(self, stmt, db) -> dict:
        db = stmt.database or db
        per_mst: dict[str, dict] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst) or not self._visible(db, mst):
                    continue
                per_mst.setdefault(mst, {}).update(sh.schema(mst))
        type_names = {
            FieldType.FLOAT: "float",
            FieldType.INT: "integer",
            FieldType.BOOL: "boolean",
            FieldType.STRING: "string",
        }
        series = []
        for mst, sch in sorted(per_mst.items()):
            rows = [[k, type_names[t]] for k, t in sorted(sch.items())]
            series.append(_series(mst, None, ["fieldKey", "fieldType"], rows))
        return {"series": series} if series else {}


    def _show_series(self, stmt, db) -> dict:
        from opengemini_tpu.ingest.line_protocol import series_key

        db = stmt.database or db
        keys: set[str] = set()
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst):
                    continue
                for sid in self._matching_sids(sh, mst, stmt.condition):
                    m, tags = sh.index.series_entry(sid)
                    keys.add(series_key(m, tags))
        if not keys:
            return {}
        return _series_result("", None, ["key"], [[k] for k in sorted(keys)])


    def _show_series_exact_cardinality(self, stmt, db) -> dict:
        """Per-measurement exact distinct-series count (reference:
        ShowSeriesCardinalityStatement with EXACT, executor.go)."""
        from opengemini_tpu.ingest.line_protocol import series_key

        db = stmt.database or db
        per_mst: dict[str, set] = {}
        for sh in self._all_shards_db(db):
            for mst in sh.measurements():
                if not self._mst_match(stmt, mst):
                    continue
                bucket = per_mst.setdefault(mst, set())
                for sid in self._matching_sids(sh, mst, stmt.condition):
                    m, tags = sh.index.series_entry(sid)
                    bucket.add(series_key(m, tags))
        series = [
            _series(m, None, ["count"], [[len(keys)]])
            for m, keys in sorted(per_mst.items())
            if keys
        ]
        return {"series": series} if series else {}


    def _show_rps(self, stmt, db) -> dict:
        db = stmt.database or db
        d = self.engine.databases.get(db)
        if d is None:
            raise QueryError(f"database not found: {db}")
        rows = []
        for rp in d.rps.values():
            rows.append(
                [
                    rp.name,
                    _fmt_duration(rp.duration_ns),
                    _fmt_duration(rp.shard_duration_ns),
                    1,
                    rp.name == d.default_rp,
                ]
            )
        return _series_result(
            "", None,
            ["name", "duration", "shardGroupDuration", "replicaN", "default"],
            rows,
        )


# -- helpers -----------------------------------------------------------------



