"""FROM (SELECT ...) handling (Executor mixin): subquery
materialization, direct projections, INTO writes. Split out of
query/executor.py (reference: subquery builders in
engine/executor/select.go).
"""

from __future__ import annotations

import math
import os
import re
import threading as _threading
import time as _time

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.ops import aggregates as aggmod
from opengemini_tpu.parallel import cluster as pcluster
from opengemini_tpu.ops import window as winmod
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query import functions as fnmod
from opengemini_tpu.record import FieldType, FieldTypeConflict
from opengemini_tpu.sql import ast
from opengemini_tpu.meta.users import AuthError as _AuthError
from opengemini_tpu.storage.engine import WriteError
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER, QueryKilled
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.sql.parser import parse

from opengemini_tpu.query.qhelpers import *  # noqa: F401,F403
from opengemini_tpu.query.qhelpers import (  # noqa: F401
    NS, MAX_SELECT_BUCKETS, QueryError,
)


# chunked inner evaluation: estimated inner scans above the threshold
# evaluate window-aligned time chunks into the spill engine one at a
# time, bounding the JSON intermediate (VERDICT r4 #9; reference:
# streaming subquery_transform.go). The cap is the loud guard for
# non-chunkable shapes (reference analogue: max-select-point).
SUBQUERY_CHUNK_ROWS = int(os.environ.get(
    "OGTPU_SUBQUERY_CHUNK_ROWS", "0")) or 5_000_000
SUBQUERY_CHUNK_TARGET = int(os.environ.get(
    "OGTPU_SUBQUERY_CHUNK_TARGET", "0")) or 2_000_000
SUBQUERY_MAX_ROWS = int(os.environ.get(
    "OGTPU_SUBQUERY_MAX_ROWS", "50000000"))


def _subquery_chunk_safe(inner) -> bool:
    """True when evaluating `inner` over disjoint window-aligned time
    chunks produces the same rows as one evaluation: no global
    limits, no cross-window sequence transforms, no fill that reaches
    across windows, plain measurement sources."""
    if not isinstance(inner, ast.SelectStatement):
        return False
    if inner.limit or inner.offset or inner.slimit or inner.soffset:
        return False
    if inner.fill_option not in (None, "null", "none"):
        return False  # fill(previous/linear) crosses chunk edges and
        # fill(<number>) emits rows per KNOWN series — series discovery
        # is chunk-dependent, so numeric fill must evaluate single-shot
    if not all(isinstance(s, ast.Measurement) for s in inner.sources):
        return False
    calls = []
    for f in inner.fields:
        calls.extend(_calls_in(f.expr))
    if not calls:
        return True  # raw projection: rows are window-independent
    if inner.group_by_time is None:
        return False  # whole-range aggregate: cannot split
    for c in calls:
        if c.name in fnmod.TRANSFORMS or c.name == "sliding_window":
            return False  # sequence transforms need neighboring windows
    return True


def _row_fields(cols: list, vals) -> dict:
    """Result-row values -> typed field dict (shared by the subquery
    materializer and SELECT INTO — the two paths must classify python
    values into FieldTypes identically)."""
    fields = {}
    for name, v in zip(cols, vals):
        if v is None:
            continue
        if isinstance(v, bool):
            fields[name] = (FieldType.BOOL, v)
        elif isinstance(v, int):
            fields[name] = (FieldType.INT, v)
        elif isinstance(v, float):
            fields[name] = (FieldType.FLOAT, v)
        else:
            fields[name] = (FieldType.STRING, str(v))
    return fields


def _materialize_into(tmp_engine, mst_name: str, series_list,
                      spent: int = 0) -> int:
    """Write one inner-result batch into the spill engine. Points at the
    same (tags, time) MERGE their fields — multi-source inners
    legitimately emit one row per source at the same timestamp with
    disjoint columns, and the engine's point-level LWW would otherwise
    drop all but the last (TestServer_Query_MultiMeasurements#4/#5).
    Returns the cumulative row count; beyond SUBQUERY_MAX_ROWS the
    materialization fails loudly instead of exhausting memory/disk."""
    by_key: dict[tuple, dict] = {}
    key_order: list[tuple] = []
    for series in series_list:
        tags = tuple(sorted(series.get("tags", {}).items()))
        cols = series["columns"][1:]
        for row in series["values"]:
            fields = _row_fields(cols, row[1:])
            if fields:
                pkey = (tags, row[0])
                got = by_key.get(pkey)
                if got is None:
                    by_key[pkey] = fields
                    key_order.append(pkey)
                else:
                    got.update(fields)
    spent += len(key_order)
    if SUBQUERY_MAX_ROWS and spent > SUBQUERY_MAX_ROWS:
        raise QueryError(
            f"subquery materialized more than {SUBQUERY_MAX_ROWS} rows; "
            "narrow the inner time range (OGTPU_SUBQUERY_MAX_ROWS)")
    points = [
        (mst_name, tags, t, by_key[(tags, t)])
        for tags, t in key_order
    ]
    if points:
        tmp_engine.write_rows("sub", points)
    return spent


class SubqueryMixin:
    def _project_union(self, stmt, inner_res) -> list[dict] | None:
        """Raw column projection over a union subquery result; returns None
        when the outer statement needs real execution (aggregates, WHERE,
        grouping) and must fall back to materialization."""
        if (stmt.condition is not None or stmt.group_by_tags
                or stmt.group_by_all_tags or stmt.group_by_time):
            return None
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if not isinstance(e, (ast.VarRef, ast.Wildcard)):
                return None
        series = inner_res.get("series", [])
        if not series:
            return []
        src = series[0]
        cols_in = src["columns"]
        names, idxs = [], []
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Wildcard):
                for i, c in enumerate(cols_in[1:], start=1):
                    names.append(c)
                    idxs.append(i)
            else:
                if e.name.lower() == "time":
                    continue  # always column 0
                names.append(f.alias or e.name)
                idxs.append(cols_in.index(e.name) if e.name in cols_in else -1)
        rows = [
            [row[0]] + [row[i] if i >= 0 else None for i in idxs]
            for row in src["values"]
        ]
        if not stmt.ascending:
            rows.reverse()
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit:
            rows = rows[: stmt.limit]
        return [{"name": src["name"], "columns": ["time"] + names, "values": rows}]


    def _project_dimensioned(self, stmt, series_list: list[dict],
                             dims: list[str], name: str):
        """Bare projection over a dimensioned subquery: one output series,
        dim tags as leading columns, inner rows (incl. all-null ones) in
        series order. Returns None when the outer needs real execution."""
        if (stmt.condition is not None or stmt.group_by_tags
                or stmt.group_by_all_tags or stmt.group_by_time
                or not series_list):
            return None
        for f in stmt.fields:
            if not isinstance(_strip_expr(f.expr), (ast.VarRef, ast.Wildcard)):
                return None
        cols_in = series_list[0]["columns"]
        names, sources = [], []  # source: ("dim", key) | ("col", idx)
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Wildcard):
                for d in dims:
                    names.append(d)
                    sources.append(("dim", d))
                for i, c in enumerate(cols_in[1:], start=1):
                    names.append(c)
                    sources.append(("col", i))
            elif e.name.lower() == "time":
                continue
            elif e.name in dims:
                names.append(f.alias or e.name)
                sources.append(("dim", e.name))
            else:
                names.append(f.alias or e.name)
                sources.append(
                    ("col", cols_in.index(e.name))
                    if e.name in cols_in else ("col", -1))
        rows = []
        for s in series_list:
            tags = s.get("tags", {})
            for row in s["values"]:
                out = [row[0]]
                for kind, ref in sources:
                    if kind == "dim":
                        out.append(tags.get(ref))
                    else:
                        out.append(row[ref] if ref >= 0 else None)
                rows.append(out)
        if not stmt.ascending:
            rows.reverse()
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit:
            rows = rows[: stmt.limit]
        return [{"name": name, "columns": ["time"] + names, "values": rows}]


    def _write_into(self, target: ast.Measurement, db: str, series_list: list[dict]) -> int:
        """SELECT INTO: write result rows into the target measurement
        (reference: into clause handling in statement_executor.go). Rows go
        through the structured write path (WAL'd, schema-checked) — never
        through line-protocol text, so arbitrary tag/field content is safe."""
        tgt_db = target.database or db
        if tgt_db not in self.engine.databases:
            raise QueryError(f"database not found: {tgt_db}")
        points = []
        for series in series_list:
            base_tags = dict(series.get("tags", {}))
            cols = series["columns"][1:]
            # top/bottom(field, tag, N) columns marked as tags write back
            # as TAGS (reference TestServer_Query_TopBottomWriteTags)
            tag_cols = set(series.get("_tag_cols", ()))
            tag_idx = [(i, c) for i, c in enumerate(cols) if c in tag_cols]
            if not tag_idx:
                # the common path: one tag tuple per series, never per row
                tags_t = tuple(sorted(base_tags.items()))
                for row in series["values"]:
                    fields = _row_fields(cols, row[1:])
                    if fields:
                        points.append((target.name, tags_t, row[0], fields))
                continue
            field_idx = [i for i, c in enumerate(cols) if c not in tag_cols]
            for row in series["values"]:
                vals = row[1:]
                fields = _row_fields([cols[i] for i in field_idx],
                                     [vals[i] for i in field_idx])
                if fields:
                    tags = dict(base_tags)
                    for i, c in tag_idx:
                        if vals[i] is not None:
                            tags[c] = str(vals[i])
                    points.append((target.name,
                                   tuple(sorted(tags.items())),
                                   row[0], fields))
        if not points:
            return 0
        if self.router is not None:
            # route INTO results by shard-group owner like any other write:
            # result rows written only-locally would duplicate across nodes
            # (every copy double-counts in merged scans)
            from opengemini_tpu.parallel.cluster import RemoteScanError

            try:
                return self.router.routed_write(
                    tgt_db, target.rp or None, points)
            except (OSError, RemoteScanError) as e:
                raise QueryError(f"INTO forward failed: {e}") from e
        return self.engine.write_rows(tgt_db, points, rp=target.rp or None)


    def _select_from_subquery(self, stmt, src: ast.SubQuery, db: str,
                              now_ns: int, trace=tracing.NOOP) -> list[dict]:
        """FROM (SELECT ...): the inner result materializes into a
        throw-away engine (tags stay tags, columns become fields), then the
        outer statement runs against it. Reference: subquery builders in
        engine/executor/select.go; correctness-first materialization here,
        streaming later."""
        import copy  # noqa: F811 — local import for the materializer
        import tempfile

        from opengemini_tpu.storage.engine import Engine as _Engine

        inner = src.stmt
        inner_has_wild = False
        if isinstance(inner, ast.SelectStatement):
            inner_has_wild = any(
                isinstance(_strip_expr(f.expr), ast.Wildcard)
                or _call_wildcard_inner(_strip_expr(f.expr)) is not None
                for f in inner.fields
            )
            if _classify_select(inner) == "raw" and not (
                inner.group_by_tags or inner.group_by_all_tags
            ):
                # influx propagates series tags through subqueries: a raw
                # inner select must emit per-series output, never one
                # merged series
                inner = copy.copy(inner)
                inner.group_by_all_tags = True
            elif (
                stmt.group_by_tags
                and not inner.group_by_tags
                and not inner.group_by_all_tags
            ):
                # influx subqueries INHERIT the outer GROUP BY dimensions:
                # an inner call (top/agg) computes per outer group and its
                # output series carry those tags
                # (TestServer_SubQuery_Top_Min#0)
                inner = copy.copy(inner)
                inner.group_by_tags = list(stmt.group_by_tags)
        # push the outer time range into the inner select so the inner scan
        # (and the materialization below) covers only the needed window
        if isinstance(inner, ast.UnionStatement):
            pass  # union bodies materialize whole (no time pushdown yet)
        else:
            try:
                sc_outer = cond.split(stmt.condition, set(), now_ns)
                if sc_outer.tmin != cond.MIN_TIME or sc_outer.tmax != cond.MAX_TIME:
                    bound = ast.BinaryExpr(
                        "AND",
                        ast.BinaryExpr(">=", ast.VarRef("time"),
                                       ast.IntegerLiteral(sc_outer.tmin)),
                        ast.BinaryExpr("<", ast.VarRef("time"),
                                       ast.IntegerLiteral(sc_outer.tmax)),
                    )
                    inner = copy.copy(inner)
                    inner.condition = (
                        bound if inner.condition is None
                        else ast.BinaryExpr("AND", inner.condition, bound)
                    )
            except cond.ConditionError:
                pass  # un-splittable outer condition: no pushdown
        chunk_plan = None
        if (
            not isinstance(inner, ast.UnionStatement)
            and _subquery_chunk_safe(inner)
            # a bare outer projection takes the _project_* fast paths on
            # the full inner result — chunking would bypass them
            and not (stmt.condition is None and not stmt.group_by_tags
                     and not stmt.group_by_all_tags
                     and not stmt.group_by_time
                     and all(isinstance(_strip_expr(f.expr),
                                        (ast.VarRef, ast.Wildcard))
                             for f in stmt.fields))
        ):
            chunk_plan = self._plan_subquery_chunks(inner, db, now_ns)
        if chunk_plan is not None:
            return self._run_subquery_chunked(
                stmt, src, inner, inner_has_wild, chunk_plan, db, now_ns,
                trace)
        with trace.span("subquery"):
            if isinstance(inner, ast.UnionStatement):
                from opengemini_tpu.query import join as joinmod

                inner_res = joinmod.execute_union(self, inner, db, now_ns)
                # a raw projection over a union must NOT round-trip through
                # the point materializer: union rows legitimately repeat
                # (series, time) pairs, which the engine would LWW-dedup
                proj = self._project_union(stmt, inner_res)
                if proj is not None:
                    return proj
            else:
                inner_res = self._select(inner, db, now_ns, trace)
        series_list = inner_res.get("series", [])
        if (
            not isinstance(inner, ast.UnionStatement)
            and len(series_list) == 1
            and not series_list[0].get("tags")
        ):
            # single untagged inner series + bare outer projection: project
            # directly so all-null computed rows survive (the materializer
            # cannot represent a row whose only field is null —
            # TestServer_Query_SubqueryMath#0)
            proj = self._project_union(stmt, inner_res)
            if proj is not None:
                return proj
        if (
            not isinstance(inner, ast.UnionStatement)
            and isinstance(src.stmt, ast.SelectStatement)
            and src.stmt.group_by_tags
        ):
            # dimensioned inner (explicit GROUP BY tags): a bare outer
            # projection flattens series into one with the dims as columns,
            # null rows preserved (TestServer_Query_Sliding_Window #8/#9)
            proj = self._project_dimensioned(
                stmt, series_list, list(src.stmt.group_by_tags),
                _inner_source_name(inner))
            if proj is not None:
                return proj
        mst_name = _inner_source_name(inner)
        with tempfile.TemporaryDirectory(prefix="ogtpu-sub-") as tmp:
            tmp_engine = _Engine(tmp, sync_wal=False)
            try:
                tmp_engine.create_database("sub")
                _materialize_into(tmp_engine, mst_name, series_list)
                return self._run_outer_on(
                    tmp_engine, stmt, src, inner_has_wild, mst_name,
                    now_ns, trace)
            finally:
                tmp_engine.close()

    def _run_outer_on(self, tmp_engine, stmt, src, inner_has_wild,
                      mst_name, now_ns, trace):
        """Run the outer statement against the spill engine holding the
        materialized inner rows."""
        import copy

        outer = copy.copy(stmt)
        outer.sources = [ast.Measurement(name=mst_name)]
        outer.into = None  # INTO applies once, in the caller
        # the source is now a materialized measurement: it must not
        # re-resolve as a CTE name against the throw-away engine
        outer.ctes = None
        # influx wildcard-over-subquery expands to the inner's
        # ORIGINAL output columns: explicit inner fields stay
        # fields-only; an inner wildcard (bare or inside a call)
        # lets the outer wildcard inline propagated tags. Inner
        # EXPLICIT GROUP BY tags are output dimensions — the outer
        # wildcard includes them as columns
        # (TestServer_Query_SubqueryForLogicalOptimize#5)
        outer._from_subquery = not inner_has_wild
        if isinstance(src.stmt, ast.SelectStatement):
            outer._subquery_dims = list(src.stmt.group_by_tags)
        # a flattenable plain-projection inner (bare field renames,
        # no grouping) donates its explicit time bounds to the
        # outer statement — the reference's subquery flattening
        # makes the outer render window start at the inner tmin
        # (SubqueryForLogicalOptimize#2); non-flattenable inners
        # (computed projections) keep epoch-0 rendering (#4)
        if (
            isinstance(src.stmt, ast.SelectStatement)
            and src.stmt.fields
            and all(isinstance(_strip_expr(f.expr), ast.VarRef)
                    for f in src.stmt.fields)
            and not src.stmt.group_by_tags
            and not src.stmt.group_by_all_tags
            and src.stmt.group_by_time is None
            and src.stmt.condition is not None
        ):
            try:
                sc_in = cond.split(src.stmt.condition, set(), now_ns)
                sc_out = cond.split(stmt.condition, set(), now_ns)
                if (
                    sc_out.tmin == cond.MIN_TIME
                    and sc_out.tmax == cond.MAX_TIME
                    and (sc_in.tmin != cond.MIN_TIME
                         or sc_in.tmax != cond.MAX_TIME)
                ):
                    bound = ast.BinaryExpr(
                        "AND",
                        ast.BinaryExpr(
                            ">=", ast.VarRef("time"),
                            ast.IntegerLiteral(sc_in.tmin)),
                        ast.BinaryExpr(
                            "<", ast.VarRef("time"),
                            ast.IntegerLiteral(sc_in.tmax)),
                    )
                    outer.condition = (
                        bound if outer.condition is None
                        else ast.BinaryExpr(
                            "AND", outer.condition, bound)
                    )
            except cond.ConditionError:
                pass
        from opengemini_tpu.query.executor import Executor

        sub_ex = Executor(tmp_engine, users=self.users)
        res = sub_ex._select(outer, "sub", now_ns, trace)
        return res.get("series", [])

    def _plan_subquery_chunks(self, inner, db: str, now_ns: int):
        """[(lo, hi)] window-aligned chunk ranges when the estimated
        inner scan is big enough to bound, else None. The estimate comes
        from chunk metadata (same planner as the sliced scan)."""
        try:
            tag_keys = set()
            sc = cond.split(inner.condition, tag_keys, now_ns)
        except cond.ConditionError:
            return None
        tmin, tmax = sc.tmin, sc.tmax
        if tmin == cond.MIN_TIME or tmax == cond.MAX_TIME:
            return None  # unbounded range: nothing to split against
        total = 0
        for msrc in inner.sources:
            sdb = msrc.database or db
            shards = self.engine.shards_for_range(
                sdb, msrc.rp or None, tmin, tmax)
            for sh in shards:
                approx = getattr(sh, "approx_rows", None)
                if approx is None:
                    # remote shard: no cheap estimate — chunking is
                    # bypassed and only the row cap bounds the
                    # materialization. Record it so an OOM-adjacent
                    # incident is diagnosable.
                    STATS.incr("executor", "subquery_chunking_bypassed")
                    return None
                r, _c = approx(msrc.name, tmin, tmax)
                total += r
        if total < SUBQUERY_CHUNK_ROWS:
            return None
        n_chunks = min(-(-total // SUBQUERY_CHUNK_TARGET), 256)
        if n_chunks < 2:
            return None
        gt = inner.group_by_time
        if gt is not None:
            aligned = int(winmod.window_start(
                tmin, gt.every_ns, gt.offset_ns))
            W = winmod.num_windows(tmin, tmax, gt.every_ns, gt.offset_ns)
            per = -(-W // n_chunks)
            if per < 1 or per >= W:
                return None
            bounds = [aligned + i * per * gt.every_ns
                      for i in range(1, n_chunks)]
        else:
            span = tmax - tmin
            bounds = [tmin + span * i // n_chunks
                      for i in range(1, n_chunks)]
        edges = [tmin] + [b for b in bounds if tmin < b < tmax] + [tmax]
        return [(edges[i], edges[i + 1]) for i in range(len(edges) - 1)
                if edges[i] < edges[i + 1]]

    def _run_subquery_chunked(self, stmt, src, inner, inner_has_wild,
                              chunk_plan, db, now_ns, trace):
        """Evaluate the inner select chunk-by-chunk into one spill
        engine, then run the outer once. Peak memory is one chunk's
        JSON intermediate; the spill engine flushes to TSF as it grows
        (reference: streaming subquery_transform.go)."""
        import copy
        import tempfile

        from opengemini_tpu.storage.engine import Engine as _Engine

        mst_name = _inner_source_name(inner)
        with tempfile.TemporaryDirectory(prefix="ogtpu-sub-") as tmp:
            tmp_engine = _Engine(tmp, sync_wal=False)
            try:
                tmp_engine.create_database("sub")
                with trace.span("subquery(chunked)") as sp:
                    sp.add_field("chunks", len(chunk_plan))
                    spent = 0
                    for lo, hi in chunk_plan:
                        TRACKER.check()
                        part = copy.copy(inner)
                        bound = ast.BinaryExpr(
                            "AND",
                            ast.BinaryExpr(">=", ast.VarRef("time"),
                                           ast.IntegerLiteral(lo)),
                            ast.BinaryExpr("<", ast.VarRef("time"),
                                           ast.IntegerLiteral(hi)),
                        )
                        part.condition = (
                            bound if part.condition is None
                            else ast.BinaryExpr(
                                "AND", part.condition, bound))
                        part_res = self._select(part, db, now_ns, trace)
                        spent = _materialize_into(
                            tmp_engine, mst_name,
                            part_res.get("series", []), spent)
                return self._run_outer_on(
                    tmp_engine, stmt, src, inner_has_wild, mst_name,
                    now_ns, trace)
            finally:
                tmp_engine.close()


