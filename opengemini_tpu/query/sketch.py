"""Approximate percentile from chunk histogram sketches.

Reference: OGSketch quantile sketches (engine/executor/ogsketch.go) — but
persisted per chunk in the TSF pre-agg metadata, so
`percentile_approx(field, q)` answers WITHOUT decoding data blocks:
chunk histograms re-bin into one global histogram (proportional count
distribution), memtable rows and histogram-less chunks bin directly.
Error bound: directly-binned values are within one GLOBAL bin width
(range/256); mass re-binned from a chunk histogram is within one CHUNK
bin width ((chunk_max - chunk_min)/32), which dominates when a chunk
spans most of the value range.
"""

from __future__ import annotations

import math

import numpy as np

GLOBAL_BINS = 256


class HistSketch:
    """Mergeable equi-width histogram over a fixed global [lo, hi]."""

    def __init__(self, lo: float, hi: float, bins: int = GLOBAL_BINS):
        self.lo = lo
        self.hi = max(hi, lo)
        self.bins = bins
        self.counts = np.zeros(bins, dtype=np.float64)
        self.total = 0.0

    def _width(self) -> float:
        return (self.hi - self.lo) / self.bins if self.hi > self.lo else 1.0

    def add_chunk_hist(self, vmin: float, vmax: float, hist: list) -> None:
        """Re-bin a chunk's histogram: each source bin's count spreads
        proportionally over the global bins it overlaps."""
        src = np.asarray(hist, dtype=np.float64)
        n_src = len(src)
        src_w = (vmax - vmin) / n_src if vmax > vmin else 0.0
        if src_w == 0.0:
            self.add_values(np.full(int(src.sum()), vmin))
            return
        w = self._width()
        for i, c in enumerate(src):
            if c == 0:
                continue
            a = vmin + i * src_w
            b = a + src_w
            g0 = int(np.clip((a - self.lo) / w, 0, self.bins - 1))
            g1 = int(np.clip((b - self.lo) / w - 1e-12, 0, self.bins - 1))
            if g1 <= g0:
                self.counts[g0] += c
            else:
                # proportional split over covered global bins
                for g in range(g0, g1 + 1):
                    lo_g = self.lo + g * w
                    hi_g = lo_g + w
                    overlap = max(0.0, min(b, hi_g) - max(a, lo_g))
                    self.counts[g] += c * overlap / src_w
        self.total += float(src.sum())

    def add_values(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        v = np.asarray(values, dtype=np.float64)
        idx = np.clip(
            ((v - self.lo) / self._width()).astype(np.int64), 0, self.bins - 1
        )
        np.add.at(self.counts, idx, 1.0)
        self.total += len(v)

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile, interpolated inside the winning bin."""
        if self.total <= 0:
            return None
        rank = max(np.ceil(q / 100.0 * self.total), 1.0)
        cum = np.cumsum(self.counts)
        g = int(np.searchsorted(cum, rank - 1e-9))
        g = min(g, self.bins - 1)
        prev = cum[g - 1] if g > 0 else 0.0
        in_bin = self.counts[g]
        frac = (rank - prev) / in_bin if in_bin > 0 else 0.5
        w = self._width()
        return float(self.lo + g * w + frac * w)


# -- OGSketch: centroid (t-digest-family) quantile sketch --------------------


class OGSketch:
    """Centroid quantile sketch — the role of the reference's OGSketch
    (engine/executor/ogsketch.go: bounded ClusterSet of (mean, weight)
    centroids, quantiles interpolated over half-weight accumulative sums).

    TPU-first shape: centroids live as parallel numpy arrays (means,
    weights) and inserts are BATCH merges — buffer values, then one
    sort + vectorized cumulative-weight compression pass, never a
    per-point tree walk. Mergeable across nodes (concatenate centroid
    sets, recompress): a peer ships O(compression) floats per segment
    regardless of row count, which is what makes huge-cardinality
    quantiles cheap in a cluster."""

    def __init__(self, compression: int = 100):
        self.compression = max(int(compression), 4)
        self.means = np.empty(0, np.float64)
        self.weights = np.empty(0, np.float64)
        self._buf: list[np.ndarray] = []
        self._buf_n = 0
        self.min = math.inf
        self.max = -math.inf

    # -- build ----------------------------------------------------------

    def insert(self, values) -> None:
        v = np.asarray(values, np.float64).ravel()
        v = v[np.isfinite(v)]
        if not len(v):
            return
        self.min = min(self.min, float(v.min()))
        self.max = max(self.max, float(v.max()))
        self._buf.append(v)
        self._buf_n += len(v)
        if self._buf_n >= 8 * self.compression:
            self._compress()

    def merge(self, other: "OGSketch") -> None:
        """Fold another sketch in as WEIGHTED centroids (lossless relative
        to both sketches' own precision) and recompress."""
        other._compress()
        self._compress()
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        if len(other.means):
            self.means, self.weights = _tdigest_compress(
                np.concatenate([self.means, other.means]),
                np.concatenate([self.weights, other.weights]),
                self.compression,
            )

    def _compress(self) -> None:
        if not self._buf:
            return
        bufv = np.concatenate(self._buf)
        self._buf, self._buf_n = [], 0
        m = np.concatenate([self.means, bufv])
        w = np.concatenate([self.weights,
                            np.ones(len(bufv), np.float64)])
        self.means, self.weights = _tdigest_compress(m, w, self.compression)

    # -- query ----------------------------------------------------------

    @property
    def n(self) -> float:
        self._compress()
        return float(self.weights.sum())

    def quantile(self, q: float) -> float:
        """Value at quantile q in [0, 1]: interpolation over half-weight
        accumulative sums (the reference's updateAccumulativeSum +
        Quantile walk, vectorized via searchsorted)."""
        self._compress()
        if not len(self.means):
            return math.nan
        q = min(max(q, 0.0), 1.0)
        w = self.weights
        total = w.sum()
        # centroid "positions": cumulative weight at centroid midpoints
        cum = np.cumsum(w) - w / 2
        target = q * total
        if target <= cum[0]:
            return float(self.min if total > 1 else self.means[0])
        if target >= cum[-1]:
            return float(self.max if total > 1 else self.means[-1])
        i = int(np.searchsorted(cum, target))
        lo, hi = cum[i - 1], cum[i]
        frac = (target - lo) / max(hi - lo, 1e-12)
        return float(self.means[i - 1]
                     + (self.means[i] - self.means[i - 1]) * frac)

    # -- wire ------------------------------------------------------------

    def serialize(self) -> bytes:
        self._compress()
        head = np.asarray(
            [self.compression, len(self.means), self.min, self.max],
            np.float64)
        return b"".join(a.tobytes() for a in (head, self.means, self.weights))

    @classmethod
    def deserialize(cls, raw: bytes) -> "OGSketch":
        if len(raw) < 32:
            raise ValueError("truncated OGSketch payload")
        head = np.frombuffer(raw[:32], np.float64)
        comp, k = int(head[0]), int(head[1])
        if len(raw) != 32 + 16 * k:
            raise ValueError(
                f"OGSketch payload length {len(raw)} != {32 + 16 * k}")
        s = cls(comp)
        s.min, s.max = float(head[2]), float(head[3])
        s.means = np.frombuffer(raw[32:32 + 8 * k], np.float64).copy()
        s.weights = np.frombuffer(raw[32 + 8 * k:32 + 16 * k],
                                  np.float64).copy()
        return s


def _tdigest_compress(means: np.ndarray, weights: np.ndarray,
                      compression: int):
    """Merge (mean, weight) centroids down to <= ~compression clusters
    with the k1 (arcsine) scale function: tight clusters at the tails,
    coarse in the middle — the error profile quantile sketches need.
    Fully vectorized: one sort, one k-scale bucket assignment over the
    cumulative weights, one reduceat per output array (a per-element
    greedy loop was ~100x slower than np.quantile at 1M rows)."""
    order = np.argsort(means, kind="stable")
    m, w = means[order], weights[order]
    total = w.sum()
    if total <= 0:
        return np.empty(0, np.float64), np.empty(0, np.float64)
    q_left = (np.cumsum(w) - w) / total
    k = np.floor(compression * (
        np.arcsin(np.clip(2 * q_left - 1, -1.0, 1.0)) / np.pi + 0.5))
    starts = np.flatnonzero(np.concatenate([[True], k[1:] != k[:-1]]))
    out_w = np.add.reduceat(w, starts)
    out_m = np.add.reduceat(m * w, starts) / out_w
    return out_m, out_w


# -- rollup percentile cell (exact-until-K, then t-digest) -------------------


class RollupSketch:
    """Per-(series, window, field) percentile cell persisted by the
    materialized-rollup subsystem (storage/rollup.py).

    Two modes:
      exact  — keeps the raw values while there are at most `exact_limit`
               of them; `percentile()` reproduces influx's nearest-rank
               semantics bit-for-bit, so a rollup-spliced percentile
               equals the raw-scan answer (the splice fuzz asserts this).
      digest — past the limit the cell degrades to an OGSketch (bounded
               memory regardless of row count); `percentile()` is then
               the t-digest interpolated quantile (documented approximate,
               same trade the reference's downsampled quantiles make).

    Merging (across series of one GROUP BY key, and across sub-windows
    when the query's time(T) is a multiple of the rollup interval)
    preserves exactness while the combined cell fits the limit."""

    def __init__(self, exact_limit: int = 512, compression: int = 100):
        self.exact_limit = int(exact_limit)
        self.compression = int(compression)
        self._vals: list[np.ndarray] = []
        self._n = 0
        self._digest: OGSketch | None = None

    @property
    def exact(self) -> bool:
        return self._digest is None

    @property
    def n(self) -> float:
        if self._digest is not None:
            return self._digest.n
        return float(self._n)

    def add_values(self, values) -> None:
        v = np.asarray(values, np.float64).ravel()
        if not len(v):
            return
        if self._digest is not None:
            self._digest.insert(v)
            return
        self._vals.append(v)
        self._n += len(v)
        if self._n > self.exact_limit:
            self._degrade()

    def merge(self, other: "RollupSketch") -> None:
        if other._digest is None:
            for v in other._vals:
                self.add_values(v)
            return
        self._degrade()
        self._digest.merge(other._digest)

    def _degrade(self) -> None:
        if self._digest is not None:
            return
        self._digest = OGSketch(self.compression)
        for v in self._vals:
            self._digest.insert(v)
        self._vals, self._n = [], 0

    def percentile(self, q_pct: float) -> float | None:
        """Influx nearest-rank percentile in exact mode (rank
        floor(n*q/100+0.5)-1, None when that rank is out of range — the
        executor's 'no row for this window' rule); t-digest quantile in
        digest mode."""
        if self._digest is not None:
            if self._digest.n <= 0:
                return None
            return self._digest.quantile(q_pct / 100.0)
        if self._n == 0:
            return None
        allv = np.sort(np.concatenate(self._vals), kind="stable")
        i = int(math.floor(len(allv) * q_pct / 100.0 + 0.5)) - 1
        if i < 0 or i >= len(allv):
            return None
        return float(allv[i])

    # -- wire ------------------------------------------------------------

    def serialize(self) -> bytes:
        if self._digest is not None:
            return b"\x01" + self._digest.serialize()
        head = np.asarray([self.exact_limit, self.compression], np.int64)
        body = (np.concatenate(self._vals) if self._vals
                else np.empty(0, np.float64))
        return b"\x00" + head.tobytes() + body.tobytes()

    @classmethod
    def deserialize(cls, raw: bytes) -> "RollupSketch":
        if not raw:
            raise ValueError("empty RollupSketch payload")
        mode, rest = raw[0], raw[1:]
        if mode == 1:
            s = cls()
            s._digest = OGSketch.deserialize(rest)
            s.compression = s._digest.compression
            return s
        if mode != 0 or len(rest) < 16 or (len(rest) - 16) % 8:
            raise ValueError("bad RollupSketch payload")
        head = np.frombuffer(rest[:16], np.int64)
        s = cls(int(head[0]), int(head[1]))
        vals = np.frombuffer(rest[16:], np.float64).copy()
        if len(vals):
            s._vals = [vals]
            s._n = len(vals)
        return s


# -- count-min sketch --------------------------------------------------------


class CountMinSketch:
    """Approximate frequency counts in sublinear space (reference:
    engine/executor/count_min_sketch.go): a (depth x width) counter
    matrix, point estimate = min over rows. Adds are VECTORIZED — a whole
    batch of items hashes in one numpy pass per row (no per-item loop),
    matching how the engine feeds columnar batches."""

    def __init__(self, width: int = 2048, depth: int = 4, seed: int = 7):
        self.width = int(width)
        self.depth = int(depth)
        self.seed = int(seed)
        self.counts = np.zeros((depth, self.width), np.int64)
        rng = np.random.default_rng(seed)
        self._row_seed = rng.integers(0, 2**63, size=depth,
                                      dtype=np.int64).astype(np.uint64)

    def _rows(self, keys: np.ndarray) -> np.ndarray:
        """(depth, n) column indices: splitmix64 finalizer with a per-row
        seed xor. Plain multiply-shift fails here — float64 bit patterns
        of small integers have 52 trailing zero bits, leaving the
        product's top bits with almost no entropy (measured: every small
        key collided with the heavy hitter)."""
        k = keys.astype(np.uint64)[None, :] ^ self._row_seed[:, None]
        with np.errstate(over="ignore"):
            k ^= k >> np.uint64(30)
            k *= np.uint64(0xBF58476D1CE4E5B9)
            k ^= k >> np.uint64(27)
            k *= np.uint64(0x94D049BB133111EB)
            k ^= k >> np.uint64(31)
        return (k % np.uint64(self.width)).astype(np.int64)

    @staticmethod
    def _keys_of(items) -> np.ndarray:
        arr = np.asarray(items)
        if arr.dtype.kind in "iuf":
            # ONE numeric representation: 7 and 7.0 (and -0.0 and 0.0)
            # must collide, or a float producer + int consumer
            # underestimates (the one thing count-min must never do).
            # float64 is exact for ints < 2^53; +0.0 canonicalizes -0.0.
            return (arr.astype(np.float64) + 0.0).view(np.int64)
        # strings/objects: stable 64-bit digests
        import hashlib

        return np.asarray([
            int.from_bytes(
                hashlib.blake2b(str(x).encode(), digest_size=8).digest(),
                "little", signed=True)
            for x in arr
        ], np.int64)

    def add(self, items, counts=1) -> None:
        keys = self._keys_of(items)
        if not len(keys):
            return
        c = np.broadcast_to(np.asarray(counts, np.int64), keys.shape)
        idx = self._rows(keys)
        for d in range(self.depth):
            np.add.at(self.counts[d], idx[d], c)

    def count(self, item) -> int:
        keys = self._keys_of([item])
        idx = self._rows(keys)
        return int(min(self.counts[d, idx[d, 0]] for d in range(self.depth)))

    def merge(self, other: "CountMinSketch") -> None:
        if (other.width != self.width or other.depth != self.depth
                or other.seed != self.seed):
            raise ValueError("count-min parameters differ")
        self.counts += other.counts

    def serialize(self) -> bytes:
        head = np.asarray([self.width, self.depth, self.seed], np.int64)
        return head.tobytes() + self.counts.tobytes()

    @classmethod
    def deserialize(cls, raw: bytes) -> "CountMinSketch":
        width, depth, seed = np.frombuffer(raw[:24], np.int64)
        s = cls(int(width), int(depth), int(seed))
        body = np.frombuffer(raw[24:], np.int64)
        if len(body) != s.depth * s.width:
            raise ValueError("truncated count-min payload")
        s.counts = body.reshape(s.depth, s.width).copy()
        return s
