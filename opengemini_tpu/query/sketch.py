"""Approximate percentile from chunk histogram sketches.

Reference: OGSketch quantile sketches (engine/executor/ogsketch.go) — but
persisted per chunk in the TSF pre-agg metadata, so
`percentile_approx(field, q)` answers WITHOUT decoding data blocks:
chunk histograms re-bin into one global histogram (proportional count
distribution), memtable rows and histogram-less chunks bin directly.
Error bound: directly-binned values are within one GLOBAL bin width
(range/256); mass re-binned from a chunk histogram is within one CHUNK
bin width ((chunk_max - chunk_min)/32), which dominates when a chunk
spans most of the value range.
"""

from __future__ import annotations

import numpy as np

GLOBAL_BINS = 256


class HistSketch:
    """Mergeable equi-width histogram over a fixed global [lo, hi]."""

    def __init__(self, lo: float, hi: float, bins: int = GLOBAL_BINS):
        self.lo = lo
        self.hi = max(hi, lo)
        self.bins = bins
        self.counts = np.zeros(bins, dtype=np.float64)
        self.total = 0.0

    def _width(self) -> float:
        return (self.hi - self.lo) / self.bins if self.hi > self.lo else 1.0

    def add_chunk_hist(self, vmin: float, vmax: float, hist: list) -> None:
        """Re-bin a chunk's histogram: each source bin's count spreads
        proportionally over the global bins it overlaps."""
        src = np.asarray(hist, dtype=np.float64)
        n_src = len(src)
        src_w = (vmax - vmin) / n_src if vmax > vmin else 0.0
        if src_w == 0.0:
            self.add_values(np.full(int(src.sum()), vmin))
            return
        w = self._width()
        for i, c in enumerate(src):
            if c == 0:
                continue
            a = vmin + i * src_w
            b = a + src_w
            g0 = int(np.clip((a - self.lo) / w, 0, self.bins - 1))
            g1 = int(np.clip((b - self.lo) / w - 1e-12, 0, self.bins - 1))
            if g1 <= g0:
                self.counts[g0] += c
            else:
                # proportional split over covered global bins
                for g in range(g0, g1 + 1):
                    lo_g = self.lo + g * w
                    hi_g = lo_g + w
                    overlap = max(0.0, min(b, hi_g) - max(a, lo_g))
                    self.counts[g] += c * overlap / src_w
        self.total += float(src.sum())

    def add_values(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        v = np.asarray(values, dtype=np.float64)
        idx = np.clip(
            ((v - self.lo) / self._width()).astype(np.int64), 0, self.bins - 1
        )
        np.add.at(self.counts, idx, 1.0)
        self.total += len(v)

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile, interpolated inside the winning bin."""
        if self.total <= 0:
            return None
        rank = max(np.ceil(q / 100.0 * self.total), 1.0)
        cum = np.cumsum(self.counts)
        g = int(np.searchsorted(cum, rank - 1e-9))
        g = min(g, self.bins - 1)
        prev = cum[g - 1] if g > 0 else 0.0
        in_bin = self.counts[g]
        frac = (rank - prev) / in_bin if in_bin > 0 else 0.5
        w = self._width()
        return float(self.lo + g * w + frac * w)
