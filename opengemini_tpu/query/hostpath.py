"""Host-evaluated select paths (Executor mixin): raw projection,
transform/multi-row functions, selector+aux columns, top/bottom
companions, percentile_approx sketches. Split out of
query/executor.py (reference: the sql-side transform processors,
SURVEY.md section 2.3).
"""

from __future__ import annotations

import math
import os
import re
import threading as _threading
import time as _time

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.ops import aggregates as aggmod
from opengemini_tpu.parallel import cluster as pcluster
from opengemini_tpu.ops import window as winmod
from opengemini_tpu.query import condition as cond
from opengemini_tpu.query import functions as fnmod
from opengemini_tpu.record import FieldType, FieldTypeConflict
from opengemini_tpu.sql import ast
from opengemini_tpu.meta.users import AuthError as _AuthError
from opengemini_tpu.storage.engine import WriteError
from opengemini_tpu.storage.tsf import CorruptFile
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.querytracker import GLOBAL as TRACKER, QueryKilled
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.sql.parser import parse

from opengemini_tpu.query.qhelpers import *  # noqa: F401,F403
from opengemini_tpu.query.qhelpers import (  # noqa: F401
    NS, MAX_SELECT_BUCKETS, QueryError,
)


def _is_time_field(f) -> bool:
    """Explicit `SELECT time, ...` — always column 0, never a real
    projection/companion (the one definition all three call sites
    share)."""
    e = _strip_expr(f.expr)
    return isinstance(e, ast.VarRef) and e.name.lower() == "time"


def _eval_host_output(e, bt, col_maps, call_plan_idx):
    """Evaluate a call-math output expression at one window: leaves are
    host-call plan columns (absent -> null, which poisons the expression
    like influx), numeric literals, and +-*/% with null-on-zero-divide."""
    e = _strip_expr(e)
    if isinstance(e, ast.Call):
        entry = col_maps[call_plan_idx[id(e)]].get(bt)
        if entry is None:
            return None, False
        return entry[0], True
    if isinstance(e, (ast.IntegerLiteral, ast.NumberLiteral)):
        return e.val, False
    if isinstance(e, ast.DurationLiteral):
        return e.val_ns, False
    if isinstance(e, ast.UnaryExpr) and e.op == "-":
        v, p = _eval_host_output(e.expr, bt, col_maps, call_plan_idx)
        return (None if v is None else -v), p
    if isinstance(e, ast.BinaryExpr):
        lv, lp = _eval_host_output(e.lhs, bt, col_maps, call_plan_idx)
        rv, rp = _eval_host_output(e.rhs, bt, col_maps, call_plan_idx)
        present = lp or rp
        if lv is None or rv is None:
            return None, present
        try:
            if e.op == "+":
                return lv + rv, present
            if e.op == "-":
                return lv - rv, present
            if e.op == "*":
                return lv * rv, present
            if e.op == "/":
                return (None if rv == 0 else lv / rv), present
            if e.op == "%":
                return (None if rv == 0 else lv % rv), present
        except TypeError:
            return None, present
    raise QueryError(
        "unsupported expression in host-path SELECT (functions, numbers "
        "and +-*/% only)")


class HostPathMixin:
    def _select_percentile_approx(self, stmt, db, rp, mst, now_ns, call) -> list[dict]:
        """percentile_approx(field, q): served from the per-chunk histogram
        sketches in TSF pre-agg metadata — covered chunks contribute their
        histograms with NO data decode (reference: OGSketch, persisted).
        Memtable rows, partially-covered and histogram-less chunks decode
        and bin exactly. Error: within one chunk-histogram bin width
        (chunk_range/32) for sketch-served mass, one global bin width
        (range/256) for directly-binned rows."""
        from opengemini_tpu.query.sketch import HistSketch

        if stmt.group_by_time is not None:
            raise QueryError("percentile_approx() does not support GROUP BY time yet")
        if len(call.args) != 2:
            raise QueryError("percentile_approx() takes (field, q)")
        fld = _strip_expr(call.args[0])
        if not isinstance(fld, ast.VarRef):
            raise QueryError("percentile_approx() field must be a field name")
        qv = float(_call_param_value(call.args[1]))
        if not (0 <= qv <= 100):
            raise QueryError("percentile_approx() q must be between 0 and 100")
        fname = fld.name
        ctx = self._scan_context(stmt, db, rp, mst, now_ns)
        if ctx is None:
            return []
        if ctx.schema.get(fname) not in (FieldType.FLOAT, FieldType.INT):
            raise QueryError("percentile_approx() requires a numeric field")
        if ctx.sc.has_row_filter:
            raise QueryError("percentile_approx() does not support field filters")
        tmin, tmax = ctx.tmin, ctx.tmax

        # pass 1: per group, chunk hists (zero decode) or decoded values;
        # any dedup risk (overlapping chunks / memtable rows) falls the
        # whole series back to the merged read_series view
        plans: dict[int, list] = {}  # gid -> [(kind, payload)]
        bounds: dict[int, list] = {}

        def _add_vals(gid, vals):
            vals = vals[np.isfinite(vals)]  # nan/inf points never bin
            if not len(vals):
                return
            plans.setdefault(gid, []).append(("values", vals))
            b = bounds.setdefault(gid, [np.inf, -np.inf])
            b[0] = min(b[0], float(vals.min()))
            b[1] = max(b[1], float(vals.max()))

        for sh, sid, gid in ctx.scan_plan:
            TRACKER.check()  # KILL QUERY cancellation point
            needs_merge, srcs = _series_needs_merged_decode(sh, mst, sid, tmin, tmax)
            if needs_merge:
                rec = sh.read_series(mst, sid, tmin, tmax, fields=[fname])
                col = rec.columns.get(fname)
                if col is not None and len(rec):
                    _add_vals(gid, col.values[col.valid].astype(np.float64))
                continue
            for r, c in srcs:
                loc = c.cols.get(fname)
                pre = loc["pre"] if loc else None
                covered = tmin <= c.tmin and c.tmax < tmax
                if covered and pre is not None and pre.count and pre.hist is not None:
                    plans.setdefault(gid, []).append(("hist", pre))
                    b = bounds.setdefault(gid, [np.inf, -np.inf])
                    b[0] = min(b[0], pre.vmin)
                    b[1] = max(b[1], pre.vmax)
                else:
                    try:
                        rec = r.read_chunk(
                            mst, c, [fname]).slice_time(tmin, tmax)
                    except CorruptFile as e:
                        # quarantine through the owning shard (raises
                        # FileQuarantined) — see executor._scan_preagg
                        handler = getattr(sh, "note_corrupt", None)
                        if handler is not None:
                            handler(e)
                        raise
                    col = rec.columns.get(fname)
                    if col is not None and len(rec):
                        _add_vals(gid, col.values[col.valid].astype(np.float64))

        name = stmt.fields[0].alias or "percentile_approx"
        out_series = []
        order = sorted(range(len(ctx.group_keys)), key=lambda g: ctx.group_keys[g])
        t0 = ctx.aligned if ctx.aligned else 0
        for g in order:
            entries = plans.get(g)
            if not entries:
                continue
            lo, hi = bounds[g]
            sk = HistSketch(lo, hi)
            for kind, payload in entries:
                if kind == "hist":
                    sk.add_chunk_hist(payload.vmin, payload.vmax, payload.hist)
                else:
                    sk.add_values(payload)
            v = sk.percentile(qv)
            if v is None:
                continue
            rows = [[t0, v]]
            if not stmt.ascending:
                rows.reverse()
            rows = rows[stmt.offset :]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {"name": mst, "columns": ["time", name], "values": rows}
            if ctx.group_tags:
                series["tags"] = dict(zip(ctx.group_tags, ctx.group_keys[g]))
            out_series.append(series)
        return out_series

    # -- selector + auxiliary columns (host path) ----------------------------


    def _select_selector_aux(self, stmt, db, rp, mst, now_ns, plan) -> list[dict]:
        """One selector call + bare/arithmetic auxiliary columns: the
        selector picks rows, aux columns are read from the selected rows
        (reference: aux fields in the cursor iterators, call iterator
        top/bottom transforms).  time = the selected point's timestamp,
        except 1-row selectors under GROUP BY time, which emit the window
        start (matching the reference's output tables)."""
        sel_call, aux_fields = plan
        sel_name = sel_call.name
        sel_field = _strip_expr(sel_call.args[0]).name
        n_rows = 1
        if sel_name in ("top", "bottom"):
            if len(sel_call.args) != 2:
                raise QueryError(f"{sel_name}() takes (field, N)")
            n_rows = int(_call_param_value(sel_call.args[1]))
            if n_rows <= 0:
                raise QueryError(f"{sel_name}() N must be positive")
        pctl = None
        if sel_name == "percentile":
            if len(sel_call.args) != 2:
                raise QueryError("percentile() takes (field, p)")
            pctl = float(_call_param_value(sel_call.args[1]))

        ctx = self._scan_context(stmt, db, rp, mst, now_ns)
        if ctx is None:
            return []
        sc, schema = ctx.sc, ctx.schema
        tmin, tmax = ctx.tmin, ctx.tmax
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W
        every = group_time.every_ns if group_time else 0

        if (schema.get(sel_field) == FieldType.STRING
                and sel_name not in ("first", "last")):
            raise QueryError(
                f"{sel_name}() is not supported on string field {sel_field!r}")

        # output columns: drop explicit bare `time` refs (always col 0)
        columns = ["time"]
        col_plans = []  # ("sel",) | ("aux", expr)
        used_names: dict[str, int] = {}
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.VarRef) and e.name.lower() == "time":
                continue
            name = f.alias or _default_field_name(e)
            k = used_names.get(name, 0)
            used_names[name] = k + 1
            if k:
                name = f"{name}_{k}"
            columns.append(name)
            if isinstance(e, ast.Call):
                col_plans.append(("sel",))
            else:
                col_plans.append(("aux", e))

        aux_field_names = [n for n in aux_fields if n in schema]
        read_fields = sorted({sel_field, *aux_field_names}
                             | cond.row_filter_refs(sc))

        groups: dict[int, list] = {}
        for sh, sid, gid in ctx.scan_plan:
            groups.setdefault(gid, []).append((sh, sid))

        out_series = []
        for gid in sorted(groups, key=lambda g: ctx.group_keys[g]):
            key = ctx.group_keys[gid]
            # gather rows of every member series: time, selector value,
            # aux field columns, per-row tag values
            t_list, v_list = [], []
            aux_cols: dict[str, list] = {n: [] for n in aux_field_names}
            aux_valid: dict[str, list] = {n: [] for n in aux_field_names}
            tag_cols: dict[str, list] = {}
            tag_names = {
                n for n in aux_fields if n not in schema
            }
            for n in tag_names:
                tag_cols[n] = []
            for sh, sid in groups[gid]:
                TRACKER.check()
                rec = sh.read_series(mst, sid, tmin, tmax, fields=read_fields)
                col = rec.columns.get(sel_field)
                if col is None or len(rec) == 0:
                    continue
                m = col.valid.copy()
                if sc.has_row_filter:
                    m &= cond.eval_row_filter(sc, rec,
                                              tags=sh.index.tags_of(sid))
                if not m.any():
                    continue
                t_list.append(rec.times[m])
                v_list.append(col.values[m])
                nsel = int(m.sum())
                for n in aux_field_names:
                    ac = rec.columns.get(n)
                    if ac is None:
                        aux_cols[n].append(np.full(nsel, np.nan))
                        aux_valid[n].append(np.zeros(nsel, bool))
                    else:
                        aux_cols[n].append(np.asarray(ac.values)[m])
                        aux_valid[n].append(np.asarray(ac.valid)[m])
                _, tags = sh.index.series_entry(sid)
                tagd = dict(tags)
                for n in tag_names:
                    tag_cols[n].append([tagd.get(n)] * nsel)
            if not t_list:
                continue
            t = np.concatenate(t_list)
            v = np.concatenate(v_list)
            order = np.argsort(t, kind="stable")
            t, v = t[order], v[order]
            aux_arr = {
                n: (np.concatenate(aux_cols[n])[order],
                    np.concatenate(aux_valid[n])[order])
                for n in aux_field_names
            }
            tag_arr = {
                n: [x for chunk in tag_cols[n] for x in chunk]
                for n in tag_names
            }
            for n, vals in tag_arr.items():
                tag_arr[n] = [vals[i] for i in order]

            if group_time:
                bounds = np.searchsorted(
                    t, [aligned + w * every for w in range(W + 1)]
                )
                windows = [
                    (aligned + w * every, slice(bounds[w], bounds[w + 1]))
                    for w in range(W)
                ]
            else:
                windows = [(aligned, slice(None))]

            rows = []
            for t_out, sl in windows:
                tw, vw = t[sl], v[sl]
                base = sl.start or 0
                if len(vw) == 0:
                    if n_rows == 1 and sel_name not in ("top", "bottom"):
                        rows.append((t_out, [None] * (len(columns) - 1), False))
                    continue
                idxs = _selector_pick(sel_name, tw, vw, n_rows, pctl)
                for i in idxs:
                    ri = base + int(i)
                    vals = []
                    for cp in col_plans:
                        if cp[0] == "sel":
                            vals.append(_render_cell(
                                v[ri], schema.get(sel_field), sel_name))
                        else:
                            vals.append(_eval_aux_expr(
                                cp[1], ri, aux_arr, tag_arr, schema))
                    t_row = (
                        t_out
                        if (group_time and n_rows == 1
                            and sel_name not in ("top", "bottom"))
                        else int(t[ri])
                    )
                    rows.append((t_row, vals, True))
            if n_rows == 1 and sel_name not in ("top", "bottom"):
                rows = _apply_fill(rows, stmt, columns)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset:]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {
                "name": mst,
                "columns": columns,
                "values": [[tr] + vv for tr, vv, _p in rows],
            }
            if ctx.group_tags:
                series["tags"] = dict(zip(ctx.group_tags, key))
            out_series.append(series)
        return out_series


    def _select_top_companions(self, stmt, ctx, multi_plan, mst) -> list[dict]:
        """top()/bottom() with companion projections: select rows by the
        call, then evaluate every other projection against the SELECTED
        source rows (wildcards expand to fields+tags; scalar math follows
        the raw-path null rules). Reference: the reference's top/bottom
        transform keeps auxiliary columns from the winning rows
        (TestServer_Query_For_BugList#2, TestServer_SubQuery_Top_Min#0)."""
        sel_name, call_name, sel_field, params = multi_plan
        sc, schema, tag_keys = ctx.sc, ctx.schema, ctx.tag_keys
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W

        cols = []  # (output name, spec)
        for f in stmt.fields:
            if _is_time_field(f):
                continue  # explicit time is column 0, not a companion
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Call):
                cols.append((f.alias or _default_field_name(e), ("top",)))
            elif isinstance(e, ast.Wildcard):
                for n in sorted(set(schema) | tag_keys):
                    if n in schema:
                        cols.append((n, ("field", n)))
                    else:
                        cols.append((n, ("tag", n)))
            elif isinstance(e, ast.VarRef):
                kind = ("tag", e.name) if e.name in tag_keys and \
                    e.name not in schema else ("field", e.name)
                cols.append((f.alias or e.name, kind))
            else:
                cols.append((f.alias or _default_field_name(f.expr),
                             ("expr", e)))
        need_fields = {sel_field}
        for _n, spec in cols:
            if spec[0] == "field":
                need_fields.add(spec[1])
            elif spec[0] == "expr":
                need_fields |= _scalar_refs(spec[1])
        read_fields = sorted((need_fields | cond.row_filter_refs(sc))
                             & set(schema))

        groups: dict[tuple, list] = {}
        for sh, sid, gid in ctx.scan_plan:
            groups.setdefault(ctx.group_keys[gid], []).append((sh, sid))

        out_series = []
        for key in sorted(groups):
            times_l, topv_l, rowcols_l, tags_l = [], [], [], []
            for sh, sid in groups[key]:
                TRACKER.check()
                rec = sh.read_series(mst, sid, ctx.tmin, ctx.tmax,
                                     fields=read_fields)
                col = rec.columns.get(sel_field)
                if col is None or len(rec) == 0:
                    continue
                m = col.valid.copy()
                if sc.has_row_filter:
                    m &= cond.eval_row_filter(
                        sc, rec, tags=sh.index.tags_of(sid))
                if not m.any():
                    continue
                times_l.append(rec.times[m])
                topv_l.append(col.values[m].astype(np.float64))
                per = {}
                for fname in read_fields:
                    c2 = rec.columns.get(fname)
                    if c2 is not None:
                        per[fname] = (c2.values[m], c2.valid[m], c2.ftype)
                rowcols_l.append(per)
                tags_l.append((sh.index.tags_of(sid), int(m.sum())))
            if not times_l:
                continue
            t = np.concatenate(times_l)
            v = np.concatenate(topv_l)
            src_i = np.concatenate([
                np.full(n, i, np.int32)
                for i, (_tg, n) in enumerate(tags_l)
            ])
            off_i = np.concatenate([
                np.arange(n, dtype=np.int64) for _tg, n in tags_l
            ])
            order = np.argsort(t, kind="stable")
            t, v, src_i, off_i = t[order], v[order], src_i[order], off_i[order]

            def window_bounds():
                if not group_time:
                    return [slice(None)]
                bs = np.searchsorted(
                    t, [aligned + w * group_time.every_ns for w in range(W + 1)])
                return [slice(bs[w], bs[w + 1]) for w in range(W)]

            def row_value(spec, si, oi):
                per = rowcols_l[si]
                if spec[0] == "tag":
                    return tags_l[si][0].get(spec[1])
                if spec[0] == "field":
                    got = per.get(spec[1])
                    if got is None or not got[1][oi]:
                        return None
                    return _pyval(got[0][oi], got[2])
                return _eval_scalar_row(spec[1], per, tags_l[si][0], oi)

            rows = []
            for sl in window_bounds():
                idx = fnmod.select_top_bottom_idx(
                    call_name, t[sl], v[sl], params)
                base = sl.start or 0
                for i in idx:
                    gi = base + int(i)
                    row = [int(t[gi])]
                    for _n, spec in cols:
                        if spec[0] == "top":
                            row.append(_pyval(v[gi], schema.get(sel_field)))
                        else:
                            row.append(
                                row_value(spec, int(src_i[gi]), int(off_i[gi])))
                    rows.append(row)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset:]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {"name": mst, "columns": ["time"] + [n for n, _s in cols],
                      "values": rows}
            if ctx.group_tags:
                series["tags"] = dict(zip(ctx.group_tags, key))
            out_series.append(series)
        return out_series

    # -- host function path (transforms, mode/integral/top/bottom/...) ------


    def _select_host(self, stmt, db, rp, mst, now_ns) -> list[dict]:
        """General host path for calls outside the device aggregate set
        (reference: sql-side transform processors, SURVEY.md §2.3)."""
        ctx = self._scan_context(stmt, db, rp, mst, now_ns)
        if ctx is None:
            return []
        sc, schema = ctx.sc, ctx.schema
        tmin, tmax = ctx.tmin, ctx.tmax
        group_time, aligned, W = ctx.group_time, ctx.aligned, ctx.W
        group_tags = ctx.group_tags
        if group_time:
            window_times = [aligned + w * group_time.every_ns for w in range(W)]
        else:
            window_times = [aligned]
        groups: dict[tuple, list] = {}
        for sh, sid, gid in ctx.scan_plan:
            groups.setdefault(ctx.group_keys[gid], []).append((sh, sid))

        # top/bottom with companion columns (wildcards, fields, math):
        # detected before plan resolution — companions are not calls
        if len(stmt.fields) > 1:
            tb = [
                _strip_expr(f.expr) for f in stmt.fields
                if isinstance(_strip_expr(f.expr), ast.Call)
                and _strip_expr(f.expr).name.lower() in ("top", "bottom")
            ]
            if len(tb) == 1 and all(
                not isinstance(_strip_expr(f.expr), ast.Call)
                or _strip_expr(f.expr) is tb[0]
                for f in stmt.fields
            ):
                e = tb[0]
                _kind, call_name, field, params, _inner = _resolve_host_call(
                    e, group_time)
                if len(params) == 2 and isinstance(params[1], tuple):
                    # companion columns would silently ignore the
                    # per-tag selection — refuse loudly
                    raise QueryError(
                        f"{call_name}(field, tag..., N) cannot be "
                        "combined with other columns")
                name = next(
                    (f.alias for f in stmt.fields
                     if _strip_expr(f.expr) is e and f.alias),
                    _default_field_name(e))
                return self._select_top_companions(
                    stmt, ctx, (name, call_name, field, params), mst)

        # resolve output columns
        plans = []  # (name, kind, call_name, field, params, inner_agg|None)
        multi_plan = None
        outputs = []  # (name, plan_index | ast expr for call math)
        call_plan_idx: dict[int, int] = {}  # id(call) -> plans index

        def _plan_call(e: ast.Call) -> int:
            kind, call_name, field, params, inner = _resolve_host_call(
                e, group_time)
            _check_host_field_type(
                inner[0] if kind == "sliding" and inner else call_name,
                field, schema)
            if kind == "multi":
                raise QueryError(
                    f"{call_name}() cannot be combined with other "
                    "expressions")
            plans.append((None, kind, call_name, field, params, inner))
            call_plan_idx[id(e)] = len(plans) - 1
            return len(plans) - 1

        for f in stmt.fields:
            if _is_time_field(f):
                continue  # explicit `time` is always column 0
            e = _strip_expr(f.expr)
            if not isinstance(e, ast.Call):
                # scalar math over host calls: `4 * mode(v)`,
                # `sum(v) / elapsed(sum(v), 1m)` — every leaf call gets
                # its own plan, the expression evaluates per window
                # (reference: sql-side binary-expr materialize transform)
                calls = _calls_in(f.expr)
                if not calls:
                    raise QueryError(
                        "host-path expressions need at least one function")
                for c in calls:
                    _plan_call(c)
                outputs.append((f.alias or _default_field_name(f.expr),
                                f.expr))
                continue
            name = f.alias or _default_field_name(e)
            kind, call_name, field, params, inner = _resolve_host_call(e, group_time)
            _check_host_field_type(
                inner[0] if kind == "sliding" and inner else call_name,
                field, schema)
            if kind == "multi":
                if sum(1 for f2 in stmt.fields
                       if not _is_time_field(f2)) > 1:
                    raise QueryError(f"{call_name}() must be the only field")
                if call_name == "distinct" and field in sc.tag_keys \
                        and field not in schema:
                    # influx: DISTINCT over a tag is not a field selection
                    raise QueryError(
                        "statement must have at least one field in "
                        "select clause")
                multi_plan = (name, call_name, field, params)
            else:
                plans.append((name, kind, call_name, field, params, inner))
                outputs.append((name, len(plans) - 1))

        fitted_models = None
        if multi_plan is not None and multi_plan[1] == "detect" \
                and multi_plan[3]:
            # one artifact read per QUERY (not per group or window slice)
            doc = self.engine.models.get(str(multi_plan[3][0]))
            if doc is not None:
                fitted_models = {str(multi_plan[3][0]): doc}
        out_series = []
        for key in sorted(groups):
            rows_by_field: dict[str, tuple[np.ndarray, np.ndarray]] = {}

            def field_rows(fname: str):
                got = rows_by_field.get(fname)
                if got is not None:
                    return got
                ts_list, vs_list = [], []
                for sh, sid in groups[key]:
                    TRACKER.check()  # KILL QUERY cancellation point
                    rec = sh.read_series(
                        mst, sid, tmin, tmax,
                        fields=[fname] + sorted(cond.row_filter_refs(sc)))
                    col = rec.columns.get(fname)
                    if col is None or len(rec) == 0:
                        continue
                    m = col.valid.copy()
                    if sc.has_row_filter:
                        m &= cond.eval_row_filter(
                            sc, rec, tags=sh.index.tags_of(sid))
                    ts_list.append(rec.times[m])
                    vs_list.append(col.values[m])
                if not ts_list:
                    got = (np.empty(0, np.int64), np.empty(0))
                else:
                    t = np.concatenate(ts_list)
                    v = np.concatenate(vs_list)
                    order = np.argsort(t, kind="stable")
                    got = (t[order], v[order])
                rows_by_field[fname] = got
                return got

            def window_slices(t: np.ndarray):
                if not group_time:
                    return [(window_times[0], slice(None))]
                bounds = np.searchsorted(
                    t, [aligned + w * group_time.every_ns for w in range(W + 1)]
                )
                return [
                    (window_times[w], slice(bounds[w], bounds[w + 1]))
                    for w in range(W)
                ]

            if multi_plan is not None and len(multi_plan[3]) == 2 and \
                    multi_plan[1] in ("top", "bottom") and \
                    isinstance(multi_plan[3][1], tuple):
                series = self._multi_top_tags(
                    stmt, multi_plan, groups[key], mst, tmin, tmax, sc,
                    window_slices)
                if series is not None:
                    if group_tags:
                        series["tags"] = dict(zip(group_tags, key))
                    out_series.append(series)
                continue

            if multi_plan is not None:
                name, call_name, fname, params = multi_plan
                t, v = field_rows(fname)
                rows = []
                for wt, sl in window_slices(t):
                    for rt, rv in fnmod.multi_row(
                            call_name, t[sl], v[sl], params,
                            models=fitted_models):
                        rows.append([rt if rt is not None else wt, rv])
                if not stmt.ascending:
                    rows.reverse()
                if stmt.offset:
                    rows = rows[stmt.offset :]
                if stmt.limit:
                    rows = rows[: stmt.limit]
                if not rows:
                    continue
                series = {"name": mst, "columns": ["time", name], "values": rows}
                if group_tags:
                    series["tags"] = dict(zip(group_tags, key))
                out_series.append(series)
                continue

            # single raw transform: emit rows directly — dict keying would
            # collapse rows when two series in the group share a timestamp
            if (len(plans) == 1 and plans[0][1] == "transform_raw"
                    and len(outputs) == 1
                    and isinstance(outputs[0][1], int)):
                # bare transform only: a call-math output (e.g.
                # difference(v) * 2) must go through the expression
                # evaluator below, not this direct-emit path
                name, _kind, call_name, fname, params, _inner = plans[0]
                t, v = field_rows(fname)
                if not stmt.ascending:
                    # ORDER BY time DESC: the transform runs over the
                    # DESC-ordered sequence (reference Null_Aggregate desc
                    # difference cases — sign and row times follow the
                    # reversed walk, not a reversed asc result)
                    t_out, v_out = fnmod.transform(
                        call_name, t[::-1], v[::-1], params
                    )
                else:
                    t_out, v_out = fnmod.transform(call_name, t, v, params)
                rows = [
                    (int(tt), [fnmod.py_value(vv)], True)
                    for tt, vv in zip(t_out, v_out)
                ]
                if stmt.offset:
                    rows = rows[stmt.offset :]
                if stmt.limit:
                    rows = rows[: stmt.limit]
                if not rows:
                    continue
                series = {
                    "name": mst,
                    "columns": ["time", name],
                    "values": [[t0] + vv for t0, vv, _p in rows],
                }
                if group_tags:
                    series["tags"] = dict(zip(group_tags, key))
                out_series.append(series)
                continue

            col_maps: list[dict] = []  # per plan: {time: value}
            has_plain_agg = False
            sliding_grid: list | None = None
            for name, kind, call_name, fname, params, inner in plans:
                t, v = field_rows(fname)
                if kind == "agg":
                    has_plain_agg = True
                    m: dict = {}
                    if (call_name in ("count", "count_distinct")
                            and fname not in schema
                            and fname in sc.tag_keys):
                        # influx: COUNT(DISTINCT <tag>) answers 0, not an
                        # empty result (tags are not countable fields)
                        m[window_times[0]] = (0, None)
                    elif (call_name == "median"
                          and schema.get(fname) == FieldType.STRING):
                        # influx: MEDIAN over strings renders a null row
                        m[window_times[0]] = (None, None)
                    else:
                        for wt, sl in window_slices(t):
                            val, sel_t = fnmod.host_agg(
                                call_name, t[sl], v[sl], params)
                            if val is not None:
                                m[wt] = (val, sel_t)
                    col_maps.append(m)
                elif kind == "sliding":
                    n = int(params[0])
                    slices = window_slices(t)
                    m = {}
                    sliding_grid = [wt for wt, _sl in slices[: max(len(slices) - n + 1, 0)]]
                    for i in range(0, len(slices) - n + 1):
                        lo = slices[i][1].start or 0
                        hi = slices[i + n - 1][1].stop
                        val, _sel = fnmod.host_agg(
                            inner[0], t[lo:hi], v[lo:hi], inner[1])
                        if val is not None:
                            m[slices[i][0]] = (val, None)
                    col_maps.append(m)
                elif kind == "transform_raw":
                    t_out, v_out = fnmod.transform(call_name, t, v, params)
                    col_maps.append({int(tt): (vv.item() if hasattr(vv, "item") else vv, None)
                                     for tt, vv in zip(t_out, v_out)})
                else:  # transform over inner aggregate windows
                    seq_t, seq_v = [], []
                    for wt, sl in window_slices(t):
                        val, _sel = fnmod.host_agg(inner[0], t[sl], v[sl], inner[1])
                        if val is not None:
                            seq_t.append(wt)
                            seq_v.append(val)
                    t_out, v_out = fnmod.transform(
                        call_name, np.asarray(seq_t, np.int64), np.asarray(seq_v), params
                    )
                    col_maps.append({int(tt): (float(vv), None) for tt, vv in zip(t_out, v_out)})

            if has_plain_agg and group_time:
                # transforms may emit times outside the window grid
                # (holt_winters forecasts) — union them in, never drop
                extra = {t for m in col_maps for t in m} - set(window_times)
                base_times = sorted(set(window_times) | extra)
            elif sliding_grid is not None:
                # sliding windows emit every output slot; empties fill null
                base_times = sliding_grid
            else:
                seen = sorted({t for m in col_maps for t in m})
                base_times = seen
            rows = []
            col_names = [name for name, _src in outputs]
            for bt in base_times:
                vals = []
                present = False
                for _name, src in outputs:
                    if isinstance(src, int):
                        entry = col_maps[src].get(bt)
                        if entry is None:
                            vals.append(None)
                        else:
                            vals.append(entry[0])
                            present = True
                    else:  # call-math expression over plan columns
                        v, p = _eval_host_output(
                            src, bt, col_maps, call_plan_idx)
                        vals.append(v)
                        present = present or p
                # single BARE selector-time semantics: a selector inside
                # math is an aggregate (influx strips the sample time)
                t_render = bt
                if (len(plans) == 1 and not group_time
                        and len(outputs) == 1
                        and isinstance(outputs[0][1], int)):
                    entry = col_maps[0].get(bt)
                    if entry and entry[1] is not None:
                        t_render = entry[1]
                rows.append((t_render, vals, present))
            rows = _apply_fill(rows, stmt, ["time"] + col_names)
            if not stmt.ascending:
                rows.reverse()
            if stmt.offset:
                rows = rows[stmt.offset :]
            if stmt.limit:
                rows = rows[: stmt.limit]
            if not rows:
                continue
            series = {
                "name": mst,
                "columns": ["time"] + col_names,
                "values": [[t] + v for t, v, _p in rows],
            }
            if group_tags:
                series["tags"] = dict(zip(group_tags, key))
            out_series.append(series)
        return out_series

    def _multi_top_tags(self, stmt, multi_plan, shard_sids, mst, tmin,
                        tmax, sc, window_slices):
        """top/bottom(field, tag..., N): per window, each DISTINCT tag
        combination contributes its best point, and the best N
        combinations emit (time-ascending). The tag columns ride along —
        and INTO writes them back as TAGS, not fields (reference:
        TestServer_Query_TopBottomWriteTags)."""
        name, call_name, fname, (n_take, tagkeys) = multi_plan
        want_top = call_name == "top"
        ts_list, vs_list, ci_list = [], [], []
        combos: list[tuple] = []
        combo_idx: dict[tuple, int] = {}
        filter_fields = [fname] + sorted(cond.row_filter_refs(sc))
        for sh, sid in shard_sids:
            TRACKER.check()
            rec = sh.read_series(mst, sid, tmin, tmax, fields=filter_fields)
            col = rec.columns.get(fname)
            if col is None or len(rec) == 0:
                continue
            m = col.valid.copy()
            if sc.has_row_filter:
                m &= cond.eval_row_filter(sc, rec, tags=sh.index.tags_of(sid))
            if not m.any():
                continue
            tags = sh.index.tags_of(sid)
            combo = tuple(tags.get(k, "") for k in tagkeys)
            ci = combo_idx.get(combo)
            if ci is None:
                ci = combo_idx[combo] = len(combos)
                combos.append(combo)
            ts_list.append(rec.times[m])
            vs_list.append(col.values[m])  # native dtype: int64 stays exact
            ci_list.append(np.full(int(m.sum()), ci, np.int64))
        if not ts_list:
            return None
        t = np.concatenate(ts_list)
        v = np.concatenate(vs_list)
        ci = np.concatenate(ci_list)
        order = np.argsort(t, kind="stable")
        t, v, ci = t[order], v[order], ci[order]
        rows = []
        for wt, sl in window_slices(t):
            tw, vw, cw = t[sl], v[sl], ci[sl]
            if not len(tw):
                continue
            best: dict[int, tuple] = {}  # combo -> (value, time)
            for i in range(len(tw)):
                cur = best.get(int(cw[i]))
                better = cur is None or (
                    (vw[i] > cur[0]) if want_top else (vw[i] < cur[0]))
                # value ties keep the EARLIEST point (time-sorted walk:
                # first seen wins)
                if better:
                    best[int(cw[i])] = (vw[i], int(tw[i]))
            ranked = sorted(
                best.items(),
                key=lambda kv: ((-kv[1][0]) if want_top else kv[1][0],
                                kv[1][1]))[:n_take]
            picked = sorted(ranked, key=lambda kv: kv[1][1])  # time asc
            for combo_i, (val, t_ns) in picked:
                rows.append([t_ns, fnmod.py_value(val)]
                            + list(combos[combo_i]))
        if not stmt.ascending:
            rows.reverse()
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit:
            rows = rows[: stmt.limit]
        if not rows:
            return None
        series = {"name": mst, "columns": ["time", name] + list(tagkeys),
                  "values": rows}
        if stmt.into is not None:
            # INTO must write the tag columns back as TAGS
            series["_tag_cols"] = list(tagkeys)
        return series

    # -- raw path -----------------------------------------------------------


    def _select_table_function(self, stmt, call, db: str, now_ns: int) -> dict:
        """SELECT <table_function>('<params json>') FROM m WHERE time ...
        (reference: LogicalTableFunction, logic_plan.go:3863; the one
        production operator is rca, table_function_factory.go:26). The
        measurement's raw rows in the time range are the function input;
        the result is one row holding the output graph as JSON."""
        from opengemini_tpu.query import tablefunc as tfmod

        if len(call.args) != 1:
            raise QueryError(f"{call.name}() takes one string argument")
        arg = _strip_expr(call.args[0])
        if not isinstance(arg, ast.StringLiteral):
            raise QueryError(f"{call.name}() parameter must be a quoted string")
        import dataclasses

        raw_stmt = dataclasses.replace(
            stmt, fields=[ast.Field(expr=ast.Wildcard())],
            group_by_all_tags=True, limit=0, offset=0,
        )
        rows: list[dict] = []
        for src in stmt.sources:
            if not isinstance(src, ast.Measurement):
                raise QueryError(f"{call.name}() requires a measurement source")
            src_db = src.database or db
            for series in self._select_raw(raw_stmt, src_db, src.rp or None,
                                           src.name, now_ns):
                tags = series.get("tags") or {}
                cols = series["columns"]
                for vals in series["values"]:
                    row = dict(tags)
                    for c, v in zip(cols, vals):
                        if v is not None:
                            row[c] = v
                    rows.append(row)
        try:
            graph = tfmod.TABLE_FUNCTIONS[call.name](rows, arg.val)
        except tfmod.TableFunctionError as e:
            raise QueryError(str(e)) from None
        name = stmt.sources[0].name if stmt.sources else call.name
        import json as _json

        return {"series": [_series(name, None, [call.name],
                                   [[_json.dumps(graph, sort_keys=True)]])]}


    def _select_raw(self, stmt, db, rp, mst, now_ns) -> list[dict]:
        if self.engine.is_measurement_dropped(db, mst):
            return []  # mark-deleted: hidden from SELECT pre-purge
        shards_all, _live = self._all_shards_with_remote(
            db, rp, mst, stmt.condition, now_ns
        )
        tag_keys: set[str] = set()
        schema: dict[str, FieldType] = {}
        for sh in shards_all:
            tag_keys.update(sh.index.tag_keys(mst))
            schema.update(sh.schema(mst))
        if not schema:
            if stmt.group_by_all_tags:
                # GROUP BY * requires the measurement's tag keys from
                # meta — a missing measurement is an error there, not an
                # empty result (reference meta.Measurement ->
                # ErrMeasurementNotFound; TestServer_Query_Where_Fields)
                raise QueryError("measurement not found")
            return []
        sc = cond.split(stmt.condition, tag_keys, now_ns)
        shards = [sh for sh in shards_all if sh.tmax > sc.tmin and sh.tmin < sc.tmax]
        if not shards:
            return []

        # output columns: * expands to fields + tags, except tags consumed
        # by GROUP BY (explicit or *), which surface in the series tags dict
        # (influx wildcard semantics)
        if stmt.group_by_all_tags:
            grouped_tags = tag_keys
        elif getattr(stmt, "_from_subquery", False):
            # inner EXPLICIT group-by tags are subquery output dimensions:
            # the outer wildcard lists them as columns
            grouped_tags = tag_keys - set(getattr(stmt, "_subquery_dims", ()))
        else:
            grouped_tags = set(stmt.group_by_tags)
        names: list[tuple] = []  # (output name, kind, payload)
        for f in stmt.fields:
            e = _strip_expr(f.expr)
            if isinstance(e, ast.Wildcard):
                names.extend(
                    (n, "ref", n)
                    for n in sorted(set(schema) | (tag_keys - grouped_tags))
                )
            elif isinstance(e, ast.StringLiteral):
                # constant column (validated to carry an alias upstream)
                names.append(
                    (f.alias or _default_field_name(f.expr), "const", e.val))
            elif (
                isinstance(e, (ast.BinaryExpr, ast.UnaryExpr))
                and not _calls_in(e)
            ):
                # scalar field math (`f1 + f2 + f3`, `100 - age`): null
                # unless every referenced field is present on the row;
                # rows where ANY referenced field exists still emit
                # (reference TestServer_Query_SubqueryMath)
                names.append(
                    (f.alias or _default_field_name(f.expr), "expr", e))
            else:
                src_name = e.name if isinstance(e, ast.VarRef) else ""
                names.append(
                    (f.alias or _default_field_name(f.expr), "ref", src_name))
        # duplicate output names get _N suffixes, all columns kept —
        # `SELECT value, * FROM m` yields value, ..., value_1 (influx
        # duplicate-column naming; TestServer_Query_Wildcards#4). const/
        # expr lookups key by the FINAL (suffixed) name so colliding
        # aliases stay wired to their own payloads.
        used: dict[str, int] = {}
        out_cols = []  # (final name, source ref)
        const_cols: dict[str, str] = {}  # final name -> literal value
        expr_cols: dict[str, object] = {}  # final name -> scalar expr AST
        for n, kind, payload in names:
            k = used.get(n, 0)
            used[n] = k + 1
            final = f"{n}_{k}" if k else n
            if kind == "const":
                const_cols[final] = payload
                out_cols.append((final, final))
            elif kind == "expr":
                expr_cols[final] = payload
                out_cols.append((final, final))
            else:
                out_cols.append((final, payload or n))
        columns = ["time"] + [n for n, _s in out_cols]
        src_of = {n: s_ for n, s_ in out_cols}

        group_tags = self._group_tags(stmt, shards, mst)
        groups: dict[tuple, list] = {}
        match_terms = cond.conjunctive_match_terms(sc.field_expr)
        hinted = bool({"full_series", "specific_series"}
                      & set(getattr(stmt, "hints", ())))
        exact_tags = (
            cond.exact_series_tags(stmt.condition, tag_keys)
            if "full_series" in getattr(stmt, "hints", ()) else None
        ) or None  # no tag equalities -> the hint pins nothing
        for sh in shards:
            sids = cond.eval_tag_expr(sc.tag_expr, sh.index, mst)
            if sc.mixed_expr is not None:
                if hinted:
                    sids &= cond.series_only_sids(
                        sc.mixed_expr, sh.index, mst, sc.tag_keys)
                else:
                    sids &= cond.tag_superset_sids(
                        sc.mixed_expr, sh.index, mst, sc.tag_keys)
            if exact_tags is not None:
                sids = {s for s in sids
                        if sh.index.tags_of(s) == exact_tags}
            sids = _prune_text_sids(sh, mst, sids, match_terms)
            for sid in sorted(sids):
                tags = sh.index.tags_of(sid)
                key = tuple(tags.get(k, "") for k in group_tags)
                groups.setdefault(key, []).append((sh, sid, tags))
        if hinted:
            sc.mixed_series_level = True  # consumed at the series level

        # project only needed columns: selected fields + filter refs +
        # scalar-math operand fields
        filter_refs = cond.row_filter_refs(sc)
        expr_refs: set[str] = set()
        for e in expr_cols.values():
            expr_refs |= _scalar_refs(e)
        read_fields = sorted(
            ({src_of[c] for c in columns[1:] if src_of[c] in schema}
             | set(filter_refs) | expr_refs) & set(schema)
        )
        # tag-only selects (e.g. SELECT "name" FROM m, openGemini
        # semantics): a row exists wherever ANY field is set, so read
        # every field for presence
        tag_only = not read_fields and any(
            src_of[c] in tag_keys for c in columns[1:])
        if tag_only:
            read_fields = None
        out_series = []
        for key in sorted(groups):
            rows: list[list] = []
            for sh, sid, tags in groups[key]:
                TRACKER.check()  # KILL QUERY cancellation point
                rec = sh.read_series(mst, sid, sc.tmin, sc.tmax, fields=read_fields)
                if len(rec) == 0:
                    continue
                fmask = (
                    cond.eval_row_filter(sc, rec, tags=tags)
                    if sc.has_row_filter
                    else np.ones(len(rec), dtype=bool)
                )
                # a raw row is emitted if any selected *field* is present
                # (tag-only selects: any field at all)
                present = np.zeros(len(rec), dtype=bool)
                col_arrays = []
                for name in columns[1:]:
                    if name in const_cols:
                        col_arrays.append((None, None, const_cols[name]))
                        continue
                    ref = src_of[name]
                    if ref in expr_cols:
                        vals, valid, touched = _eval_scalar_cols(
                            expr_cols[ref], rec)
                        col_arrays.append((vals, valid, FieldType.FLOAT))
                        present |= touched
                        continue
                    col = rec.columns.get(ref)
                    if col is not None:
                        col_arrays.append((col.values, col.valid, col.ftype))
                        present |= col.valid
                    elif ref in tags:
                        col_arrays.append((None, None, tags[ref]))
                    else:
                        col_arrays.append((None, None, None))
                if tag_only:
                    for col in rec.columns.values():
                        present |= col.valid
                sel = np.nonzero(fmask & present)[0]
                for i in sel:
                    row = [int(rec.times[i])]
                    for values, valid, extra in col_arrays:
                        if values is None:
                            row.append(extra if isinstance(extra, str) else None)
                        elif valid[i]:
                            row.append(_pyval(values[i], extra))
                        else:
                            row.append(None)
                    rows.append(row)
            if not rows:
                continue
            if getattr(stmt, "_subquery_dims", None) and not group_tags:
                # ungrouped select over a dimensioned subquery keeps the
                # inner series order (rows appended per-series, ascending
                # within each — reference SubqueryForLogicalOptimize#5)
                if not stmt.ascending:
                    rows.reverse()
            else:
                rows.sort(key=lambda r: r[0], reverse=not stmt.ascending)
            series = {"name": mst, "columns": columns, "values": rows}
            if group_tags:
                series["tags"] = dict(zip(group_tags, key))
            out_series.append(series)
        if stmt.offset or stmt.limit:
            # LIMIT/OFFSET apply GLOBALLY over the time-merged row stream,
            # not per series (reference TestServer_Query_LimitAndOffset:
            # `group by tennant limit 1` returns one row total); series
            # left empty by the slice are omitted entirely
            flat = []
            for si, s in enumerate(out_series):
                flat.extend((row[0], si, row) for row in s["values"])
            flat.sort(key=lambda e: (e[0], e[1]), reverse=not stmt.ascending)
            if stmt.offset:
                flat = flat[stmt.offset:]
            if stmt.limit:
                flat = flat[: stmt.limit]
            kept: dict[int, list] = {}
            for _t, si, row in flat:
                kept.setdefault(si, []).append(row)
            out_series = [
                dict(s, values=kept[si])
                for si, s in enumerate(out_series)
                if si in kept
            ]
        return out_series

    # -- SHOW ---------------------------------------------------------------


