"""Distributed partial aggregation: the data-node side of aggregate
pushdown plus the coordinator-side merge.

Reference: the store-side partial aggregation + exchange/merge pipeline
(engine/executor/rpc_transform.go:117, merge_transform.go,
agg_transform.go). The reference streams chunk partials through RPC
transforms; here each peer runs the SAME device batch machinery the
coordinator uses (models/templates.AggBatch & friends) over its local
shards against the coordinator's window grid, and ships one dense
per-(group, window) partial array set — O(groups x windows) for the
MERGEABLE aggregates — which the coordinator merges with numpy before
rendering. Rank aggregates ship per-segment (value, count) multisets
instead: O(groups x distinct values), which degenerates toward O(rows)
on continuous float fields — a density cutoff (below) refuses such
wires and the coordinator falls back to the raw column exchange.

Mergeability table (what travels per requested aggregate):
  count          -> count
  sum            -> sum            mean   -> sum + count
  min/max        -> value + exact ns time (selector rendering)
  first/last     -> value + exact ns time (lexicographic winner)
  spread         -> min + max
  stddev         -> count + mean + M2 (Chan et al. parallel variance —
                    numerically stable pairwise combine, unlike the
                    naive sum-of-squares formula in low precision)

Everything else (percentile, median, distinct, host transforms) is not
losslessly mergeable from fixed-size partials and falls back to the raw
column exchange (parallel/cluster.serialize_series_binary).
"""

from __future__ import annotations

import json
import struct

import numpy as np

# aggregate names whose cross-node merge is lossless from the partial set
MERGEABLE = {
    "count", "sum", "mean", "min", "max", "first", "last", "spread", "stddev",
}

# rank-based aggregates: not mergeable from FIXED-SIZE partials, but
# exactly mergeable from per-segment (value, count) multisets — peers ship
# O(groups x distinct-values) instead of raw columns (reference
# distributes these via hash exchange; here the multiset IS the exchange)
MULTISET_MERGEABLE = {"median", "percentile", "count_distinct"}

# partial arrays required per requested aggregate
_REQUIRES = {
    "count": (),
    "sum": ("sum",),
    "mean": ("sum",),
    "min": ("min",),
    "max": ("max",),
    "first": ("first",),
    "last": ("last",),
    "spread": ("min", "max"),
    "stddev": ("mean", "m2"),
    # the ragged multiset trio travels as mvals/mcnts/moffs on the wire
    "median": ("mset",),
    "percentile": ("mset",),
    "count_distinct": ("mset",),
}

_BIG = np.int64(2**62)


def partial_names(agg_names) -> list[str]:
    """Wire partial-array names for a field's requested aggregates.
    count is always present: it doubles as the per-window presence mask."""
    out = {"count"}
    for a in agg_names:
        out.update(_REQUIRES[a])
    return sorted(out)


# -- peer side ---------------------------------------------------------------


def compute_partials(engine, router, req: dict) -> bytes:
    """Run the local slice of a distributed aggregate query.

    req (built by DataRouter.select_partials): db, rp, mst, tmin, tmax,
    aligned, every_ns, offset_ns, W, group_tags, aggs {field: [names]},
    tag_expr / field_expr (astjson docs), live, rf.
    """
    from opengemini_tpu.models import templates
    from opengemini_tpu.ops import aggregates as aggmod
    from opengemini_tpu.ops import window as winmod
    from opengemini_tpu.query import condition as cond
    from opengemini_tpu.query.executor import (
        _add_record_to_batches,
        _prune_text_sids,
        pick_batch,
    )
    from opengemini_tpu.sql import astjson

    db, rp, mst = req["db"], req.get("rp") or None, req["mst"]
    tmin, tmax = int(req["tmin"]), int(req["tmax"])
    aligned, W = int(req["aligned"]), int(req["W"])
    every = int(req.get("every_ns") or 0)
    offset = int(req.get("offset_ns") or 0)
    group_tags = list(req["group_tags"])
    per_field = {f: list(names) for f, names in req["aggs"].items()}
    tag_expr = astjson.from_json(req.get("tag_expr"))
    field_expr = astjson.from_json(req.get("field_expr"))
    mixed_expr = astjson.from_json(req.get("mixed_expr"))

    shards = engine.shards_for_range(db, rp, tmin, tmax)
    live = req.get("live")
    if int(req.get("rf", 1)) > 1 and live and router is not None:
        shards = [
            sh for sh in shards
            if router.is_primary(db, rp, sh.tmin, live)
        ]

    schema = {}
    tag_keys: set[str] = set()
    for sh in shards:
        schema.update(sh.schema(mst))
        tag_keys.update(sh.index.tag_keys(mst))
    if req.get("tag_keys") is not None:
        # the coordinator's classification governs: a tag key it knows but
        # no peer-local shard indexes must still inject as an empty-string
        # column in row evaluation (tag != 'x' over a missing tag is TRUE,
        # not column-missing-false)
        tag_keys = set(req["tag_keys"])
    # peer-side SplitCondition over the coordinator's view; this only
    # drives row evaluation here
    sc = cond.SplitCondition(tmin, tmax, tag_expr, field_expr, mixed_expr,
                             frozenset(tag_keys))
    sc.mixed_series_level = bool(req.get("mixed_series_level"))

    read_fields = sorted(set(per_field) | cond.row_filter_refs(sc))
    dtype = templates.compute_dtype()
    # same grid_ctx the coordinator uses: peers take the identical
    # windows-on-lanes fast path for stride-regular data (pick_batch's
    # "both sides pick identical numerics" contract)
    grid_ctx = (W, every) if every else None
    batches = {
        f: pick_batch(schema, per_field[f], f, dtype, grid_ctx)
        for f in per_field
    }

    # replica-side child trace (utils/tracing): parented at the
    # coordinator's wire ctx when the request carries one, shipped back
    # in the partials header so the coordinator stitches one tree
    from opengemini_tpu.utils import tracing

    trace, cm = tracing.start_remote_activated(
        "select_partials", req.get("trace"),
        node=getattr(router, "self_id", "") or "")
    with cm:
        cur = tracing.current()
        # group bookkeeping against the COORDINATOR's grid.  Two passes
        # under separate spans: index-side series selection ("scan"),
        # then chunk decode + batch staging ("decode") — the per-stage
        # split is what straggler attribution needs when one node's
        # partials round is slow
        gid_of: dict[tuple, int] = {}
        group_keys: list[tuple] = []
        group_tag_dicts: list[dict] = []
        match_terms = [] if every else cond.conjunctive_match_terms(field_expr)
        plan: list[tuple] = []  # (shard, sid, gid, tags)
        with cur.span("scan") as sp:
            for sh in shards:
                sids = cond.eval_tag_expr(tag_expr, sh.index, mst)
                if mixed_expr is not None:
                    if sc.mixed_series_level:  # hinted: exact series filter
                        sids &= cond.series_only_sids(
                            mixed_expr, sh.index, mst, tag_keys)
                    else:
                        sids &= cond.tag_superset_sids(
                            mixed_expr, sh.index, mst, tag_keys)
                sids = _prune_text_sids(sh, mst, sids, match_terms)
                for sid in sorted(sids):
                    tags = sh.index.tags_of(sid)
                    key = tuple(tags.get(k, "") for k in group_tags)
                    gid = gid_of.get(key)
                    if gid is None:
                        gid = len(group_keys)
                        gid_of[key] = gid
                        group_keys.append(key)
                        group_tag_dicts.append(
                            {k: tags.get(k, "") for k in group_tags})
                    plan.append((sh, sid, gid, tags))
            sp.add_field("shards", len(shards))
            sp.add_field("series", len(plan))
        rows = 0
        with cur.span("decode") as sp:
            for sh, sid, gid, tags in plan:
                rec = sh.read_series(mst, sid, tmin, tmax,
                                     fields=read_fields)
                if len(rec) == 0:
                    continue
                rows += len(rec)
                fmask = (
                    cond.eval_row_filter(sc, rec, tags=tags)
                    if sc.has_row_filter else None
                )
                if every:
                    widx, _ = winmod.window_index(
                        rec.times, tmin, every, offset)
                    seg = (gid * W + widx.astype(np.int64)).astype(np.int32)
                else:
                    seg = np.full(len(rec), gid, dtype=np.int32)
                _add_record_to_batches(
                    rec, seg, aligned, sorted(per_field), batches, dtype,
                    fmask, sids=sid,
                )
            sp.add_field("rows", rows)

        with cur.span("partial_merge") as sp:
            fields_out = _compute_field_partials(
                per_field, batches, group_keys, W, aggmod)
            sp.add_field("fields", len(fields_out))
    return serialize_partials(group_tag_dicts, fields_out,
                              len(group_keys), W,
                              trace=tracing.ship_subtree(trace))


def _compute_field_partials(per_field, batches, group_keys, W, aggmod):
    """Run the partial-array computation for every requested field (the
    peer-side 'partial_merge' stage): {field: {partial_name: array}}."""
    n_seg = max(len(group_keys), 1) * W
    fields_out: dict[str, dict[str, np.ndarray]] = {}
    for f, names in per_field.items():
        batch = batches[f]
        want = partial_names(names)
        arrs: dict[str, np.ndarray] = {}
        counts = None

        def run(spec_name):
            out, sel, cnt = batch.run(aggmod.get(spec_name), n_seg)
            return out, sel, cnt

        for p in want:
            if p == "count":
                _o, _s, counts = run("count")
                arrs["count"] = np.asarray(counts, np.int64)
            elif p == "sum":
                out, _s, counts = run("sum")
                arrs["sum"] = np.asarray(out)
            elif p in ("min", "max", "first", "last"):
                out, sel, counts = run(p)
                arrs[p + "_v"] = np.asarray(out, np.float64)
                times = batch.host_times()
                if sel is not None and len(times):
                    t = times[np.clip(np.asarray(sel), 0, len(times) - 1)]
                else:
                    t = np.zeros(n_seg, np.int64)
                arrs[p + "_t"] = np.asarray(t, np.int64)
            elif p == "mean":
                out, _s, counts = run("mean")
                arrs["mean"] = np.asarray(out, np.float64)
            elif p == "m2":
                sd, _s, counts = run("stddev")
                c = np.asarray(counts, np.float64)
                arrs["m2"] = np.asarray(sd, np.float64) ** 2 * np.maximum(
                    c - 1, 0
                )
            elif p == "mset":
                mv, mc, mo = batch.host_value_multiset(n_seg)
                if len(mv) > 10_000 and len(mv) > 0.5 * max(batch.n, 1):
                    # continuous float fields: distinct ~ rows, the
                    # multiset wire would exceed a raw value column —
                    # refuse (the 400 becomes PartialsUnavailable on the
                    # coordinator, which falls back to the raw exchange)
                    raise ValueError(
                        "rank-aggregate multiset too dense "
                        f"({len(mv)} distinct / {batch.n} rows)")
                arrs["mvals"] = mv
                arrs["mcnts"] = mc
                arrs["moffs"] = mo
        if counts is None:
            _o, _s, counts = run("count")
        arrs.setdefault("count", np.asarray(counts, np.int64))
        fields_out[f] = arrs

    ngroups = len(group_keys)
    if ngroups * W != n_seg:  # zero local groups: ship empty arrays
        def _slice(p, a):
            if p == "moffs":
                return a[: ngroups * W + 1]  # offsets carry one extra slot
            if p in ("mvals", "mcnts"):
                return a  # already empty with zero groups
            return a[: ngroups * W]

        fields_out = {
            f: {p: _slice(p, a) for p, a in arrs.items()}
            for f, arrs in fields_out.items()
        }
    return fields_out


# -- wire format -------------------------------------------------------------
# [u32 header_len][header JSON][raw little-endian array buffers]


def serialize_partials(group_tag_dicts, fields_out, ngroups: int, W: int,
                       trace: dict | None = None) -> bytes:
    buffers: list[bytes] = []
    off = 0

    def add(arr: np.ndarray) -> dict:
        nonlocal off
        a = np.ascontiguousarray(arr)
        d = "<i8" if a.dtype.kind in "iu" else "<f8"
        b = a.astype(d, copy=False).tobytes()
        buffers.append(b)
        loc = {"d": d, "o": off, "n": len(b)}
        off += len(b)
        return loc

    header = {
        "groups": group_tag_dicts,
        "W": W,
        "fields": {
            f: {p: add(arr) for p, arr in arrs.items()}
            for f, arrs in fields_out.items()
        },
    }
    if trace is not None:
        # the replica's span subtree rides the header (JSON next to the
        # group/field directory, never the raw buffers)
        header["trace"] = trace
    hbuf = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack("<I", len(hbuf)) + hbuf + b"".join(buffers)


def parse_partials(data: bytes) -> dict:
    (hlen,) = struct.unpack_from("<I", data, 0)
    header = json.loads(data[4 : 4 + hlen])
    payload = memoryview(data)[4 + hlen :]
    fields = {}
    for f, arrs in header["fields"].items():
        fields[f] = {
            p: np.frombuffer(payload[loc["o"] : loc["o"] + loc["n"]], loc["d"])
            for p, loc in arrs.items()
        }
    out = {"groups": header["groups"], "W": header["W"], "fields": fields}
    if "trace" in header:
        out["trace"] = header["trace"]
    return out


# -- coordinator side --------------------------------------------------------


def merge_remote_partials(
    agg_results, aggs, batches, group_keys, W, peer_docs, group_tags,
):
    """Fold peers' partial docs into the locally-computed agg_results.

    Mutates group_keys in place (appending remote-only groups) and
    REPLACES each mergeable call's entry with the cluster-wide result:
    (values, None, counts, spec, field, times_abs|None). Stack order for
    time ties is local first, then peers in the order given (the caller
    passes them sorted by node id) — deterministic across retries.
    """
    from opengemini_tpu.ops import aggregates as aggmod

    gid_of = {k: i for i, k in enumerate(group_keys)}
    for doc in peer_docs:
        for gtags in doc["groups"]:
            key = tuple(gtags.get(k, "") for k in group_tags)
            if key not in gid_of:
                gid_of[key] = len(group_keys)
                group_keys.append(key)
    n_seg = len(group_keys) * W

    def expand(arr, fill=0):
        arr = np.asarray(arr)
        if len(arr) == n_seg:
            return arr
        out = np.full(n_seg, fill, dtype=arr.dtype if fill == 0 else np.float64)
        out[: len(arr)] = arr
        return out

    # per-peer segment index maps (peer-local seg -> global seg)
    peer_maps = []
    for doc in peer_docs:
        gmap = np.array(
            [gid_of[tuple(g.get(k, "") for k in group_tags)] for g in doc["groups"]],
            dtype=np.int64,
        )
        if len(gmap):
            segs = (gmap[:, None] * W + np.arange(W)[None, :]).reshape(-1)
        else:
            segs = np.empty(0, np.int64)
        peer_maps.append(segs)

    def scatter(doc_i, field, pname, fill, dtype=np.float64):
        """Peer partial array -> global-shaped array with `fill` holes.
        dtype=int64 keeps ns timestamps exact (they do not fit f64)."""
        out = np.full(n_seg, fill, dtype)
        arrs = peer_docs[doc_i]["fields"].get(field)
        segs = peer_maps[doc_i]
        if arrs is None or pname not in arrs or not len(segs):
            return out
        a = np.asarray(arrs[pname], dtype)
        out[segs[: len(a)]] = a
        return out

    def peer_counts(field):
        return [
            scatter(i, field, "count", 0).astype(np.int64)
            for i in range(len(peer_docs))
        ]

    for call, spec, params, fname in aggs:
        if spec.name in MULTISET_MERGEABLE:
            entry = agg_results[id(call)]
            l_counts = entry[2]
            pc = peer_counts(fname)
            total_counts = expand(l_counts) + sum(pc)
            out = _merge_multiset(
                spec, params, entry, batches[fname], l_counts, fname,
                peer_docs, peer_maps, n_seg,
            )
            agg_results[id(call)] = (
                out, None, total_counts, spec, fname, None)
            continue
        if spec.name not in MERGEABLE:
            continue
        entry = agg_results[id(call)]
        l_out, l_counts = entry[0], entry[2]
        n_local = len(l_counts)
        pc = peer_counts(fname)
        total_counts = expand(l_counts) + sum(pc)
        times_abs = None

        if spec.name == "count":
            out = expand(np.asarray(l_out, np.int64)) + sum(pc)
        elif spec.name == "sum":
            # int sums stay int64 end-to-end (exact beyond 2^53) when
            # every source shipped int64 partials
            raws = [
                (peer_maps[i], np.asarray(peer_docs[i]["fields"][fname]["sum"]))
                for i in range(len(peer_docs))
                if "sum" in peer_docs[i]["fields"].get(fname, {})
            ]
            all_int = np.asarray(l_out).dtype.kind in "iu" and all(
                a.dtype.kind in "iu" for _s, a in raws
            )
            acc = expand(
                np.asarray(l_out, np.int64 if all_int else np.float64)
            ).copy()
            for segs, a in raws:
                if len(segs) and len(a):
                    acc[segs[: len(a)]] += a.astype(acc.dtype)
            out = acc
        elif spec.name == "mean":
            # local sum = local mean * local count — recovered from the
            # FINAL local entry so pre-aggregation fast-path contributions
            # (which never enter the device batch) are included
            l_sum = np.asarray(l_out, np.float64) * np.asarray(
                l_counts, np.float64
            )
            total_sum = expand(l_sum) + sum(
                scatter(i, fname, "sum", 0) for i in range(len(peer_docs))
            )
            out = total_sum / np.maximum(total_counts, 1)
        elif spec.name in ("min", "max", "first", "last"):
            out, times_abs = _merge_selector(
                spec.name, entry, batches[fname], l_counts, pc, fname,
                peer_docs, scatter, expand, n_seg,
            )
        elif spec.name == "spread":
            mn, _t1 = _merge_selector(
                "min", None, batches[fname], l_counts, pc, fname,
                peer_docs, scatter, expand, n_seg, local_spec="min",
            )
            mx, _t2 = _merge_selector(
                "max", None, batches[fname], l_counts, pc, fname,
                peer_docs, scatter, expand, n_seg, local_spec="max",
            )
            out = mx - mn
            if np.asarray(entry[0]).dtype.kind in "iu":
                out = np.rint(out).astype(np.int64)
        elif spec.name == "stddev":
            out = _merge_stddev(
                entry, batches[fname], l_counts, pc, fname, peer_docs,
                scatter, expand, n_seg,
            )
        else:  # pragma: no cover — MERGEABLE guard above
            continue

        agg_results[id(call)] = (out, None, total_counts, spec, fname, times_abs)


def _merge_multiset(spec, params, entry, batch, l_counts, fname, peer_docs,
                    peer_maps, n_seg):
    """Exact cluster-wide rank aggregate from per-segment (value, count)
    multisets: local batch rows + every peer's shipped trio, combined and
    rank-selected with the SAME semantics as the device kernels
    (ops/segment.py seg_percentile nearest-rank, seg_median two-middle
    mean, seg_count_distinct)."""
    n_local = len(l_counts)
    lv, lc, loffs = batch.host_value_multiset(n_local)
    segs_all = [np.repeat(np.arange(n_local, dtype=np.int64),
                          np.diff(loffs))]
    vals_all = [lv]
    cnts_all = [lc]
    for i, doc in enumerate(peer_docs):
        arrs = doc["fields"].get(fname) or {}
        if "mvals" not in arrs or not len(peer_maps[i]):
            continue
        offs = np.asarray(arrs["moffs"], np.int64)
        pv = np.asarray(arrs["mvals"], np.float64)
        pcn = np.asarray(arrs["mcnts"], np.int64)
        per_seg = np.diff(offs)
        local_seg = np.repeat(np.arange(len(per_seg), dtype=np.int64), per_seg)
        segs_all.append(peer_maps[i][local_seg])
        vals_all.append(pv)
        cnts_all.append(pcn)
    seg = np.concatenate(segs_all)
    val = np.concatenate(vals_all)
    cnt = np.concatenate(cnts_all)
    if len(seg) == 0:
        dtype = np.int64 if spec.int_output else np.float64
        return np.zeros(n_seg, dtype)
    order = np.lexsort((val, seg))
    seg, val, cnt = seg[order], val[order], cnt[order]
    totals = np.bincount(seg, weights=cnt, minlength=n_seg).astype(np.int64)

    if spec.name == "count_distinct":
        head = np.empty(len(seg), np.bool_)
        head[0] = True
        head[1:] = (seg[1:] != seg[:-1]) | (val[1:] != val[:-1])
        return np.bincount(seg[head], minlength=n_seg).astype(np.int64)

    csum = np.cumsum(cnt)
    first_run = np.searchsorted(seg, np.arange(n_seg), "left")
    base = np.where(first_run > 0, csum[np.maximum(first_run, 1) - 1], 0)

    def value_at_rank(rank):
        """rank is 1-indexed within each segment."""
        target = base + np.clip(rank, 1, np.maximum(totals, 1))
        idx = np.searchsorted(csum, target, "left")
        return val[np.clip(idx, 0, len(val) - 1)]

    if spec.name == "percentile":
        q = float(params[0]) if params else 50.0
        rank = np.ceil(q / 100.0 * totals).astype(np.int64)
        out = value_at_rank(rank)
    else:  # median: mean of the two middle values
        lo = value_at_rank((totals - 1) // 2 + 1)
        hi = value_at_rank(totals // 2 + 1)
        out = (lo + hi) / 2.0
    if np.asarray(entry[0]).dtype.kind in "iu" and spec.name == "percentile":
        out = np.rint(out).astype(np.int64)
    return np.where(totals > 0, out, 0.0 if out.dtype.kind == "f" else 0)


def _local_selector(batch, spec_name, n_local):
    from opengemini_tpu.ops import aggregates as aggmod

    out, sel, counts = batch.run(aggmod.get(spec_name), n_local)
    times = batch.host_times()
    if sel is not None and len(times):
        t = times[np.clip(np.asarray(sel), 0, len(times) - 1)]
    else:
        t = np.zeros(n_local, np.int64)
    return np.asarray(out, np.float64), np.asarray(t, np.int64), counts


def _merge_selector(
    name, entry, batch, l_counts, pc, fname, peer_docs, scatter, expand,
    n_seg, local_spec=None,
):
    """Merge a value+time selector across local + peers.

    min/max pick the extreme VALUE (time = that point's time); first/last
    pick the extreme TIME. Ties resolve to the earliest source in stack
    order (local, then peers by node id) — one real point, deterministic."""
    n_local = len(l_counts) if entry is None else len(entry[2])
    if entry is not None and entry[1] is not None:
        l_out = np.asarray(entry[0], np.float64)
        times = batch.host_times()
        l_t = (
            times[np.clip(np.asarray(entry[1]), 0, len(times) - 1)]
            if len(times) else np.zeros(n_local, np.int64)
        )
    else:
        l_out, l_t, _c = _local_selector(batch, local_spec or name, n_local)
    l_present = expand(l_counts[:n_local] if entry is None else entry[2]) > 0
    vals = [expand(l_out)]
    ts = [expand(l_t).astype(np.int64)]
    present = [l_present]
    for i in range(len(peer_docs)):
        vals.append(scatter(i, fname, name + "_v", np.nan))
        ts.append(scatter(i, fname, name + "_t", 0, np.int64))
        present.append(pc[i] > 0)
    V = np.stack(vals)
    T = np.stack(ts)
    P = np.stack(present)
    if name in ("min", "max"):
        # value ties break by EARLIEST timestamp — same rule as the
        # single-device kernels (ops/segment.py) and the mesh merge
        key = np.where(P, V, np.inf if name == "min" else -np.inf)
        best = key.min(0) if name == "min" else key.max(0)
        cand = P & (V == best[None, :])
        tkey = np.where(cand, T, _BIG)
        pick = np.argmin(tkey, 0)
    else:
        key = np.where(P, T, _BIG if name == "first" else -_BIG)
        tbest = key.min(0) if name == "first" else key.max(0)
        cand = P & (T == tbest[None, :])
        # exact-time ties across sources: larger value wins (reference
        # FirstReduce/LastReduce); remaining ties to stack order
        vbest = np.where(cand, V, -np.inf).max(0)
        cand &= V == vbest[None, :]
        pick = np.argmax(cand, 0)
    idx = (pick, np.arange(n_seg))
    return V[idx], T[idx]


def _merge_stddev(
    entry, batch, l_counts, pc, fname, peer_docs, scatter, expand, n_seg,
):
    """Chan et al. pairwise (n, mean, M2) combine across sources."""
    from opengemini_tpu.ops import aggregates as aggmod

    n_local = len(entry[2])
    l_sd = np.asarray(entry[0], np.float64)
    l_mean, _s, _c = batch.run(aggmod.get("mean"), n_local)
    n = expand(entry[2]).astype(np.float64)
    mean = expand(np.asarray(l_mean, np.float64))
    m2 = expand(l_sd) ** 2 * np.maximum(n - 1, 0)
    for i in range(len(peer_docs)):
        nb = pc[i].astype(np.float64)
        mb = scatter(i, fname, "mean", 0.0)
        m2b = scatter(i, fname, "m2", 0.0)
        tot = n + nb
        safe = np.maximum(tot, 1)
        delta = mb - mean
        mean = np.where(tot > 0, (n * mean + nb * mb) / safe, 0.0)
        m2 = m2 + m2b + delta * delta * n * nb / safe
        n = tot
    return np.sqrt(np.maximum(m2 / np.maximum(n - 1, 1), 0.0))
