"""JOIN and UNION execution over raw row sets.

Reference: engine/executor/logic_plan.go:3679 (LogicalJoin),
sort_merge_join_transform.go / hash_join_transform.go, join_rule.go
(MatchSortMergeJoin: join keys within the GROUP BY subset), and the
behavior tables in tests/server_test.go (TestServer_Join_Table,
TestServer_HashJoin_Table, TestServer_Union_Table).

Model (validated against the reference's expected outputs):
  - each side evaluates as a raw per-series row set with tags preserved;
  - rows join per ON-tag-key equality, optionally requiring equal
    timestamps when the ON clause contains `l.time = r.time`;
  - the LEFT side drives in (time, series) order: inner/left/outer/full
    emit the left row's timestamp, right joins emit the matched right
    row's timestamp; unmatched non-driving rows append afterwards in
    (key, row) order;
  - `outer join` null-fills the missing side, `full join` zero-fills
    numeric columns (observed reference behavior);
  - `select *` expands each side's fields plus any tags not consumed by
    the outer GROUP BY, qualified `label.name`, alphabetically.
"""

from __future__ import annotations

import copy

from opengemini_tpu.sql import ast

__all__ = ["select_join", "execute_union", "JoinError"]


class JoinError(ValueError):
    pass


def _source_label(src) -> str:
    alias = getattr(src, "alias", "")
    if alias:
        return alias
    if isinstance(src, ast.Measurement) and src.name:
        return src.name
    raise JoinError("join sources need a name or alias")


def _side_rows(executor, src, db: str, now_ns: int, condition, ctes):
    """Evaluate one join side into (label, series_list) where each series
    is {'tags': dict, 'columns': [names], 'rows': [[t, v...], ...]}."""
    label = _source_label(src)
    if isinstance(src, ast.Measurement):
        inner_src = ast.Measurement(
            name=src.name, regex=src.regex, database=src.database, rp=src.rp
        )
    else:
        stmt = copy.copy(src.stmt)
        if not stmt.group_by_tags and not stmt.group_by_all_tags:
            # raw subquery sides must keep series tags for the ON keys
            stmt = copy.copy(stmt)
            stmt.group_by_all_tags = True
        inner_src = ast.SubQuery(stmt)
    inner = ast.SelectStatement(
        fields=[ast.Field(ast.Wildcard())],
        sources=[inner_src],
        condition=condition,
        group_by_all_tags=True,
    )
    inner.ctes = ctes
    res = executor._select(inner, db, now_ns)
    series = []
    for s in res.get("series", []):
        series.append({
            "tags": s.get("tags", {}) or {},
            "columns": s["columns"][1:],  # strip time
            "rows": s["values"],
        })
    series.sort(key=lambda s: tuple(sorted(s["tags"].items())))
    return label, series


def _parse_on(on, llabel: str, rlabel: str):
    """ON conjunction -> ([(ltag, rtag)], time_eq). Only tag equality and
    l.time = r.time are supported (reference MatchSortMergeJoin rule 1)."""
    pairs: list[tuple[str, str]] = []
    time_eq = False

    def strip(e):
        while isinstance(e, ast.ParenExpr):
            e = e.expr
        return e

    def walk(e):
        nonlocal time_eq
        e = strip(e)
        if isinstance(e, ast.BinaryExpr) and e.op == "AND":
            walk(e.lhs)
            walk(e.rhs)
            return
        if not (isinstance(e, ast.BinaryExpr) and e.op == "="):
            raise JoinError("join ON supports only equality conditions")
        l, r = strip(e.lhs), strip(e.rhs)
        if not (isinstance(l, ast.VarRef) and isinstance(r, ast.VarRef)):
            raise JoinError("join ON operands must be column references")
        lname, rname = l.name, r.name
        if not (lname.startswith(llabel + ".") and rname.startswith(rlabel + ".")):
            # allow reversed order r.x = l.x
            if rname.startswith(llabel + ".") and lname.startswith(rlabel + "."):
                lname, rname = rname, lname
            else:
                raise JoinError(
                    f"join ON references must qualify {llabel!r} and {rlabel!r}")
        lkey = lname[len(llabel) + 1:]
        rkey = rname[len(rlabel) + 1:]
        if lkey.lower() == "time" and rkey.lower() == "time":
            time_eq = True
            return
        pairs.append((lkey, rkey))

    walk(on)
    if not pairs:
        raise JoinError("join ON requires at least one tag equality")
    return pairs, time_eq


def _split_where(condition, llabel: str, rlabel: str):
    """Split the outer WHERE's top-level AND terms per join side: time-only
    terms go to both, `label.x`-qualified terms to their side (prefix
    stripped), anything else is rejected — pushing a one-side field
    predicate to the other side would zero it out."""
    if condition is None:
        return None, None
    terms: list = []

    def flatten_and(e):
        while isinstance(e, ast.ParenExpr):
            e = e.expr
        if isinstance(e, ast.BinaryExpr) and e.op.upper() == "AND":
            flatten_and(e.lhs)
            flatten_and(e.rhs)
        else:
            terms.append(e)

    flatten_and(condition)

    def refs_of(e, acc):
        if isinstance(e, ast.VarRef):
            acc.append(e.name)
        elif isinstance(e, ast.BinaryExpr):
            refs_of(e.lhs, acc)
            refs_of(e.rhs, acc)
        elif isinstance(e, (ast.ParenExpr, ast.UnaryExpr)):
            refs_of(e.expr, acc)

    def strip_label(e, label):
        if isinstance(e, ast.VarRef) and e.name.startswith(label + "."):
            return ast.VarRef(e.name[len(label) + 1:])
        if isinstance(e, ast.BinaryExpr):
            return ast.BinaryExpr(
                e.op, strip_label(e.lhs, label), strip_label(e.rhs, label))
        if isinstance(e, ast.ParenExpr):
            return ast.ParenExpr(strip_label(e.expr, label))
        if isinstance(e, ast.UnaryExpr):
            return ast.UnaryExpr(e.op, strip_label(e.expr, label))
        return e

    lterms, rterms = [], []
    for t in terms:
        acc: list[str] = []
        refs_of(t, acc)
        non_time = [r for r in acc if r.lower() != "time"]
        if not non_time:
            lterms.append(t)
            rterms.append(t)
        elif all(r.startswith(llabel + ".") for r in non_time):
            lterms.append(strip_label(t, llabel))
        elif all(r.startswith(rlabel + ".") for r in non_time):
            rterms.append(strip_label(t, rlabel))
        else:
            raise JoinError(
                "join WHERE predicates must qualify one side "
                f"({llabel!r} or {rlabel!r}) or reference time only")

    def conj(ts):
        out = None
        for t in ts:
            out = t if out is None else ast.BinaryExpr("AND", out, t)
        return out

    return conj(lterms), conj(rterms)


def _flatten(series):
    """[(t, tags, {field: val}, series_idx)] in (time, series) order."""
    out = []
    for si, s in enumerate(series):
        cols = s["columns"]
        for row in s["rows"]:
            t = row[0]
            out.append((t, s["tags"], dict(zip(cols, row[1:])), si))
    out.sort(key=lambda r: (r[0], r[3]))
    return out


def _side_columns(series) -> list[str]:
    cols: set[str] = set()
    tags: set[str] = set()
    for s in series:
        cols.update(s["columns"])
        tags.update(s["tags"].keys())
    return sorted(cols), sorted(tags)


def select_join(executor, stmt, join_src, db: str, now_ns: int) -> list[dict]:
    from opengemini_tpu.query.executor import QueryError, _strip_expr

    if isinstance(join_src.left, ast.JoinSource) or isinstance(
            join_src.right, ast.JoinSource):
        raise QueryError("cascading joins are not supported yet")
    for f in stmt.fields:
        e = _strip_expr(f.expr)
        if isinstance(e, ast.Call):
            raise QueryError("aggregates over joins are not supported yet")

    llabel = _source_label(join_src.left)
    rlabel = _source_label(join_src.right)
    try:
        lcond, rcond = _split_where(stmt.condition, llabel, rlabel)
        pairs, time_eq = _parse_on(join_src.on, llabel, rlabel)
    except JoinError as e:
        raise QueryError(str(e)) from None
    llabel, lseries = _side_rows(
        executor, join_src.left, db, now_ns, lcond, stmt.ctes)
    rlabel, rseries = _side_rows(
        executor, join_src.right, db, now_ns, rcond, stmt.ctes)
    kind = join_src.kind

    lrows = _flatten(lseries)
    rrows = _flatten(rseries)
    lfields, ltags = _side_columns(lseries)
    rfields, rtags = _side_columns(rseries)

    # ON keys must be tags: a FIELD key would silently degrade to "" on
    # every row and produce a cartesian product
    for lt, rt in pairs:
        if lt in lfields and lt not in ltags:
            raise QueryError(f"join ON key {lt!r} is a field of {llabel!r}; "
                             "joins support tag keys only")
        if rt in rfields and rt not in rtags:
            raise QueryError(f"join ON key {rt!r} is a field of {rlabel!r}; "
                             "joins support tag keys only")

    def lkey(tags):
        return tuple(tags.get(lt, "") for lt, _ in pairs)

    def rkey(tags):
        return tuple(tags.get(rt, "") for _, rt in pairs)

    rindex: dict[tuple, list[int]] = {}
    for i, (t, tags, vals, si) in enumerate(rrows):
        rindex.setdefault(rkey(tags), []).append(i)

    matched_right: set[int] = set()
    # out rows: (out_time, drive_tags, ltags, lvals, rtags, rvals)
    out_rows = []
    for t, tags, vals, _si in lrows:
        key = lkey(tags)
        cands = rindex.get(key, [])
        if time_eq:
            cands = [i for i in cands if rrows[i][0] == t]
        if cands:
            for i in cands:
                matched_right.add(i)
                rt, rtg, rvals, _ = rrows[i]
                out_time = rt if kind == "right" else t
                out_rows.append((out_time, tags, tags, vals, rtg, rvals))
        else:
            if kind in ("left", "outer", "full"):
                out_rows.append((t, tags, tags, vals, None, None))
            # inner/right: unmatched left dropped
    if kind in ("right", "outer", "full"):
        unmatched = [i for i in range(len(rrows)) if i not in matched_right]
        unmatched.sort(key=lambda i: (rkey(rrows[i][1]), i))
        for i in unmatched:
            rt, rtg, rvals, _ = rrows[i]
            out_rows.append((rt, rtg, None, None, rtg, rvals))

    # ---- output columns ----
    group_tags = list(stmt.group_by_tags)
    out_name = f"{llabel},{rlabel}"

    def expand_side(label, fields, tags):
        names = set(fields) | {t for t in tags if t not in group_tags}
        return [(label, n) for n in sorted(names)]

    col_plan: list[tuple[str, str]] = []  # (side_label, name) per column
    columns = ["time"]
    for f in stmt.fields:
        e = _strip_expr(f.expr)
        if isinstance(e, ast.Wildcard):
            for side in (expand_side(llabel, lfields, ltags)
                         + expand_side(rlabel, rfields, rtags)):
                col_plan.append(side)
                columns.append(f"{side[0]}.{side[1]}")
        elif isinstance(e, ast.VarRef):
            name = e.name
            if name.endswith(".*"):
                lab = name[:-2]
                if lab == llabel:
                    sides = expand_side(llabel, lfields, ltags)
                elif lab == rlabel:
                    sides = expand_side(rlabel, rfields, rtags)
                else:
                    raise QueryError(f"unknown join side {lab!r}")
                for side in sides:
                    col_plan.append(side)
                    columns.append(f"{side[0]}.{side[1]}")
                continue
            if "." in name:
                lab, _, fldname = name.partition(".")
                if lab not in (llabel, rlabel):
                    raise QueryError(f"unknown join side {lab!r} in {name!r}")
            else:
                lab = llabel if name in lfields or name in ltags else rlabel
                fldname = name
            col_plan.append((lab, fldname))
            columns.append(f.alias or f"{lab}.{fldname}")
        else:
            raise QueryError(
                "join select supports fields, qualified refs and * only")

    # numeric columns for full-join zero fill (computed once per side)
    def _numeric_map(series):
        out: dict[str, bool] = {}
        for s in series:
            for ci, name in enumerate(s["columns"]):
                if out.get(name):
                    continue
                for row in s["rows"]:
                    v = row[ci + 1]
                    if v is not None:
                        out[name] = (isinstance(v, (int, float))
                                     and not isinstance(v, bool))
                        break
        return out

    numeric_l = _numeric_map(lseries)
    numeric_r = _numeric_map(rseries)

    def is_numeric(lab, name):
        return (numeric_l if lab == llabel else numeric_r).get(name, False)

    def cell(lab, name, tags, vals):
        if vals is None:
            if kind == "full" and is_numeric(lab, name):
                return 0
            return None
        if name in vals:
            return vals[name]
        if tags is not None:
            side_tags = ltags if lab == llabel else rtags
            if name in side_tags:
                return tags.get(name, "")
        return None

    # ---- group + render ----
    grouped: dict[tuple, list] = {}
    for out_time, dtags, ltg, lvals, rtg, rvals in out_rows:
        gkey = tuple(dtags.get(t, "") for t in group_tags)
        row = [out_time]
        for lab, name in col_plan:
            if lab == llabel:
                row.append(cell(lab, name, ltg, lvals))
            else:
                row.append(cell(lab, name, rtg, rvals))
        grouped.setdefault(gkey, []).append(row)

    out_series = []
    for gkey in sorted(grouped):
        rows = grouped[gkey]
        if not stmt.ascending:
            rows = list(reversed(rows))
        if stmt.offset:
            rows = rows[stmt.offset:]
        if stmt.limit:
            rows = rows[: stmt.limit]
        if not rows:
            continue
        series = {"name": out_name, "columns": columns, "values": rows}
        if group_tags:
            series["tags"] = dict(zip(group_tags, gkey))
        out_series.append(series)
    return out_series



# ---------------------------------------------------------------------------
# UNION


def _type_class(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return "boolean"
    if isinstance(v, (int, float)):
        return "number"
    return "string"


def _col_types(cols, rows):
    types = {}
    for ci, c in enumerate(cols):
        if c == "time":
            continue
        for _nm, row in rows:
            tc = _type_class(row[ci])
            if tc is not None:
                types[c] = tc
                break
    return types


def _eval_union_side(executor, s, db: str, now_ns: int):
    """Terminal union side -> (cols, [(side_name, row)]).

    Layout per side (observed reference union tables): time, the side's
    own output columns, its GROUP BY tags (sorted), then remaining tags
    (sorted).  Tag columns materialize only for wildcard selects — an
    explicit field list never grows tag columns."""
    from opengemini_tpu.query.executor import (
        _classify_select, _inner_source_name, _strip_expr)

    name = _inner_source_name(s)
    has_wild = any(
        isinstance(_strip_expr(f.expr), ast.Wildcard) for f in s.fields
    )
    run_stmt = s
    if has_wild and _classify_select(s) == "raw" and not s.group_by_all_tags:
        run_stmt = copy.copy(s)
        run_stmt.group_by_all_tags = True
    res = executor._select(run_stmt, db, now_ns)
    series = res.get("series", [])
    if not series:
        return None
    base_cols = series[0]["columns"]
    group_tags = sorted(s.group_by_tags)
    rows = []
    tag_cols: list[str] = []
    if has_wild:
        all_tags = sorted({k for ser in series for k in (ser.get("tags") or {})})
        tag_cols = group_tags + [t for t in all_tags if t not in group_tags]
    cols = list(base_cols) + tag_cols
    for ser in series:
        if ser["columns"] != base_cols:
            raise JoinError("union sides must produce uniform columns")
        tags = ser.get("tags") or {}
        extra = [tags.get(t, "") for t in tag_cols]
        for row in ser["values"]:
            rows.append((name, list(row) + extra))
    # within a side, rows order by (time, values in alphabetical column
    # order) — the reference's observed union row order
    order_ix = [0] + sorted(range(1, len(cols)), key=lambda i: cols[i])

    def _key(item):
        _nm, row = item
        return tuple(
            (0, row[i]) if row[i] is not None else (1, "")
            for i in order_ix
        )

    rows.sort(key=_key)
    return cols, rows


def execute_union(executor, stmt, db: str, now_ns: int) -> dict:
    from opengemini_tpu.query.executor import QueryError

    def eval_unit(s):
        if isinstance(s, ast.UnionStatement):
            return _fold_union(executor, s, db, now_ns)
        try:
            return _eval_union_side(executor, s, db, now_ns)
        except JoinError as e:
            raise QueryError(str(e)) from None

    def _fold_union(executor, ustmt, db, now_ns):
        units = [eval_unit(s) for s in ustmt.selects]
        acc = None
        for unit, (all_, by_name) in zip(units, [(True, False)] + ustmt.combines):
            if unit is None:
                continue
            cols, rows = unit
            types = _col_types(cols, rows)
            if acc is None:
                acc_cols, acc_rows, acc_types = list(cols), list(rows), types
                acc = True
                continue
            if by_name:
                for c, tc in types.items():
                    if c in acc_types and acc_types[c] != tc:
                        raise QueryError(
                            "columns with same name must have the same data "
                            "type when using union by name/union all by name")
                merged = ["time"] + sorted((set(acc_cols) | set(cols)) - {"time"})
                old_ix = [acc_cols.index(c) if c in acc_cols else None for c in merged]
                new_ix = [cols.index(c) if c in cols else None for c in merged]
                acc_rows = [
                    (nm, [row[i] if i is not None else None for i in old_ix])
                    for nm, row in acc_rows
                ]
                acc_rows += [
                    (nm, [row[i] if i is not None else None for i in new_ix])
                    for nm, row in rows
                ]
                acc_cols = merged
                acc_types.update(types)
            else:
                if len(cols) != len(acc_cols):
                    raise QueryError(
                        "union/union all can only apply to expressions with "
                        "the same number of result columns")
                for ci in range(len(acc_cols)):
                    tc_old = acc_types.get(acc_cols[ci])
                    tc_new = types.get(cols[ci])
                    if tc_old and tc_new and tc_old != tc_new:
                        raise QueryError(
                            "columns in the same index position must have the "
                            "same data type when using union/union all")
                acc_rows += [(nm, list(row)) for nm, row in rows]
            if not all_:
                seen, dedup = set(), []
                for nm, row in acc_rows:
                    k = tuple(row)
                    if k not in seen:
                        seen.add(k)
                        dedup.append((nm, row))
                acc_rows = dedup
        if acc is None:
            return None
        return acc_cols, acc_rows

    folded = _fold_union(executor, stmt, db, now_ns)
    if folded is None:
        return {}
    cols, rows = folded
    # final columns sort alphabetically (time first); values were already
    # name-mapped during the fold
    order_ix = [0] + sorted(range(1, len(cols)), key=lambda i: cols[i])
    cols = [cols[i] for i in order_ix]
    rows = [(nm, [row[i] for i in order_ix]) for nm, row in rows]
    # block-sort rows by source name (stable within a side), matching the
    # reference's sorted compound series name
    rows.sort(key=lambda nr: nr[0])
    names = sorted({nm for nm, _ in rows})
    name = ",".join(names) if names else "union"
    return {"series": [{"name": name,
                        "columns": cols,
                        "values": [row for _nm, row in rows]}]}
