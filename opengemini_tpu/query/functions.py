"""Host-side function machinery: transforms and host-only aggregators.

Reference: engine/executor transforms (difference, derivative,
cumulative_sum, moving_average, elapsed — one transform file each,
SURVEY.md §2.3) and call processors for mode/integral/top/bottom/sample.

The device path (models/templates.py) executes the hot aggregates; any
SELECT containing a call outside that set falls back to this host path,
which evaluates per (group, window) over time-sorted numpy rows. This
mirrors the reference's split between pushdown-able aggregates and
sql-side transforms.
"""

from __future__ import annotations

import math

import numpy as np

NS = 1_000_000_000


def py_value(v):
    """numpy scalar -> python value; strings pass through. Non-finite
    floats become None: every caller feeds JSON row output, where a bare
    NaN/Infinity literal is not strict JSON (influx marshals null)."""
    out = v.item() if hasattr(v, "item") else v
    if isinstance(out, float) and not math.isfinite(out):
        return None
    return out

# transforms: f(times, values) -> (out_times, out_values); applied per
# series-group over raw points, or over the window-aggregated sequence
TRANSFORMS = {
    "derivative",
    "non_negative_derivative",
    "difference",
    "non_negative_difference",
    "cumulative_sum",
    "moving_average",
    "elapsed",
    "holt_winters",
    "holt_winters_with_fit",
}

# host aggregators: one value per (group, window)
HOST_AGGS = {"mode", "integral", "sum", "count", "mean", "min", "max",
             "first", "last", "spread", "stddev", "median", "percentile",
             "percentile_ogsketch", "count_distinct", "rate", "irate",
             "absent", "regr_slope"}

# multi-row selectors: several output rows per group
MULTI_ROW = {"top", "bottom", "sample", "distinct", "detect"}


def _dedup_duplicate_times(times: np.ndarray, values: np.ndarray):
    """Collapse runs of equal timestamps to one point (several series can
    share an instant in a merged raw sequence). The reference
    difference/derivative iterators keep the first point per distinct
    timestamp and skip the rest (agg_iterator.gen.go
    FloatDifferenceItem.AppendItemFastFunc: `if st == times[i] {continue}`);
    its merge heap breaks time ties arbitrarily (merge_transform.go
    HeapItems.Less is non-strict on equal keys), and the acceptance output
    (TestServer_difference_derivative_time_duplicate) has the smallest
    value winning — made deterministic here."""
    if len(times) < 2:
        return times, values
    change = np.empty(len(times), bool)
    change[0] = True
    np.not_equal(times[1:], times[:-1], out=change[1:])
    if change.all():
        return times, values
    starts = np.flatnonzero(change)
    ends = np.append(starts[1:], len(times))
    keep = np.array([s + int(np.argmin(values[s:e]))
                     for s, e in zip(starts, ends)])
    return times[keep], values[keep]


# transforms whose reference iterators skip duplicate timestamps
_DEDUP_TRANSFORMS = {
    "difference", "non_negative_difference",
    "derivative", "non_negative_derivative",
}


def transform(name: str, times: np.ndarray, values: np.ndarray, params: tuple):
    """Apply a transform over one (time-sorted) sequence; None values must
    already be removed. Returns (times, values)."""
    if len(times) == 0:
        return times, values
    if name in _DEDUP_TRANSFORMS:
        times, values = _dedup_duplicate_times(times, values)
    if name in ("derivative", "non_negative_derivative"):
        unit_ns = params[0] if params else NS
        if len(times) < 2:
            return times[:0], values[:0]
        dv = np.diff(values)
        dt = np.diff(times)
        dt = np.where(dt == 0, 1, dt)
        out = dv / (dt / unit_ns)
        t_out = times[1:]
        if name == "non_negative_derivative":
            keep = out >= 0
            return t_out[keep], out[keep]
        return t_out, out
    if name in ("difference", "non_negative_difference"):
        if len(times) < 2:
            return times[:0], values[:0]
        out = np.diff(values)  # 'behind' (default): v[i] - v[i-1]
        mode = params[0] if params and isinstance(params[0], str) else "behind"
        if mode == "front":
            out = -out
        elif mode == "absolute":
            out = np.abs(out)
        t_out = times[1:]
        if name == "non_negative_difference":
            keep = out >= 0
            return t_out[keep], out[keep]
        return t_out, out
    if name == "cumulative_sum":
        return times, np.cumsum(values)
    if name == "moving_average":
        n = int(params[0]) if params else 2
        if n < 1 or len(values) < n:
            return times[:0], values[:0]
        kernel = np.ones(n) / n
        out = np.convolve(values, kernel, mode="valid")
        return times[n - 1 :], out
    if name == "elapsed":
        unit_ns = params[0] if params else 1  # default ns
        if len(times) < 2:
            return times[:0], values[:0]
        return times[1:], (np.diff(times) // unit_ns).astype(np.int64)
    if name in ("holt_winters", "holt_winters_with_fit"):
        n_forecast = int(params[0]) if params else 1
        season = int(params[1]) if len(params) > 1 else 0
        return holt_winters(times, np.asarray(values, np.float64), n_forecast,
                            season, name.endswith("_with_fit"))
    raise ValueError(f"unsupported transform {name!r}")


def host_agg(name: str, times: np.ndarray, values: np.ndarray, params: tuple):
    """One aggregate value over one window's points; returns (value, time_ns
    | None). None value means null."""
    if len(values) == 0:
        return None, None
    if name == "count":
        return int(len(values)), None
    if name == "sum":
        return values.sum().item(), None
    if name == "mean":
        return float(values.mean()), None
    if name == "min":
        i = int(np.argmin(values))
        return py_value(values[i]), int(times[i])
    if name == "max":
        i = int(np.argmax(values))
        return py_value(values[i]), int(times[i])
    if name == "first":
        return py_value(values[0]), int(times[0])
    if name == "last":
        return py_value(values[-1]), int(times[-1])
    if name == "spread":
        return (values.max() - values.min()).item(), None
    if name == "stddev":
        if len(values) < 2:
            return None, None
        return float(values.std(ddof=1)), None
    if name == "median":
        return float(np.median(values)), None
    if name == "percentile":
        # percentile is a SELECTOR in influx: it returns an actual sample,
        # and without GROUP BY time() the row carries that sample's OWN
        # timestamp (server_test.go Selectors 'percentile'); earliest
        # point wins a value tie
        q = params[0]
        # influx nearest-rank: floor(n*q/100 + 0.5) - 1; an index below 0
        # means NO qualifying sample (nil), not the minimum
        # (FloatPercentileReduceSlice)
        rank = int(np.floor(q / 100.0 * len(values) + 0.5)) - 1
        if rank < 0 or rank >= len(values):
            return None, None
        order = np.argsort(values, kind="stable")
        i = int(order[rank])
        hits = np.flatnonzero(values == values[i])
        sel_t = int(times[hits[np.argmin(times[hits])]]) if len(hits) \
            else int(times[i])
        return py_value(values[i]), sel_t
    if name == "percentile_ogsketch":
        # centroid-sketch quantile (reference percentile_ogsketch,
        # call_processor.go:41): O(compression) memory per window however
        # many rows feed it, mergeable across nodes (query/sketch.py)
        from opengemini_tpu.query.sketch import OGSketch

        q = params[0]
        sk = OGSketch()
        sk.insert(np.asarray(values, np.float64))
        out = sk.quantile(q / 100.0)
        return (None if math.isnan(out) else float(out)), None
    if name == "count_distinct":
        return int(len(np.unique(values))), None
    if name == "mode":
        # most frequent; ties -> smallest value (influx semantics)
        uniq, counts = np.unique(values, return_counts=True)
        return py_value(uniq[np.argmax(counts)]), None
    if name == "integral":
        unit_ns = params[0] if params else NS
        if len(values) < 2:
            return 0.0, None
        dt = np.diff(times) / unit_ns
        areas = (values[1:] + values[:-1]) / 2 * dt
        return float(areas.sum()), None
    if name == "rate":
        # (last - first) / elapsed-seconds (openGemini InfluxQL rate,
        # TestServer_Query_Null_Aggregate#22)
        if len(values) < 2 or times[-1] == times[0]:
            return None, None
        dt_s = (int(times[-1]) - int(times[0])) / NS
        return float((values[-1] - values[0]) / dt_s), None
    if name == "irate":
        # slope of the LAST sample pair (Null_Aggregate#23)
        if len(values) < 2 or times[-1] == times[-2]:
            return None, None
        dt_s = (int(times[-1]) - int(times[-2])) / NS
        return float((values[-1] - values[-2]) / dt_s), None
    if name == "absent":
        return 1, None  # any data in range -> 1 (Null_Aggregate#24)
    if name == "regr_slope":
        # least-squares slope against the SAMPLE ORDINAL, not wall time
        # (verified against Null_Aggregate#32: gaps in the time axis do
        # not stretch the x spacing)
        if len(values) < 2:
            return None, None
        x = np.arange(len(values), dtype=np.float64)
        v = values.astype(np.float64)
        xc = x - x.mean()
        return float((xc * (v - v.mean())).sum() / (xc * xc).sum()), None
    raise ValueError(f"unsupported host aggregate {name!r}")


def holt_winters(times: np.ndarray, values: np.ndarray, n_forecast: int,
                 season: int, with_fit: bool):
    """Influx holt_winters(agg, N, S): triple (or double, S=0) exponential
    smoothing fitted by SSE grid search, forecasting N points at the
    sequence's stride (reference: engine/executor holt_winters transform).
    Returns (times, values) — fitted values + forecasts when with_fit,
    else the N forecasts only."""
    n = len(values)
    if n < max(2, 2 * max(season, 1)):
        return times[:0], values[:0]
    stride = int(np.median(np.diff(times))) if n > 1 else NS

    def sse_and_fit(alpha, beta, gamma):
        alpha = float(np.clip(alpha, 1e-3, 1 - 1e-3))
        beta = float(np.clip(beta, 1e-3, 1 - 1e-3))
        gamma = float(np.clip(gamma, 1e-3, 1 - 1e-3))
        level = values[0]
        trend = values[1] - values[0]
        seas = (
            values[:season] - values[:season].mean() if season else None
        )
        fit = np.empty(n)
        for i in range(n):
            s_i = seas[i % season] if season else 0.0
            fit[i] = level + trend + s_i
            err_base = values[i] - s_i
            new_level = alpha * err_base + (1 - alpha) * (level + trend)
            trend = beta * (new_level - level) + (1 - beta) * trend
            if season:
                seas[i % season] = gamma * (values[i] - new_level) + (1 - gamma) * s_i
            level = new_level
        resid = fit - values
        return float(resid @ resid), fit, level, trend, seas

    # Nelder-Mead like the reference (scipy when present: ~100 SSE evals
    # instead of a 1000-point grid); coarse grid fallback otherwise
    best = None
    try:
        from scipy.optimize import minimize

        x0 = [0.5, 0.1, 0.1] if season else [0.5, 0.1]

        def objective(x):
            a, b = x[0], x[1]
            g = x[2] if season else 0.0
            return sse_and_fit(a, b, g)[0]

        res = minimize(objective, x0, method="Nelder-Mead",
                       options={"maxfev": 200, "xatol": 1e-3, "fatol": 1e-6})
        a, b = res.x[0], res.x[1]
        g = res.x[2] if season else 0.0
        best = sse_and_fit(a, b, g)
    except ImportError:  # pragma: no cover
        grid = np.linspace(0.1, 0.9, 5)
        gammas = grid if season else [0.0]
        for a in grid:
            for b in grid:
                for g in gammas:
                    cand = sse_and_fit(a, b, g)
                    if best is None or cand[0] < best[0]:
                        best = cand
    _, fit, level, trend, seas = best
    f_times = times[-1] + stride * np.arange(1, n_forecast + 1)
    f_vals = np.array([
        level + (k + 1) * trend + (seas[(n + k) % season] if season else 0.0)
        for k in range(n_forecast)
    ])
    if with_fit:
        return (
            np.concatenate([times, f_times]),
            np.concatenate([fit, f_vals]),
        )
    return f_times, f_vals


def select_top_bottom_idx(name: str, times: np.ndarray, values: np.ndarray,
                          params: tuple) -> np.ndarray:
    """Row indices selected by top()/bottom(): extreme value first, value
    ties take the OLDEST timestamp (influx rule), output ordered by time.
    Exposed separately so companion-column projections can fetch other
    fields of the selected rows (reference TestServer_Query_For_BugList#2:
    `SELECT TOP(f, 2), *`)."""
    n = int(params[0]) if params else 1
    n = min(n, len(values))
    order = (np.lexsort((times, -values)) if name == "top"
             else np.lexsort((times, values)))
    idx = order[:n]
    return idx[np.argsort(times[idx], kind="stable")]


def multi_row(name: str, times: np.ndarray, values: np.ndarray, params: tuple,
              rng: np.random.Generator | None = None, models=None):
    """top/bottom/sample/distinct: list of (time_ns, value) output rows."""
    if len(values) == 0:
        return []
    if name in ("top", "bottom"):
        idx = select_top_bottom_idx(name, times, values, params)
        return [(int(times[i]), values[i].item()) for i in idx]
    if name == "sample":
        n = int(params[0]) if params else 1
        n = min(n, len(values))
        rng = rng or np.random.default_rng()
        idx = np.sort(rng.choice(len(values), size=n, replace=False))
        return [(int(times[i]), values[i].item()) for i in idx]
    if name == "distinct":
        # influx returns distinct values in FIRST-APPEARANCE order, with
        # the window time (server_test.go AggregateSelectors 'distinct')
        uniq, idx = np.unique(values, return_index=True)
        order = np.argsort(idx)
        return [(None, py_value(uniq[i])) for i in order]
    if name == "detect":
        from opengemini_tpu.services.castor import detect as _detect
        from opengemini_tpu.services.castor import detect_fitted as _fitted

        algorithm = str(params[0]) if params else "mad"
        threshold = float(params[1]) if len(params) > 1 else None
        model = models.get(algorithm) if models is not None else None
        if model is not None:
            # a FITTED model by this name: score against its persisted
            # training baseline (castor fit->detect pipeline); an explicit
            # query threshold overrides the stored one
            mask = _fitted(model, np.asarray(values, dtype=np.float64),
                           threshold)
        else:
            mask = _detect(np.asarray(values, dtype=np.float64), algorithm, threshold)
        return [
            (int(times[i]), py_value(values[i])) for i in np.nonzero(mask)[0]
        ]
    raise ValueError(f"unsupported multi-row call {name!r}")
