"""Incremental query result cache for GROUP BY time() aggregates.

The reference serves repeated dashboard queries from cached partials with
incremental append (engine/executor/inc_agg_transform.go,
inc_hash_agg_transform.go, lib/resultcache/). Here the unit of caching is
one (group, window) cell: with GROUP BY time() the renderer never needs
selector row identities (output times are window starts), so a cached
cell is just ``(value, count)`` per aggregate — losslessly re-renderable
under any fill/limit/order, including fill(previous)/linear which the
renderer applies over the merged window sequence.

Validity is tracked per window by the (path, data_version) signature of
every shard overlapping it (storage/shard.py data_version: bumped by
writes/deletes/rewrites, not by flush/compact). Appending new points
bumps only the owning shard, so a re-executed dashboard query recomputes
only the trailing (or otherwise touched) windows and re-reads nothing
else; an untouched query answers entirely from cache with no scan and no
device work.

Keys are a time-less statement fingerprint — db/rp/measurement, the
non-time WHERE trees, the window grid (every, offset), grouping, and the
ordered aggregate list — so the same dashboard panel re-queried over a
moving range keeps hitting the same entry (windows are keyed by absolute
start time).
"""

from __future__ import annotations

import json
import threading
from opengemini_tpu.utils import lockdep
from collections import OrderedDict

import numpy as np

from opengemini_tpu.utils.stats import GLOBAL as STATS

# bounds: fingerprints (distinct dashboard panels) and windows per panel
_MAX_QUERIES = 64
_MAX_WINDOWS = 16384


class IncrementalCache:
    def __init__(self, max_queries: int = _MAX_QUERIES,
                 max_windows: int = _MAX_WINDOWS):
        self._store: OrderedDict[str, dict] = OrderedDict()
        self._lock = lockdep.Lock()
        self.max_queries = max_queries
        self.max_windows = max_windows

    def lookup(self, fp: str) -> dict:
        """-> {window_start: (sig, {group_key: [(value, count), ...]})}.
        Returns a shallow COPY — update() mutates/evicts the live entry
        concurrently and a plan must keep seeing the windows it
        validated."""
        with self._lock:
            got = self._store.get(fp)
            if got is None:
                return {}
            self._store.move_to_end(fp)
            return dict(got)

    def update(self, fp: str, windows: dict) -> None:
        """Merge freshly-computed windows into the fingerprint's entry."""
        with self._lock:
            entry = self._store.get(fp)
            if entry is None:
                entry = self._store[fp] = {}
            entry.update(windows)
            self._store.move_to_end(fp)
            if len(entry) > self.max_windows:
                for ws in sorted(entry)[: len(entry) - self.max_windows]:
                    del entry[ws]
            while len(self._store) > self.max_queries:
                self._store.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._store.clear()


def fingerprint(db, rp, mst, sc, group_time, group_tags, all_tags,
                agg_specs) -> str:
    from opengemini_tpu.sql import astjson

    return json.dumps(
        [
            db, rp or "", mst,
            astjson.to_json(sc.tag_expr),
            astjson.to_json(sc.field_expr),
            astjson.to_json(sc.mixed_expr),
            bool(sc.mixed_series_level),
            group_time.every_ns, group_time.offset_ns,
            list(group_tags), bool(all_tags),
            [[name, list(params), fname] for name, params, fname in agg_specs],
        ],
        separators=(",", ":"),
    )


def window_signature(shards, ws: int, we: int) -> tuple:
    """(path, data_version) of every shard overlapping [ws, we)."""
    return tuple(sorted(
        (sh.path, sh.data_version)
        for sh in shards
        if sh.tmax > ws and sh.tmin < we
    ))


def window_fresh(cached_sig, by_path: dict, ws: int, we: int) -> bool:
    """Is a cached window still valid? The shard SET must be unchanged and
    no shard may have a mutation newer than its cached version touching
    [ws, we) — sub-shard granularity via Shard.changed_since, so a write
    into one window leaves the rest of a 7d shard's windows cached."""
    cur = {sh.path for sh in by_path.values()
           if sh.tmax > ws and sh.tmin < we}
    if {p for p, _v in cached_sig} != cur:
        return False
    for p, v in cached_sig:
        if by_path[p].changed_since(v, ws, we):
            return False
    return True


class CachePlan:
    """Per-execution cache bookkeeping for the executor's aggregate path.

    Built after the scan context; tells the executor which window range
    must actually be scanned (the stale hull) and merges cached cells with
    the fresh compute before rendering.
    """

    def __init__(self, cache: IncrementalCache, fp: str, shards, aligned: int,
                 every_ns: int, W: int, n_aggs: int, tmin: int, tmax: int):
        self.cache = cache
        self.fp = fp
        self.aligned = aligned
        self.every = every_ns
        self.W = W
        self.n_aggs = n_aggs
        self.wstarts = [aligned + w * every_ns for w in range(W)]
        self.sigs = [
            window_signature(shards, ws, ws + every_ns) for ws in self.wstarts
        ]
        # PARTIAL windows — cut by the query's time bounds — cover only a
        # slice of their range: never cached, never served (a different
        # cutoff shares the same fingerprint and window key,
        # TestServer_Query_GroupByTimeCutoffs)
        self.partial = {
            w for w in range(W)
            if self.wstarts[w] < tmin or self.wstarts[w] + every_ns > tmax
        }
        held = cache.lookup(fp)
        self.cached = held
        by_path = {sh.path: sh for sh in shards}
        stale = []
        for w in range(W):
            got = held.get(self.wstarts[w])
            if w in self.partial or got is None or not window_fresh(
                got[0], by_path, self.wstarts[w],
                self.wstarts[w] + every_ns,
            ):
                stale.append(w)
        self.stale = set(stale)
        STATS.incr("executor", "inc_cache_windows_reused", W - len(stale))
        if not stale:
            STATS.incr("executor", "inc_cache_full_hits")

    @property
    def scan_ranges(self):
        """Disjoint [lo, hi) scan ranges covering exactly the stale
        windows, or [] when everything is cached. Kept as runs (not one
        hull) so a now()-relative dashboard query — whose partial edge
        windows are always stale — still skips the cached middle."""
        if not self.stale:
            return []
        runs = []
        for w in sorted(self.stale):
            ws, we = self.wstarts[w], self.wstarts[w] + self.every
            if runs and runs[-1][1] == ws:
                runs[-1][1] = we
            else:
                runs.append([ws, we])
        return [tuple(r) for r in runs]

    def _fresh_ws(self):
        return sorted(self.stale)

    def merge(self, agg_results, aggs, group_keys):
        """Overwrite cached windows into the computed arrays (extending
        group_keys with cache-only groups), then persist the freshly
        computed hull windows. agg_results maps id(call) -> (out, sel,
        counts, spec, fname, times_abs); with GROUP BY time the renderer
        consumes only (out, counts, spec, fname)."""
        W = self.W
        gid_of = {k: i for i, k in enumerate(group_keys)}
        hull = self.stale
        for w in range(W):
            if w in hull:
                continue
            _sig, groups = self.cached[self.wstarts[w]]
            for key in groups:
                if key not in gid_of:
                    gid_of[key] = len(group_keys)
                    group_keys.append(key)
        G = len(group_keys)
        n_seg = G * W

        merged = {}
        for ai, (call, spec, params, fname) in enumerate(aggs):
            out, sel, counts, spec_, fname_, times_abs = agg_results[id(call)]
            out = np.asarray(out)
            new_out = np.zeros(n_seg, dtype=out.dtype)
            new_cnt = np.zeros(n_seg, dtype=np.int64)
            old_G = len(out) // W if W else 0
            if len(out):
                new_out.reshape(G, W)[:old_G] = out.reshape(old_G, W)
                new_cnt.reshape(G, W)[:old_G] = np.asarray(counts).reshape(
                    old_G, W)
            merged[id(call)] = (new_out, new_cnt, spec_, fname_)
        n_aggs = len(aggs)
        for w in range(W):
            if w in hull:
                continue
            _sig, groups = self.cached[self.wstarts[w]]
            if not groups:
                continue
            # vectorized per (window, agg): one fancy-index assignment
            # over all of the window's cached groups
            gids = np.fromiter((gid_of[key] for key in groups),
                               np.int64, len(groups))
            cells = np.asarray(
                [[c[1] for c in v] for v in groups.values()], np.int64)
            segs = gids * W + w
            for ai, (call, _s, _p, _f) in enumerate(aggs):
                new_out, new_cnt, _sp, _fn = merged[id(call)]
                if new_out.dtype.kind in "iu":
                    # int-exact values stay python-int end-to-end: a
                    # float64 staging array would corrupt sums > 2^53
                    new_out[segs] = np.fromiter(
                        (v[ai][0] for v in groups.values()),
                        np.int64, len(groups))
                else:
                    new_out[segs] = np.fromiter(
                        (v[ai][0] for v in groups.values()),
                        np.float64, len(groups))
                new_cnt[segs] = cells[:, ai]

        # persist the recomputed windows (never the partial edge windows;
        # only groups with data — zero cells rebuild as zeros on read, so
        # sparse windows stay cheap at high group cardinality)
        keys_by_gid = list(gid_of)  # insertion order == gid order
        outs2d = [merged[id(call)][0].reshape(G, W) for call, *_ in aggs]
        cnts2d = [merged[id(call)][1].reshape(G, W) for call, *_ in aggs]
        fresh: dict[int, tuple] = {}
        for w in self._fresh_ws():
            if w in self.partial:
                continue
            col_cnt = np.stack([c[:, w] for c in cnts2d])  # (n_aggs, G)
            col_out = np.stack([o[:, w] for o in outs2d])
            has = np.flatnonzero((col_cnt > 0).any(axis=0))
            groups = {
                keys_by_gid[g]: [
                    (col_out[ai, g].item(), int(col_cnt[ai, g]))
                    for ai in range(n_aggs)
                ]
                for g in has
            }
            fresh[self.wstarts[w]] = (self.sigs[w], groups)
        if fresh:
            self.cache.update(self.fp, fresh)

        for call, _s, _p, _f in aggs:
            new_out, new_cnt, sp, fn = merged[id(call)]
            agg_results[id(call)] = (new_out, None, new_cnt, sp, fn, None)
        return group_keys
