"""Planner splice over materialized rollups (storage/rollup.py).

For an eligible ``GROUP BY time(T)`` aggregate query — T a multiple of a
declared rollup's interval, grid on the rollup's boundaries, tags-only
WHERE, every aggregate derivable from rollup cells (count/sum/min/max,
mean = s/c, percentile from the spec's sketches) — the executor builds a
RollupPlan: windows wholly below the rollup's durable watermark and not
dirty are answered from rollup rows; everything else (the live tail,
re-dirtied late windows, partial edge windows) stays a raw scan.  The
plan composes with the incremental result cache (query/resultcache.py):
it only ever serves windows the cache already classified stale, and the
cells it fills are persisted back into the cache under the raw shards'
freshness signatures — valid because a clean rollup window is equal to
its raw computation by the watermark/dirty contract.

merge() mirrors resultcache.CachePlan.merge's array staging (int-exact
columns stay integer end-to-end) and runs BEFORE the cache merge so both
layers see one consistent array set.
"""

from __future__ import annotations

import base64

import numpy as np

from opengemini_tpu.storage import rollup as rmod
from opengemini_tpu.utils.stats import GLOBAL as STATS


def try_plan(mgr, db, rp, mst, sc, ctx, aggs, schema, cache_plan,
             tmin, tmax):
    """Build a RollupPlan or return None (query ineligible / nothing
    servable).  Cheap when no spec matches: two dict lookups."""
    if mgr is None or not mgr.read_enabled:
        return None
    group_time = ctx.group_time
    if group_time is None or not aggs:
        return None
    if sc.field_expr is not None or sc.mixed_expr is not None:
        return None  # row-level filters are not derivable from cells
    spec = mgr.spec_for(db, rp, mst, group_time.every_ns, ctx.aligned)
    if spec is None:
        return None
    for _call, aspec, _params, fname in aggs:
        if aspec.name == "percentile":
            if not spec.sketch:
                return None
        elif aspec.name not in rmod.DERIVABLE:
            return None
        if spec.fields is not None and fname not in spec.fields:
            return None
    plan = RollupPlan(mgr, db, spec, sc, ctx, aggs, tmin, tmax, cache_plan)
    if not plan.serve:
        STATS.incr("rollup", "splice_misses")
        return None
    return plan


class RollupPlan:
    def __init__(self, mgr, db, spec, sc, ctx, aggs, tmin, tmax,
                 cache_plan):
        self.mgr = mgr
        self.db = db
        self.spec = spec
        self.sc = sc
        self.aggs = aggs
        self.group_tags = ctx.group_tags
        self.aligned = ctx.aligned
        self.every = ctx.group_time.every_ns
        self.W = ctx.W
        self.tmin = tmin
        self.tmax = tmax
        self.rows_read = 0
        wstarts = [self.aligned + w * self.every for w in range(self.W)]
        partial = {
            w for w in range(self.W)
            if wstarts[w] < tmin or wstarts[w] + self.every > tmax
        }
        candidate = (set(cache_plan.stale) if cache_plan is not None
                     else set(range(self.W)))
        self.candidate = candidate
        wm, dirty = mgr.serve_view(db, spec)
        # map each dirty rollup window into its containing QUERY window
        # once (the dirty set is bounded; probing every sub-window of
        # every query window would be O(W * T/interval))
        span_hi = self.aligned + self.W * self.every
        dirty_qw = {
            int((s - self.aligned) // self.every)
            for s in dirty if self.aligned <= s < span_hi
        }
        serve = set()
        for w in candidate - partial:
            if wstarts[w] + self.every > wm or w in dirty_qw:
                continue
            serve.add(w)
        self.wstarts = wstarts
        self.serve = serve
        # {w: {group_key: [(value, count) per agg]}}
        self.cells: dict[int, dict[tuple, list]] = {}

    @property
    def scan_ranges(self):
        """Disjoint [lo, hi) raw ranges covering the candidate windows
        the rollup does NOT serve, clamped to the query bounds ([] =
        fully spliced, no raw scan at all)."""
        runs = []
        for w in sorted(self.candidate - self.serve):
            ws = self.wstarts[w]
            we = ws + self.every
            if runs and runs[-1][1] == ws:
                runs[-1][1] = we
            else:
                runs.append([ws, we])
        return [(max(self.tmin, lo), min(self.tmax, hi))
                for lo, hi in runs if max(self.tmin, lo) < min(self.tmax, hi)]

    # -- cell fetch -----------------------------------------------------------

    def fetch(self) -> int:
        """Read the rollup rows for the served windows and finalize
        per-(group, window) aggregate cells.  A window whose cells
        cannot answer an aggregate (e.g. a sketch persisted before the
        spec kept them) falls OUT of the serve set here — fetch runs
        before the raw scan ranges are consumed, so it simply re-joins
        the raw tail."""
        from opengemini_tpu.query.sketch import RollupSketch

        runs = []
        for w in sorted(self.serve):
            ws = self.wstarts[w]
            if runs and runs[-1][1] == ws:
                runs[-1][1] = ws + self.every
            else:
                runs.append([ws, ws + self.every])
        fields = sorted({a[3] for a in self.aggs})
        recs = self.mgr.read_recs(self.db, self.spec, runs, fields,
                                  tag_expr=self.sc.tag_expr)
        self.rows_read = sum(len(r) for _t, r in recs)
        need_sketch = any(a[1].name == "percentile" for a in self.aggs)
        W = self.W
        # vectorized accumulation: per (group, field) window arrays —
        # the per-row python loop was the splice's hot spot at dashboard
        # shapes (thousands of rollup rows per query)
        # accs[gkey][fname] = [cnt W-arr, sum W-arr, mn W-arr, mx W-arr,
        #                      {w: sketch}]
        accs: dict[tuple, dict[str, list]] = {}
        for tags, rec in recs:
            tagd = dict(tags)
            gkey = tuple(tagd.get(k, "") for k in self.group_tags)
            per_f = accs.setdefault(gkey, {})
            widx = ((rec.times - self.aligned) // self.every).astype(
                np.int64)
            ok = np.fromiter((int(w) in self.serve for w in widx),
                             np.bool_, len(widx))
            for fname in fields:
                c_col = rec.columns.get(rmod.C_ + fname)
                if c_col is None:
                    continue
                m = ok & c_col.valid & (c_col.values > 0)
                if not m.any():
                    continue
                wv = widx[m]
                acc = per_f.get(fname)
                if acc is None:
                    acc = per_f[fname] = [
                        np.zeros(W, np.int64), None, None, None, {}]
                np.add.at(acc[0], wv, c_col.values[m].astype(np.int64))
                for slot, prefix, combine in (
                        (1, rmod.S_, "sum"), (2, rmod.MN_, "min"),
                        (3, rmod.MX_, "max")):
                    col = rec.columns.get(prefix + fname)
                    if col is None:
                        continue
                    vm = m & col.valid
                    if not vm.any():
                        continue
                    vals = col.values[vm]
                    wvv = widx[vm]
                    arr = acc[slot]
                    if arr is None:
                        if combine == "sum":
                            init = 0
                        elif vals.dtype.kind in "iu":
                            init = (np.iinfo(np.int64).max
                                    if combine == "min"
                                    else np.iinfo(np.int64).min)
                        else:
                            init = (np.inf if combine == "min"
                                    else -np.inf)
                        arr = acc[slot] = np.full(W, init, vals.dtype)
                    if combine == "sum":
                        np.add.at(arr, wvv, vals)
                    elif combine == "min":
                        np.minimum.at(arr, wvv, vals)
                    else:
                        np.maximum.at(arr, wvv, vals)
                if need_sketch:
                    col = rec.columns.get(rmod.SK_ + fname)
                    if col is not None:
                        vm = np.flatnonzero(m & col.valid)
                        for i in vm:
                            b64 = col.values[i]
                            if not b64:
                                continue
                            sk = RollupSketch.deserialize(
                                base64.b64decode(b64))
                            w = int(widx[i])
                            held = acc[4].get(w)
                            if held is None:
                                acc[4][w] = sk
                            else:
                                held.merge(sk)
        bad: set[int] = set()
        for gkey, per_f in accs.items():
            windows = set()
            for acc in per_f.values():
                windows.update(np.flatnonzero(acc[0] > 0).tolist())
            for w in windows:
                self._finalize_cell(int(w), gkey, per_f, bad)
        if bad:
            self.serve -= bad
            for w in bad:
                self.cells.pop(w, None)
        STATS.incr("rollup", "splice_hits")
        STATS.incr("rollup", "splice_windows", len(self.serve))
        STATS.incr("rollup", "splice_raw_windows",
                   len(self.candidate - self.serve))
        return self.rows_read

    def _finalize_cell(self, w, gkey, per_f, bad):
        out_cells = []
        for _call, aspec, params, fname in self.aggs:
            acc = per_f.get(fname)
            cnt = int(acc[0][w]) if acc is not None else 0
            if not cnt:
                out_cells.append((0, 0))
                continue
            s = acc[1][w].item() if acc[1] is not None else 0
            mn = acc[2][w].item() if acc[2] is not None else None
            mx = acc[3][w].item() if acc[3] is not None else None
            sk = acc[4].get(w)
            name = aspec.name
            if name == "count":
                out_cells.append((cnt, cnt))
            elif name == "sum":
                out_cells.append((s, cnt))
            elif name == "min":
                out_cells.append((mn if mn is not None else 0.0, cnt))
            elif name == "max":
                out_cells.append((mx if mx is not None else 0.0, cnt))
            elif name == "mean":
                out_cells.append((s / cnt if cnt else 0.0, cnt))
            else:  # percentile
                if sk is None:
                    bad.add(w)  # cell predates sketches: raw-scan it
                    out_cells.append((0.0, 0))
                    continue
                qv = float(params[0]) if params else 0.0
                v = sk.percentile(qv)
                # influx: rank < 1 emits no row for the window — the
                # executor zeroes device counts the same way
                out_cells.append((0.0, 0) if v is None else (v, cnt))
        self.cells.setdefault(w, {})[gkey] = out_cells

    # -- merge into the computed arrays ---------------------------------------

    def merge(self, agg_results, aggs, group_keys):
        """Overwrite the served windows' cells into the aggregate arrays
        (extending group_keys with rollup-only groups) — the same
        contract as resultcache.CachePlan.merge, which runs after this
        and persists the spliced windows under raw freshness
        signatures."""
        W = self.W
        gid_of = {k: i for i, k in enumerate(group_keys)}
        for w in sorted(self.serve):
            for key in self.cells.get(w, ()):
                if key not in gid_of:
                    gid_of[key] = len(group_keys)
                    group_keys.append(key)
        G = len(group_keys)
        n_seg = G * W
        for ai, (call, _spec, _params, _fname) in enumerate(aggs):
            out, _sel, counts, spec_, fname_, _times = agg_results[id(call)]
            out = np.asarray(out)
            new_out = np.zeros(n_seg, dtype=out.dtype)
            new_cnt = np.zeros(n_seg, dtype=np.int64)
            old_G = len(out) // W if W else 0
            if len(out):
                new_out.reshape(G, W)[:old_G] = out.reshape(old_G, W)
                new_cnt.reshape(G, W)[:old_G] = np.asarray(
                    counts).reshape(old_G, W)
            int_out = new_out.dtype.kind in "iu"
            for w in self.serve:
                for key, cells in self.cells.get(w, {}).items():
                    seg = gid_of[key] * W + w
                    v, c = cells[ai]
                    new_out[seg] = int(v) if int_out else float(v)
                    new_cnt[seg] = c
            agg_results[id(call)] = (new_out, None, new_cnt, spec_,
                                     fname_, None)
        return group_keys
