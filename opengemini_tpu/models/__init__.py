"""Flagship jittable compute graphs ("models") — the plan-template layer.

In the reference, common query shapes hit a cached plan template
(engine/executor/select.go:121 buildPlanByCache, plan_type.go). Here the
analogue is a cache of jitted XLA programs keyed by
(aggregate set, padded batch shape, padded segment count, dtype): every
query whose shape matches reuses a compiled device program.
"""
