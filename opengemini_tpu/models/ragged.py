"""Ragged-to-dense segment batching: the TPU answer to variable group sizes.

Scatter-based segment reduction on TPU measures ~0.04-1.2 G rows/s; dense
axis reductions measure ~160 G rows/s (bench.py). So the general
aggregation path converts ragged (segment id per row) batches into
SIZE-BUCKETED DENSE matrices on the host and every aggregate becomes a
dense axis-1 reduction. Design constraints learned on hardware:

  - CANONICAL SHAPES: the WIDTHS ladder (16/64/256/1024, <=4x padding
    waste) and pow2-padded row counts keep the XLA compile cache tiny
    (arbitrary (g, w) shapes cost seconds of re-compile per query).
  - Segments wider than the top width SPLIT into consecutive sub-rows;
    combine on the host with reduceat (exact k-way variance combination
    for stddev: SSD = sum_i [ssd_i + c_i (mu_i - mu)^2]).
  - Offsets within segments come from RUN analysis (rows arrive as
    consecutive same-segment runs per series chunk), not a global
    argsort — freeze is O(N) + O(runs log runs).

This is SURVEY.md §7's 'ragged group sizes' hard part. Segments live in
exactly one bucket; per-bucket results scatter back into (num_segments,)
outputs host-side.

NOTE on this dev environment: the axon TPU tunnel moves host->device data
at ~0.03 GB/s (measured), ~1000x below a real TPU host's PCIe/ICI — so
end-to-end wall times here are transfer-bound and NOT representative;
bench.py therefore measures device-resident compute. On production
hardware the freeze (host, ~0.5s / 16M rows) and transfer (~50ms / GB)
are minor next to the scan/decode stage.
"""

from __future__ import annotations

import numpy as np

from opengemini_tpu.models import templates
from opengemini_tpu.utils import devobs

_REL_LO_BITS = 30
_REL_LO_MASK = (1 << _REL_LO_BITS) - 1

WIDTHS = (16, 64, 256, 1024)  # ~4x max padding waste, 4 canonical shapes
_MIN_G = 8

# aggregates the dense path supports (others use the scatter/lexsort path)
DENSE_AGGS = {"sum", "count", "mean", "min", "max", "first", "last",
              "spread", "stddev"}

# aggregates the host-exact int64 path supports (INT fields: float compute
# dtype would corrupt values beyond its mantissa — 2^24 in f32 on TPU).
# Selector aggs (min/max/first/last) stay on-device for row selection.
INT_EXACT_AGGS = {"sum", "count", "mean"}


class IntExactBatch:
    """Host-side exact int64 aggregation for INT fields (same add/run
    contract as AggBatch/BucketedBatch, minus selector support — the
    routing predicate never sends selectors here). numpy ufunc.at is
    slower than the device, but integer exactness wins for int columns —
    the same tradeoff storage/downsample.py makes for destructive
    rewrites. No timestamps are retained (no selectors -> no consumer)."""

    def __init__(self):
        self._vals: list[np.ndarray] = []
        self._seg: list[np.ndarray] = []
        self._mask: list[np.ndarray] = []
        self.n = 0
        self._acc = None

    def add(self, values, rel_ns, seg_ids, mask, times_ns, sids=None):
        self._vals.append(np.asarray(values))
        self._seg.append(np.asarray(seg_ids, dtype=np.int64))
        self._mask.append(np.asarray(mask, dtype=np.bool_))
        self.n += len(values)

    def layout_name(self) -> str:
        return "int-exact"

    def host_times(self) -> np.ndarray:
        return np.empty(0, np.int64)  # interface parity; never consumed

    def _accumulate(self, num_segments: int):
        if self._acc is not None:
            return self._acc
        s = np.zeros(num_segments, dtype=np.int64)
        c = np.zeros(num_segments, dtype=np.int64)
        for vals, seg, mask in zip(self._vals, self._seg, self._mask):
            idx = np.flatnonzero(mask)
            if not len(idx):
                continue
            v = vals[idx].astype(np.int64)
            g = seg[idx]
            np.add.at(s, g, v)
            np.add.at(c, g, 1)
        self._acc = (s, c)
        self._vals = self._seg = self._mask = []  # free the raw rows
        return self._acc

    def run(self, spec, num_segments: int, params: tuple = ()):
        s, c = self._accumulate(num_segments)
        if spec.name == "sum":
            out = s  # int64 end-to-end; renderer keeps integers exact
        elif spec.name == "count":
            out = c
        elif spec.name == "mean":
            out = s / np.maximum(c, 1)
        else:
            raise ValueError(f"int-exact path does not support {spec.name!r}")
        return np.asarray(out), None, c


class BucketedBatch:
    """Drop-in alternative to templates.AggBatch for dense-capable
    aggregates. add() accumulates ragged chunks; the first run() freezes
    the batch into dense buckets."""

    def __init__(self, dtype=None):
        self.dtype = dtype or templates.compute_dtype()
        self._vals: list[np.ndarray] = []
        self._rel: list[np.ndarray] = []
        self._seg: list[np.ndarray] = []
        self._mask: list[np.ndarray] = []
        self._times: list[np.ndarray] = []
        self.n = 0
        self._frozen = None

    def add(self, values, rel_ns, seg_ids, mask, times_ns, sids=None):
        self._vals.append(np.asarray(values, dtype=self.dtype))
        self._rel.append(np.asarray(rel_ns, dtype=np.int64))
        self._seg.append(np.asarray(seg_ids, dtype=np.int64))
        self._mask.append(np.asarray(mask, dtype=np.bool_))
        self._times.append(np.asarray(times_ns, dtype=np.int64))
        self.n += len(values)

    def layout_name(self) -> str:
        return "bucketed"

    def host_times(self) -> np.ndarray:
        return np.concatenate(self._times) if self._times else np.empty(0, np.int64)

    # -- freeze: ragged -> dense buckets --------------------------------

    def _freeze(self, num_segments: int):
        if self._frozen is not None:
            return self._frozen
        if self.n == 0:
            self._frozen = []
            return self._frozen
        vals = np.concatenate(self._vals)
        rel = np.concatenate(self._rel)
        seg = np.concatenate(self._seg)
        mask = np.concatenate(self._mask)
        n = len(vals)
        row_idx = np.arange(n, dtype=np.int32)

        counts = np.bincount(seg, minlength=num_segments)

        # within-segment arrival offsets via run analysis (no global sort)
        run_starts = np.concatenate([[0], np.flatnonzero(seg[1:] != seg[:-1]) + 1])
        run_segs = seg[run_starts]
        run_lens = np.diff(np.concatenate([run_starts, [n]]))
        order = np.argsort(run_segs, kind="stable")  # runs, not rows
        cum = np.zeros(len(run_starts), dtype=np.int64)
        lens_sorted = run_lens[order]
        segs_sorted = run_segs[order]
        csum = np.cumsum(lens_sorted) - lens_sorted
        first_run_of_seg = np.searchsorted(segs_sorted, segs_sorted)
        base_sorted = csum - csum[first_run_of_seg]
        cum[order] = base_sorted
        offsets = (
            np.arange(n, dtype=np.int64)
            - np.repeat(run_starts, run_lens)
            + np.repeat(cum, run_lens)
        )

        buckets: list[_Bucket] = []
        bucket_of = np.full(num_segments, -1, dtype=np.int8)
        for bi, w in enumerate(WIDTHS):
            lo = WIDTHS[bi - 1] if bi else 0
            if w == WIDTHS[-1]:
                here = counts > lo  # larger segments split into sub-rows
            else:
                here = (counts > lo) & (counts <= w)
            segs_here = np.nonzero(here)[0]
            if len(segs_here) == 0:
                continue
            bucket_of[segs_here] = len(buckets)
            buckets.append(_Bucket(w, segs_here, counts[segs_here]))

        for b in buckets:
            w = b.width
            # sub-row layout: segment k gets ceil(count/w) consecutive rows
            n_sub = np.maximum((b.seg_counts + w - 1) // w, 1)
            sub_base = np.cumsum(n_sub) - n_sub  # first sub-row per segment
            g = int(n_sub.sum())
            g_pad = _pow2_at_least(g, _MIN_G)
            slot_of = np.zeros(num_segments, dtype=np.int64)
            slot_of[b.segs] = sub_base
            rows = np.nonzero(bucket_of[seg] == _index_of(buckets, b))[0]
            off = offsets[rows]
            flat = (slot_of[seg[rows]] + off // w) * w + off % w
            vmat = np.zeros((g_pad, w), dtype=self.dtype)
            mmat = np.zeros((g_pad, w), dtype=np.bool_)
            hmat = np.zeros((g_pad, w), dtype=np.int32)
            lmat = np.zeros((g_pad, w), dtype=np.int32)
            imat = np.zeros((g_pad, w), dtype=np.int32)
            vmat.reshape(-1)[flat] = vals[rows]
            mmat.reshape(-1)[flat] = mask[rows]
            r = rel[rows]
            hmat.reshape(-1)[flat] = (r >> _REL_LO_BITS).astype(np.int32)
            lmat.reshape(-1)[flat] = (r & _REL_LO_MASK).astype(np.int32)
            imat.reshape(-1)[flat] = row_idx[rows]
            b.arrays = (vmat, hmat, lmat, imat, mmat)
            b.g = g
            b.sub_base = sub_base
            b.n_sub = n_sub
            b.rel = rel  # for host combine of split selectors
        self._frozen = buckets
        return buckets

    # -- execution -------------------------------------------------------

    supports_want_sel = True

    def run(self, spec, num_segments: int, params: tuple = (),
            want_sel: bool = True):
        """Same contract as AggBatch.run: (values, sel|None, counts).
        want_sel=False skips the selector lex-scan kernels for min/max
        (their values come from the basic pass) — GROUP BY time() scans
        never consult sel. first/last still need the selector kernel for
        their VALUES."""
        buckets = self._freeze(num_segments)
        out = np.zeros(num_segments, dtype=np.float64)
        sel = np.zeros(num_segments, dtype=np.int64)
        counts = np.zeros(num_segments, dtype=np.int64)
        is_selector = spec.name in ("min", "max", "first", "last")
        need_sel = spec.name in ("first", "last") or (
            want_sel and spec.name in ("min", "max"))
        for b in buckets:
            st = b.combined(need_selectors=need_sel)
            counts[b.segs] = st["count"]
            if spec.name == "spread":
                out[b.segs] = st["max"] - st["min"]
            elif spec.name == "stddev":
                c = np.maximum(st["count"], 1)
                out[b.segs] = np.sqrt(np.maximum(st["ssd"] / np.maximum(c - 1, 1), 0))
            else:
                out[b.segs] = st[spec.name]
            if is_selector and need_sel:
                sel[b.segs] = st["sel_" + spec.name]
        return out, (sel if (is_selector and need_sel) else None), counts


class _Bucket:
    def __init__(self, width: int, segs: np.ndarray, seg_counts: np.ndarray):
        self.width = width
        self.segs = segs
        self.seg_counts = seg_counts
        self.arrays = None
        self.g = 0
        self.sub_base = None
        self.n_sub = None
        self.rel = None
        self._raw: dict = {}
        self._combined: dict = {}
        self._mesh_arrays = None
        self._mesh_epoch = None
        self._ledger = None

    def _device_arrays(self, mesh):
        """Matrices for the kernels: with a configured mesh, row-sharded
        device arrays (bucket rows are independent — GSPMD partitions the
        dense reduces with zero collectives, parallel/distributed.py
        shard_leading_axis); otherwise the host matrices as-is. The
        sharded copy is keyed by mesh EPOCH so a hot config reload
        (runtime.set_mesh) reshards instead of serving a dead mesh."""
        if mesh is None or self.g < mesh.size:
            return self.arrays
        from opengemini_tpu.parallel import runtime as _prt

        epoch = _prt.mesh_epoch()
        if self._mesh_arrays is None or self._mesh_epoch != epoch:
            from opengemini_tpu.parallel import distributed as _dist

            devobs.LEDGER.drop(getattr(self, "_ledger", None))
            self._mesh_arrays = _dist.shard_leading_axis(
                mesh, *self.arrays, xfer_site="bucket-shard")
            self._mesh_epoch = epoch
            self._ledger = devobs.LEDGER.register(
                "bucket_mesh",
                sum(int(a.nbytes) for a in self._mesh_arrays),
                mesh_epoch=epoch, label="bucket", anchor=self)
        return self._mesh_arrays

    def _raw_stats(self, need_selectors: bool) -> dict:
        """Per-sub-row device stats, computed lazily per group: selector
        lex scans (4 extra matrix passes) run only for selector queries."""
        from opengemini_tpu.parallel import runtime as _prt

        mesh = _prt.get_mesh()
        arrays = self._device_arrays(mesh)
        # force the XLA selector form only when the inputs really are
        # mesh-sharded (pallas_call does not auto-partition); unsharded
        # buckets keep the fused Pallas kernel on TPU
        sel_kind = "selectors_xla" if arrays is not self.arrays else "selectors"
        if "count" not in self._raw:
            t0 = devobs.t0()
            got = _stats_jit("basic")(*arrays)
            if t0:
                devobs.note_exec(t0)  # dispatch; fetch attributes below
            self._raw.update({k: devobs.fetch_np(v)[: self.g]
                              for k, v in got.items()})
        if need_selectors and "sel_first" not in self._raw:
            t0 = devobs.t0()
            got = _stats_jit(sel_kind)(*arrays)
            if t0:
                devobs.note_exec(t0)
            self._raw.update({k: devobs.fetch_np(v)[: self.g]
                              for k, v in got.items()})
        return self._raw

    def combined(self, need_selectors: bool) -> dict:
        """Per-segment stats: raw sub-row stats + host k-way combine."""
        if "count" in self._combined and (
            not need_selectors or "sel_first" in self._combined
        ):
            return self._combined
        raw = self._raw_stats(need_selectors)
        if (self.n_sub == 1).all():
            self._combined = dict(raw)
            cnt = raw["count"].astype(np.int64)
            self._combined["count"] = cnt
            # mean recomputed host-side as f64(sum)/count — the SAME
            # arithmetic as the k-way combine branch below and the grid
            # layout (models/grid.py run()), so a query answers
            # identically whichever layout or slice width the planner
            # picked (the device f32 mean differs in the last ulp)
            self._combined["mean"] = raw["sum"] / np.maximum(cnt, 1)
            return self._combined
        starts = self.sub_base
        out = self._combined
        if "count" not in out:
            cnt = np.add.reduceat(raw["count"], starts).astype(np.int64)
            s = np.add.reduceat(raw["sum"], starts)
            mean = s / np.maximum(cnt, 1)
            # exact k-way variance combination:
            # SSD = sum_i [ssd_i + c_i (mu_i - mu)^2]
            mean_rep = np.repeat(mean, self.n_sub)
            extra = raw["count"] * (raw["mean"] - mean_rep) ** 2
            out.update(
                count=cnt,
                sum=s,
                mean=mean,
                min=np.minimum.reduceat(raw["min"], starts),
                max=np.maximum.reduceat(raw["max"], starts),
                ssd=np.add.reduceat(raw["ssd"] + extra, starts),
            )
        if need_selectors and "sel_first" not in out:
            rel = self.rel
            i64max = np.iinfo(np.int64).max
            i64min = np.iinfo(np.int64).min
            for name, latest in (("first", False), ("last", True)):
                sel_sub = raw["sel_" + name]
                r = np.where(
                    raw["count"] > 0, rel[sel_sub], i64max if not latest else i64min
                )
                red = np.maximum if latest else np.minimum
                best_rep = np.repeat(red.reduceat(r, starts), self.n_sub)
                hit = (r == best_rep) & (raw["count"] > 0)
                # exact-time ties across sub-rows: larger value wins
                # (reference FirstReduce/LastReduce tie rule)
                v_best = np.repeat(np.maximum.reduceat(
                    np.where(hit, raw[name], -np.inf), starts), self.n_sub)
                hit &= raw[name] == v_best
                idx_sub = np.where(hit, np.arange(len(r)), len(r))
                pick = np.clip(np.minimum.reduceat(idx_sub, starts), 0, len(r) - 1)
                out[name] = raw[name][pick]
                out["sel_" + name] = sel_sub[pick]
            for name in ("min", "max"):
                sel_sub = raw["sel_" + name]
                ext_rep = np.repeat(out[name], self.n_sub)
                hit = (raw[name] == ext_rep) & (raw["count"] > 0)
                r = np.where(hit, rel[sel_sub], i64max)
                best_rep = np.repeat(np.minimum.reduceat(r, starts), self.n_sub)
                hit &= r == best_rep
                idx_sub = np.where(hit, np.arange(len(r)), len(r))
                pick = np.clip(np.minimum.reduceat(idx_sub, starts), 0, len(r) - 1)
                out["sel_" + name] = sel_sub[pick]
        return out


def _index_of(buckets: list, b) -> int:
    for i, x in enumerate(buckets):
        if x is b:
            return i
    raise ValueError


def _pow2_at_least(n: int, floor: int) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


_STATS_FNS: dict = {}
_BIG_I32 = 2**31 - 1


def _stats_jit(kind: str):
    """Compiled per-sub-row stat kernels: 'basic' (one fused pass for
    count/sum/mean/min/max/ssd) and 'selectors' (the four lexicographic
    (hi, lo, col) scans for first/last/min/max row selection).
    'selectors_xla' forces the XLA form — used with a device mesh, where
    GSPMD partitions the plain XLA kernels over row-sharded inputs but
    pallas_call does not auto-partition.

    On a TPU backend 'selectors' routes to the fused Pallas tile kernels
    (ops/pallas_segment.py) — one HBM pass feeds every statistic; the
    XLA expressions below serve CPU runs and remain the semantics
    oracle the Pallas kernels are tested against."""
    fn = _STATS_FNS.get(kind)
    if fn is not None:
        return fn
    devobs.note_compile("bucket_" + kind)
    from opengemini_tpu.ops import pallas_segment

    if kind == "selectors" and pallas_segment.use_pallas():
        # measured on v5e-1: the fused Pallas selector kernel beats the
        # XLA lex-scan chain ~1.5x (one tile residency feeds all four
        # scans); for 'basic' XLA's own fusion already wins — see
        # ops/pallas_segment.py docstring for the numbers
        _STATS_FNS["selectors"] = pallas_segment.bucket_stats_selectors
        return _STATS_FNS[kind]
    import jax
    import jax.numpy as jnp

    def _take(mat, col_sel):
        return jnp.take_along_axis(mat, col_sel[:, None], axis=1)[:, 0]

    def _lex_col(hi, lo, cand, latest):
        """Column of the lexicographically (hi, lo) extreme candidate;
        ties by column order. int32-only — exact without x64 (TPU)."""
        big = _BIG_I32
        col = jnp.arange(hi.shape[1], dtype=jnp.int32)[None, :]
        if latest:
            hi_ext = jnp.where(cand, hi, -big).max(axis=1)
            c2 = cand & (hi == hi_ext[:, None])
            lo_ext = jnp.where(c2, lo, -big).max(axis=1)
            c3 = c2 & (lo == lo_ext[:, None])
            return jnp.where(c3, col, -big).max(axis=1)
        hi_ext = jnp.where(cand, hi, big).min(axis=1)
        c2 = cand & (hi == hi_ext[:, None])
        lo_ext = jnp.where(c2, lo, big).min(axis=1)
        c3 = c2 & (lo == lo_ext[:, None])
        return jnp.where(c3, col, big).min(axis=1)

    @jax.jit
    def basic(v, hi, lo, idx, m):
        zero = jnp.zeros((), v.dtype)
        vz = jnp.where(m, v, zero)
        cnt = m.sum(axis=1)
        s = vz.sum(axis=1)
        big = jnp.array(jnp.inf, v.dtype)
        mn = jnp.where(m, v, big).min(axis=1)
        mx = jnp.where(m, v, -big).max(axis=1)
        mean = s / jnp.maximum(cnt, 1).astype(v.dtype)
        dev = jnp.where(m, v - mean[:, None], zero)
        ssd = (dev * dev).sum(axis=1)
        return {"count": cnt, "sum": s, "ssd": ssd, "min": mn, "max": mx,
                "mean": mean}

    def _first_last_col(v, hi, lo, cand, latest):
        """Extreme (hi, lo) time; exact-time ties take the LARGER VALUE
        (reference agg_func.go FirstReduce/LastReduce), then column
        order."""
        big = _BIG_I32
        col = jnp.arange(hi.shape[1], dtype=jnp.int32)[None, :]
        if latest:
            hi_ext = jnp.where(cand, hi, -big).max(axis=1)
            c2 = cand & (hi == hi_ext[:, None])
            lo_ext = jnp.where(c2, lo, -big).max(axis=1)
            c3 = c2 & (lo == lo_ext[:, None])
        else:
            hi_ext = jnp.where(cand, hi, big).min(axis=1)
            c2 = cand & (hi == hi_ext[:, None])
            lo_ext = jnp.where(c2, lo, big).min(axis=1)
            c3 = c2 & (lo == lo_ext[:, None])
        fbig = jnp.array(jnp.inf, v.dtype)
        v_ext = jnp.where(c3, v, -fbig).max(axis=1)
        c4 = c3 & (v == v_ext[:, None])
        return jnp.where(c4, col, big).min(axis=1)

    @jax.jit
    def selectors(v, hi, lo, idx, m):
        big = jnp.array(jnp.inf, v.dtype)
        mn = jnp.where(m, v, big).min(axis=1)
        mx = jnp.where(m, v, -big).max(axis=1)
        clip = lambda c: jnp.clip(c, 0, v.shape[1] - 1)  # noqa: E731
        cf = clip(_first_last_col(v, hi, lo, m, latest=False))
        cl = clip(_first_last_col(v, hi, lo, m, latest=True))
        cmin = clip(_lex_col(hi, lo, m & (v == mn[:, None]), latest=False))
        cmax = clip(_lex_col(hi, lo, m & (v == mx[:, None]), latest=False))
        return {
            "first": _take(v, cf), "last": _take(v, cl),
            "sel_first": _take(idx, cf), "sel_last": _take(idx, cl),
            "sel_min": _take(idx, cmin), "sel_max": _take(idx, cmax),
        }

    _STATS_FNS["basic"] = basic
    _STATS_FNS["selectors_xla"] = selectors
    if not pallas_segment.use_pallas():
        # with pallas routing on, 'selectors' must stay un-cached here so a
        # later request takes the pallas branch above
        _STATS_FNS["selectors"] = selectors
    if kind in ("selectors", "selectors_xla"):
        return selectors
    return _STATS_FNS[kind]  # unknown kinds must raise, not silently alias
