"""Compiled aggregate templates: pad -> jit -> run -> slice.

The executor hands numpy batches here; this module owns padding (shape
bucketing so the XLA compile cache stays small), jit caching, and device
round-trips. Padding rows are masked out; padded segments are sliced off
after the device call.

This is the plan-template cache of the reference
(engine/executor/select.go:121 buildPlanByCache) applied to XLA programs:
queries with the same (aggregate, padded shape, padded segment count,
dtype) reuse one compiled device program.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from opengemini_tpu.ops import window as winmod
from opengemini_tpu.ops.aggregates import AggSpec
from opengemini_tpu.utils import devobs
from opengemini_tpu.utils.stats import GLOBAL as _STATS

_REL_LO_BITS = 30
_REL_LO_MASK = (1 << _REL_LO_BITS) - 1


def compute_dtype() -> np.dtype:
    """float64 when x64 is enabled (CPU parity tests), else float32 (TPU)."""
    return np.dtype(np.float64) if jax.config.jax_enable_x64 else np.dtype(np.float32)


@functools.lru_cache(maxsize=512)
def _jitted_build(fn, num_segments: int, params: tuple):
    devobs.note_compile("agg_batch",
                        (fn.__name__, num_segments, params))

    @jax.jit
    def run(values, rel_hi, rel_lo, seg_ids, mask):
        return fn(values, rel_hi, rel_lo, seg_ids, num_segments, mask, *params)

    return run


def _jitted(fn, num_segments: int, params: tuple):
    _STATS.incr("device", "jit_lookups")  # hits = lookups - misses
    return _jitted_build(fn, num_segments, params)


def _count_fn(values, rel_hi, rel_lo, seg_ids, num_segments, mask):
    from opengemini_tpu.ops import segment as seg

    return seg.seg_count(seg_ids, num_segments, mask), None


def split_rel_ns(rel_ns: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Exact int64 ns offset -> lexicographic int32 (hi, lo) pair for
    device-side time ordering without int64."""
    hi = (rel_ns >> _REL_LO_BITS).astype(np.int32)
    lo = (rel_ns & _REL_LO_MASK).astype(np.int32)
    return hi, lo


class AggBatch:
    """A device-ready batch for one field: values, (hi, lo) relative times,
    segment ids, validity mask — plus a host-only int64 ns time array for
    exact selector timestamps. Accumulated across shards/series."""

    def __init__(self, dtype=None):
        self.dtype = dtype or compute_dtype()
        self.values: list[np.ndarray] = []
        self.rel_hi: list[np.ndarray] = []
        self.rel_lo: list[np.ndarray] = []
        self.seg_ids: list[np.ndarray] = []
        self.mask: list[np.ndarray] = []
        self.times_ns: list[np.ndarray] = []  # host-side only
        self.n = 0
        self._padded = None
        self._counts_cache: dict[int, np.ndarray] = {}
        self._mesh_outs: dict[int, dict] = {}

    def add(self, values, rel_ns, seg_ids, mask, times_ns, sids=None):
        self.values.append(np.asarray(values, dtype=self.dtype))
        hi, lo = split_rel_ns(np.asarray(rel_ns, dtype=np.int64))
        self.rel_hi.append(hi)
        self.rel_lo.append(lo)
        self.seg_ids.append(np.asarray(seg_ids, dtype=np.int32))
        self.mask.append(np.asarray(mask, dtype=np.bool_))
        self.times_ns.append(np.asarray(times_ns, dtype=np.int64))
        self.n += len(values)

    def _concat_padded(self):
        if self._padded is not None:
            return self._padded
        npad = winmod.pad_to(max(self.n, 1))
        values = np.zeros(npad, dtype=self.dtype)
        rel_hi = np.zeros(npad, dtype=np.int32)
        rel_lo = np.zeros(npad, dtype=np.int32)
        seg_ids = np.zeros(npad, dtype=np.int32)
        mask = np.zeros(npad, dtype=np.bool_)
        off = 0
        for v, h, l, s, m in zip(self.values, self.rel_hi, self.rel_lo, self.seg_ids, self.mask):
            k = len(v)
            values[off : off + k] = v
            rel_hi[off : off + k] = h
            rel_lo[off : off + k] = l
            seg_ids[off : off + k] = s
            mask[off : off + k] = m
            off += k
        self._padded = (values, rel_hi, rel_lo, seg_ids, mask)
        # the padded batch crosses to the device on the next kernel call
        devobs.note_transfer("h2d", "agg-batch",
                             sum(a.nbytes for a in self._padded))
        return self._padded

    def layout_name(self) -> str:
        """Trace label for EXPLAIN ANALYZE (each batch class owns its
        own name; executor never inspects internals)."""
        return "scatter"

    def host_times(self) -> np.ndarray:
        return (
            np.concatenate(self.times_ns) if self.times_ns else np.empty(0, np.int64)
        )

    def host_value_multiset(self, num_segments: int):
        """Per-segment (value, count) multiset of the batch's masked rows:
        (values f64, counts i64, offsets i64[num_segments+1]), values
        sorted ascending within each segment. EXACTLY mergeable across
        nodes — rank-based aggregates (percentile/median/count_distinct)
        recompute losslessly from merged multisets, so distributed
        pushdown ships O(groups x distinct) instead of raw columns
        (reference: the hash-exchange distribution of rank aggs,
        engine/executor agg transforms)."""
        if not self.values:
            return (np.empty(0, np.float64), np.empty(0, np.int64),
                    np.zeros(num_segments + 1, np.int64))
        v = np.concatenate(
            [np.asarray(x, np.float64) for x in self.values])
        s = np.concatenate(
            [np.asarray(x, np.int64) for x in self.seg_ids])
        m = np.concatenate([x for x in self.mask])
        keep = m & (s >= 0) & (s < num_segments)
        v, s = v[keep], s[keep]
        if len(v) == 0:
            return (v, np.empty(0, np.int64),
                    np.zeros(num_segments + 1, np.int64))
        order = np.lexsort((v, s))
        v, s = v[order], s[order]
        new = np.empty(len(v), np.bool_)
        new[0] = True
        new[1:] = (s[1:] != s[:-1]) | (v[1:] != v[:-1])
        starts = np.flatnonzero(new)
        counts = np.diff(np.append(starts, len(v)))
        v_u, s_u = v[starts], s[starts]
        offs = np.searchsorted(s_u, np.arange(num_segments + 1))
        return v_u, counts.astype(np.int64), offs.astype(np.int64)

    def counts(self, num_segments: int) -> np.ndarray:
        """Per-segment valid-row counts (cached per batch — every aggregate
        needs them for null rendering, compute once)."""
        got = self._counts_cache.get(num_segments)
        if got is None:
            seg_pad = winmod.pad_to(max(num_segments, 1), 256)
            arrays = self._concat_padded()
            counts, _ = _jitted(_count_fn, seg_pad, ())(*arrays)
            got = devobs.fetch_np(counts)[:num_segments]
            self._counts_cache[num_segments] = got
        return got

    def run(self, spec: AggSpec, num_segments: int, params: tuple = ()):
        """Execute one aggregate; returns (values[num_segments],
        sel_idx[num_segments] | None, counts[num_segments]).

        With a configured device mesh (parallel/runtime.py) the mesh-
        servable aggregates run as ONE shard_map program over all devices
        (rows sharded, collective merges) — the executor's actual
        multi-chip path; the sel contract is identical (global row
        indices), so selector time resolution is unchanged."""
        from opengemini_tpu.parallel import runtime as prt

        mesh = prt.get_mesh()
        if mesh is not None and not params:
            got = self._run_mesh(mesh, spec, num_segments)
            if got is not None:
                return got
        seg_pad = winmod.pad_to(max(num_segments, 1), 256)
        arrays = self._concat_padded()
        fn = _jitted(spec.fn, seg_pad, tuple(params))
        _STATS.incr("device", "kernel_launches")
        t0 = devobs.t0()
        out, sel = fn(*arrays)
        if t0:
            # dispatch only — the blocking fetch below attributes to
            # device_transfer (fetch_np), never double-counted here
            devobs.note_exec(t0)
        out_np = devobs.fetch_np(out)[:num_segments]
        sel_np = (devobs.fetch_np(sel)[:num_segments]
                  if sel is not None else None)
        return out_np, sel_np, self.counts(num_segments)

    def _run_mesh(self, mesh, spec, num_segments: int):
        from opengemini_tpu.parallel import distributed as dist

        if spec.name not in dist.MESH_AGGS:
            return None
        seg_pad = winmod.pad_to(max(num_segments, 1), 256)
        # winner-merge machinery is only compiled for the selector this
        # spec actually needs; value-only aggregates share one program
        sel = (spec.name,) if spec.name in ("min", "max", "first", "last") else ()
        cache_key = (seg_pad, sel)
        outs = self._mesh_outs.get(cache_key)
        if outs is None:
            values, rel_hi, rel_lo, seg_ids, mask = self._concat_padded()
            gidx = np.arange(len(values), dtype=np.int32)
            fn = dist.batch_agg_jit(mesh, seg_pad, sel)
            sharded = dist.shard_rows(
                mesh, values, rel_hi, rel_lo, seg_ids, mask, gidx
            )
            t0 = devobs.t0()
            got = fn(*sharded)
            if t0:
                devobs.note_exec(t0)  # dispatch; fetch attributes below
            outs = {k: devobs.fetch_np(v) for k, v in got.items()}
            self._mesh_outs[cache_key] = outs
        out = outs[spec.name][:num_segments]
        sel = outs.get(spec.name + "_sel")
        if sel is not None:
            sel = sel[:num_segments]
        counts = outs["count"][:num_segments]
        return out, sel, counts
