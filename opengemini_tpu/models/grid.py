"""Regular-grid dense batch: production wiring for the windows-on-lanes
fast path (ops/segment.py grid_window_agg_t).

TSBS-shaped data — every series sampled on a constant stride — lets
windowed aggregation skip segment machinery entirely: place samples into
a dense (series_run, samples_per_window, num_windows) grid and every
per-window statistic is one sublane-axis reduce (measured 132-290 G
rows/s on v5e-1 vs 62-79 G for the bucketed layout; bench.py config #1).
The reference reaches its regular fast path through pre-aggregation
metadata + the interval cursor (engine/immutable/pre_aggregation.go:40,
engine/aggregate_cursor.go:343); here regularity is detected per scan and
the grid is assembled directly from the scanned chunks.

GridBatch is SPECULATIVE: add() accumulates raw rows exactly like
BucketedBatch; the first run() checks regularity (one global stride that
divides the window, per-series-run constant spacing, bounded density
waste) and either assembles the grid or silently delegates to a
BucketedBatch built from the same rows. Wrong results are impossible —
only the layout changes. The executor's stats counters record which path
engaged (executor/grid_batches vs executor/grid_fallbacks).

Contract is the AggBatch/BucketedBatch contract: add(values, rel_ns,
seg_ids, mask, times_ns, sids=...) + run(spec, num_segments, params) ->
(values, sel|None, counts), where sel indexes the batch's host_times()
row order (selector timestamp resolution is unchanged).
"""

from __future__ import annotations

import functools
import time

import numpy as np

from opengemini_tpu.models import ragged, templates
from opengemini_tpu.utils import devobs
from opengemini_tpu.utils.stats import GLOBAL as STATS

# aggregates the grid path serves; others never get routed here
GRID_AGGS = {"count", "sum", "mean", "min", "max", "spread", "stddev",
             "first", "last"}

_MIN_S = 8
_MIN_W = 8
# hard cap on grid slots (~0.9 GB f64+mask+idx at 2^26) and max slots per
# scanned row (sparse series would explode the dense grid)
_MAX_GRID_CELLS = 1 << 26
_MAX_EXPANSION = 8
# samples-per-window above this would make (S, k, W) degenerate (one
# giant sublane axis); bucketed split rows handle it better
_MAX_K = 8192


class _EncodedVals:
    """Array-like holder of one add_encoded() value column that is still
    in its on-disk encoded blocks (record.EncodedColumn): the grid
    freeze ships the raw payloads to the device decoder
    (ops/device_decode.py); any host consumer — the bucketed fallback,
    a scatter rebuild — decodes via __array__, the same numbers by
    construction."""

    __slots__ = ("col",)

    def __init__(self, col):
        self.col = col

    def __len__(self):
        return len(self.col)

    def __array__(self, dtype=None, copy=None):
        v = self.col.values
        return np.asarray(v, dtype=dtype) if dtype is not None \
            else np.asarray(v)


class GridBatch:
    accepts_boundaries = True  # coalesced adds forward record breaks

    def __init__(self, dtype, W: int, every_ns: int):
        self.dtype = dtype or templates.compute_dtype()
        self.W = int(W)
        self.every_ns = int(every_ns)
        self._vals: list[np.ndarray] = []
        self._rel: list[np.ndarray] = []
        self._seg: list[np.ndarray] = []
        self._mask: list[np.ndarray] = []
        self._times: list[np.ndarray] = []
        self._sids: list[np.ndarray | None] = []
        self._bnds: list[np.ndarray | None] = []
        self.n = 0
        self._state = None  # grid state dict after a successful freeze
        self._fallback = None  # BucketedBatch when the grid refuses
        self._raw: dict = {}  # lazy per-(row, window) device stats
        # scan signature for the decoded-column cache's DEVICE tier
        # (storage/colcache.py): when the executor proves the scan
        # deterministic (local shards) it stamps a token here and the
        # padded device_put grid buffers — MESH-SHARDED when a device
        # mesh is configured — are retained/reused across identical
        # scans: a warm repeat skips the H2D transfer, the per-query
        # reshard, and (on a hit) the host-side grid scatter too
        self.device_cache_token = None

    def add(self, values, rel_ns, seg_ids, mask, times_ns, sids=None,
            boundaries=None):
        """`boundaries` (optional sorted row offsets within this add)
        marks run breaks inside a coalesced add — per-shard sid numbering
        is independent, so a stager that concatenates records from
        different shards must keep equal sid values from fusing into one
        stride run."""
        self._push(np.asarray(values, dtype=self.dtype), rel_ns, seg_ids,
                   mask, times_ns, sids, boundaries)

    def add_encoded(self, col, rel_ns, seg_ids, mask, times_ns, sids=None,
                    boundaries=None):
        """add() variant taking a still-encoded value column
        (record.EncodedColumn): when EVERY add of the batch arrives
        encoded, the freeze ships the raw block payloads to the device
        and one jit program decodes, scatters, and reduces
        (ops/device_decode.py); every fallback path decodes on the host
        through the column's lazy .values — bit-identical either way."""
        self._push(_EncodedVals(col), rel_ns, seg_ids, mask, times_ns,
                   sids, boundaries)

    def _push(self, vals, rel_ns, seg_ids, mask, times_ns, sids,
              boundaries):
        self._vals.append(vals)
        self._rel.append(np.asarray(rel_ns, dtype=np.int64))
        self._seg.append(np.asarray(seg_ids, dtype=np.int64))
        self._mask.append(np.asarray(mask, dtype=np.bool_))
        self._times.append(np.asarray(times_ns, dtype=np.int64))
        if sids is None:
            self._sids.append(None)
        elif np.isscalar(sids):
            self._sids.append(
                np.full(len(self._vals[-1]), sids, dtype=np.int64))
        else:
            self._sids.append(np.asarray(sids, dtype=np.int64))
        self._bnds.append(
            None if boundaries is None
            else np.asarray(boundaries, dtype=np.int64))
        self.n += len(self._vals[-1])

    def layout_name(self) -> str:
        if self._state is not None:
            return "grid"
        if self._fallback is not None:
            return "grid->bucketed"
        return "grid (not executed)"  # e.g. full result-cache hit

    def host_times(self) -> np.ndarray:
        return (np.concatenate(self._times) if self._times
                else np.empty(0, np.int64))

    def host_value_multiset(self, num_segments: int):
        """Rank-aggregate multisets never route to the grid path locally,
        but the distributed merge may ask any batch for them."""
        self._ensure_fallback()
        return self._fallback.host_value_multiset(num_segments)

    # -- freeze ----------------------------------------------------------

    def _ensure_fallback(self):
        if self._fallback is None:
            if self._vals is None:
                raise RuntimeError(
                    "bucketed fallback requested after prefetch() dropped "
                    "the raw rows — prefetch callers must keep aggs "
                    "within GRID_AGGS")
            fb = ragged.BucketedBatch(self.dtype)
            for v, r, s, m, t in zip(self._vals, self._rel, self._seg,
                                     self._mask, self._times):
                fb.add(v, r, s, m, t)
            self._fallback = fb

    def _freeze(self, num_segments: int):
        """Returns the grid state dict, or None (delegate to bucketed)."""
        if self._state is not None or self._fallback is not None:
            return self._state
        state = self._try_grid(num_segments)
        if state is None:
            STATS.incr("executor", "grid_fallbacks")
            self._ensure_fallback()
        else:
            STATS.incr("executor", "grid_batches")
            self._state = state
        return self._state

    def _try_grid(self, num_segments: int):
        W = self.W
        if self.n == 0 or W < 1 or num_segments % W:
            return None
        if any(s is None for s in self._sids):
            return None  # no series identity: cannot prove no slot clash
        rel = np.concatenate(self._rel)
        seg = np.concatenate(self._seg)
        sid = np.concatenate(self._sids)
        n = len(rel)
        # series runs: sid change or chunk boundary (the same series split
        # across shards/chunks gets separate rows — a run is only required
        # to be internally constant-stride)
        boundary = np.zeros(n, dtype=np.bool_)
        boundary[0] = True
        boundary[1:] = sid[1:] != sid[:-1]
        off = 0
        for v, b in zip(self._vals, self._bnds):
            if b is not None and len(b):
                boundary[off + b] = True  # coalesced-add record breaks
            off += len(v)
            if off < n:
                boundary[off] = True
        d = np.diff(rel)
        inner = ~boundary[1:]
        dd = d[inner]
        if len(dd) and int(dd.min()) <= 0:
            return None  # duplicate/unsorted times within a run
        # dt = gcd(all within-run diffs, window) — every within-run diff is
        # then a positive multiple of dt and every run's times share one
        # residue class mod dt, so (window, (rel - w*every)//dt) is
        # injective per run: gaps and per-series phase shifts grid fine,
        # they just leave masked-off slots. All-singleton runs (one sample
        # per series) degenerate to k=1.
        dt = _stride_gcd(dd, self.every_ns) if len(dd) else self.every_ns
        if dt <= 0 or self.every_ns % dt:
            return None
        k = self.every_ns // dt
        if k > _MAX_K:
            return None
        bnd_idx = np.flatnonzero(boundary)
        S = len(bnd_idx)
        S_pad = _pad_rows(S, _MIN_S)
        W_pad = _pad_lanes(W, _MIN_W)
        mesh = self._mesh_for_rows(S_pad)
        if mesh is not None and S_pad % mesh.size:
            # multi-chip: pad the row axis to a mesh multiple up front so
            # the grid scatters straight into the shardable shape (no
            # second padding copy at device_put time) and the device-tier
            # signature shape is stable across cold/warm scans
            S_pad += mesh.size - S_pad % mesh.size
        cells = S_pad * k * W_pad  # padded = what actually allocates
        if cells > _MAX_GRID_CELLS or cells > max(_MAX_EXPANSION * n, 1 << 20):
            return None
        w = seg % W
        r = (rel - w * self.every_ns) // dt
        if (r < 0).any() or (r >= k).any():
            return None  # window grid misaligned with the stride grid
        rid = np.cumsum(boundary) - 1
        flat = (rid * k + r) * W_pad + w
        # device tier consult: an identically-signed earlier scan already
        # holds the padded grid on device — skip the host scatter AND the
        # H2D transfer (the signature embeds every shard's data_version,
        # so content equality is the same guarantee the incremental
        # result cache relies on)
        dev_entry = None
        if self.device_cache_token is not None:
            from opengemini_tpu.storage import colcache

            dev_entry = colcache.GLOBAL.device_get(
                self.device_cache_token,
                shape=(S_pad, k, W_pad), dtype=str(self.dtype), mesh=mesh)
        enc_plan = None
        host_s = None
        if dev_entry is None:
            enc_plan = self._encoded_plan((S_pad, k, W_pad), flat, mesh,
                                          rel, bnd_idx, dt)
            if enc_plan is not None:
                arrays = None
            else:
                # host route: the decode (through _EncodedVals.__array__)
                # + scatter wall; each launch adds its own dispatch wall
                # so the planner's host samples cover the same span the
                # fused device sample does — including the selector
                # group's second full-grid transfer, which the device
                # route avoids by keeping the grid resident
                t0 = time.perf_counter()
                arrays = self._scatter_grid((S_pad, k, W_pad), flat)
                host_s = time.perf_counter() - t0
        else:
            arrays = None
        run_gid = (seg[bnd_idx] // W).astype(np.int64)
        order = np.argsort(run_gid, kind="stable")
        sg = run_gid[order]
        gb = np.empty(S, dtype=np.bool_)
        gb[0] = True
        gb[1:] = sg[1:] != sg[:-1]
        starts = np.flatnonzero(gb)
        return {
            "k": k, "S": S, "W_pad": W_pad, "shape": (S_pad, k, W_pad),
            "arrays": arrays, "device_entry": dev_entry,
            "encoded_plan": enc_plan, "host_route_s": host_s,
            # imat (sample-index grid for the selector kernels) builds
            # lazily from `flat` — count/sum/mean scans never pay for it
            "imat": None, "flat": flat, "n": n,
            "rel": rel,
            "row_order": order,  # grid rows sorted by gid
            "gid_starts": starts,  # reduceat starts in row_order
            "gids_present": sg[starts],
            "rows_per_gid": np.diff(np.append(starts, S)),
        }

    # -- execution -------------------------------------------------------

    def run(self, spec, num_segments: int, params: tuple = (),
            want_sel: bool = True):
        """want_sel=False skips the selector index machinery for min/max
        (their values come from the basic kernel) — the sliced scan path
        never consults sel (selector timestamps only matter without
        GROUP BY time())."""
        st = self._freeze(num_segments)
        if st is None:
            return self._fallback.run(spec, num_segments, params,
                                      want_sel=want_sel)
        name = spec.name
        if name not in GRID_AGGS:
            self._ensure_fallback()
            return self._fallback.run(spec, num_segments, params,
                                      want_sel=want_sel)
        G = num_segments // self.W
        raw = self._raw_stats(
            need_ssd=(name == "stddev"),
            need_selectors=name in ("first", "last") or (
                want_sel and name in ("min", "max")),
        )
        order, starts = st["row_order"], st["gid_starts"]
        gids, W = st["gids_present"], self.W

        cnt_rows = raw["count"][order].astype(np.int64)
        cnt_g = np.add.reduceat(cnt_rows, starts, axis=0)
        counts = np.zeros(num_segments, dtype=np.int64)
        counts.reshape(G, W)[gids] = cnt_g

        out = np.zeros(num_segments, dtype=np.float64)
        out2d = out.reshape(G, W)
        sel = None
        if name == "count":
            out2d[gids] = cnt_g
        elif name == "sum":
            out2d[gids] = np.add.reduceat(raw["sum"][order], starts, axis=0)
        elif name == "mean":
            s = np.add.reduceat(raw["sum"][order], starts, axis=0)
            out2d[gids] = s / np.maximum(cnt_g, 1)
        elif name == "min":
            out2d[gids] = np.minimum.reduceat(raw["min"][order], starts, axis=0)
            if want_sel:
                sel = self._combine_value_selector(st, raw, "min", num_segments)
        elif name == "max":
            out2d[gids] = np.maximum.reduceat(raw["max"][order], starts, axis=0)
            if want_sel:
                sel = self._combine_value_selector(st, raw, "max", num_segments)
        elif name == "spread":
            mn = np.minimum.reduceat(raw["min"][order], starts, axis=0)
            mx = np.maximum.reduceat(raw["max"][order], starts, axis=0)
            out2d[gids] = mx - mn
        elif name == "stddev":
            s = np.add.reduceat(raw["sum"][order], starts, axis=0)
            mean_g = s / np.maximum(cnt_g, 1)
            # exact k-way variance combine across the gid's series rows:
            # SSD = sum_i [ssd_i + c_i (mu_i - mu)^2]
            mean_rep = np.repeat(mean_g, st["rows_per_gid"], axis=0)
            extra = cnt_rows * (raw["mean"][order] - mean_rep) ** 2
            ssd = np.add.reduceat(raw["ssd"][order] + extra, starts, axis=0)
            out2d[gids] = np.sqrt(
                np.maximum(ssd / np.maximum(cnt_g - 1, 1), 0))
        elif name in ("first", "last"):
            vals2d, sel = self._combine_time_selector(st, raw, name,
                                                      num_segments)
            out2d[gids] = vals2d
        return out, sel, counts

    def _encoded_plan(self, shape, flat, mesh, rel, starts, dt):
        """Fused device-decode plan for a fully-encoded cold scan
        (ops/device_decode.py), or None: every add must still carry its
        encoded blocks and the decoder must accept every block.  Under a
        configured mesh the plan is partitioned by output row shard
        (rows are already padded to a mesh multiple) so each device
        decodes only its own shard's bytes.  None means the freeze
        scatters on the host exactly as it always has."""
        if not self._vals:
            return None
        views = []
        any_decoded = False
        for v in self._vals:
            col = getattr(v, "col", None)
            if col is None:
                return None
            if col.is_decoded:
                # the colcache host tier already decoded this column —
                # but the encoded blocks are still attached, so the
                # DEVICE route stays available: a warm planner can
                # route the repeat back to the accelerator where the
                # decoded grid goes RESIDENT (colcache device tier)
                # and every later repeat skips decode AND transfer
                any_decoded = True
            views.append((col.blocks, col.abs_segments(), col.n_full))
        from opengemini_tpu.ops import device_decode
        from opengemini_tpu.query import offload

        # THE route decision for the encoded cold scan (query/offload.py):
        # static prior = today's behavior (attempt the device build on
        # cold encoded columns — the byte gate stays live as the
        # planner's zero-sample prior; scatter on the host once the
        # columns are already decoded), so a cold or disabled planner is
        # bit-identical to the pre-planner dispatch.  "host" skips the
        # build — the freeze scatters on the host exactly as it always
        # has, without counting it as a decode fallback (it is a
        # routing choice, not a failure)
        dev_route = "mesh" if mesh is not None else "device"
        static = "host" if any_decoded else dev_route
        geo = (tuple(shape), str(self.dtype))
        route = offload.GLOBAL.decide(
            "grid_decode", geo, ("host", dev_route), static,
            stage="grid_decode")
        if route == "host" and not offload.wants_prewarm(
                "grid_decode", geo):
            return None
        mask = np.concatenate(self._mask)
        if mesh is not None:
            plan = device_decode.build_mesh_grid_plan(
                views, flat, mask, shape, self.dtype, mesh,
                rel=rel, starts=starts, every_ns=self.every_ns, dt=dt)
        else:
            plan = device_decode.build_grid_plan(
                views, flat, mask, shape, self.dtype,
                rel=rel, starts=starts, every_ns=self.every_ns, dt=dt)
        if route == "host":
            # flip-justified by the planner but not yet compiled: hand
            # the fused program to the BACKGROUND pre-warmer (the plan
            # build above is host-side only) — this query still
            # scatters on the host, and the geometry flips to the
            # device once the compile lands
            if plan is not None:
                if mesh is not None:
                    geoms = tuple(p.geom for p in plan.shards)
                    offload.register_builder(
                        "grid_decode", geo,
                        lambda gs=geoms: [device_decode._grid_program(g)
                                          for g in gs])
                else:
                    offload.register_builder(
                        "grid_decode", geo,
                        lambda g=plan.geom: device_decode._grid_program(g))
            return None
        if plan is None:
            STATS.incr("executor", "grid_decode_fallbacks")
        return plan

    def _scatter_grid(self, shape, flat):
        """Scatter the raw rows into the padded (S_pad, k, W_pad) grid:
        the ONE scatter shared by freeze and the entry-lost rebuild, so
        the rare rebuild branch can never diverge from the hot path."""
        vt = np.zeros(shape, dtype=self.dtype)
        mt = np.zeros(shape, dtype=np.bool_)
        vt.reshape(-1)[flat] = np.concatenate(self._vals)
        mt.reshape(-1)[flat] = np.concatenate(self._mask)
        return vt, mt

    def _build_imat_np(self):
        st = self._state
        if st["flat"] is None:
            raise RuntimeError(
                "selector index grid needed after prefetch dropped the "
                "host rows — prefetch callers must declare selector aggs")
        imat = np.zeros(st["shape"], dtype=np.int32)
        imat.reshape(-1)[st["flat"]] = np.arange(st["n"], dtype=np.int32)
        return imat

    @staticmethod
    def _mesh_for_rows(rows: int):
        """The configured device mesh when ``rows`` grid rows can shard
        over it, else None (replicated single-device exactly as before)."""
        from opengemini_tpu.parallel import runtime as _prt

        mesh = _prt.get_mesh()
        if mesh is None or rows < mesh.size:
            return None
        return mesh

    def _device_put(self, mesh, *arrays_np, xfer_site: str = "grid-shard"):
        """One explicit device_put per array, straight into the final
        layout: row-sharded over the mesh when configured (NamedSharding,
        parallel/distributed.py), plain single-device otherwise — never a
        replicated intermediate that a later reshard would re-copy."""
        import time as _time

        import jax

        if mesh is not None:
            from opengemini_tpu.parallel import distributed as _dist

            return _dist.shard_leading_axis(mesh, *arrays_np,
                                            xfer_site=xfer_site)
        t0 = _time.perf_counter_ns()
        out = tuple(jax.device_put(a) for a in arrays_np)
        devobs.note_transfer(
            "h2d", xfer_site, sum(int(a.nbytes) for a in arrays_np),
            (_time.perf_counter_ns() - t0) / 1e9)
        return out

    def _device_arrays(self, with_imat: bool):
        st = self._state
        mesh = self._mesh_for_rows(st["shape"][0])
        ent = st.get("device_entry")
        if ent is not None and ent.get("mesh") is not mesh:
            # mesh changed since the entry was consulted/stored (hot
            # config reload): re-get — the cache reshards the retained
            # buffers onto the new mesh, donating the stale layout
            from opengemini_tpu.storage import colcache

            ent = colcache.GLOBAL.device_get(
                self.device_cache_token, shape=st["shape"],
                dtype=str(self.dtype), mesh=mesh)
            st["device_entry"] = ent
        if (ent is None and self.device_cache_token is not None
                and st["arrays"] is not None):
            # cold scan with the device tier on: one transfer into the
            # final (sharded) layout, retained in the cache — later
            # kernel kinds of THIS scan and identically-signed future
            # scans all skip the transfer
            from opengemini_tpu.storage import colcache

            vt_np, mt_np = st["arrays"]
            vt_d, mt_d = self._device_put(mesh, vt_np, mt_np,
                                          xfer_site="colcache-fill")
            ent = colcache.GLOBAL.device_put_grid(
                self.device_cache_token, vt_d, mt_d,
                shape=vt_np.shape, dtype=str(vt_np.dtype), mesh=mesh)
            st["device_entry"] = ent
        if ent is not None:
            imat = None
            if with_imat:
                imat = ent.get("imat")
                if imat is None:
                    from opengemini_tpu.storage import colcache

                    ent_mesh = ent.get("mesh")
                    flat_dev = st.get("flat_dev")
                    if flat_dev is not None and ent_mesh is None:
                        # fused-decode entries keep their scatter slots
                        # on device: build the selector grid there
                        from opengemini_tpu.ops import device_decode

                        imat_d = device_decode.imat_from_flat(
                            flat_dev, st["shape"])
                    else:
                        (imat_d,) = self._device_put(
                            ent_mesh, self._build_imat_np(),
                            xfer_site="colcache-fill")
                    imat = colcache.GLOBAL.device_add_imat(
                        self.device_cache_token, ent, imat_d,
                        mesh=ent_mesh)
                    if ent.get("mesh") is not ent_mesh:
                        # a concurrent reshard moved the entry while the
                        # imat was building: one more pass picks up the
                        # new layout end to end (bounded by mesh swaps,
                        # which are rare admin events)
                        return self._device_arrays(with_imat)
            return ent["vt"], ent["mt"], imat
        if st["arrays"] is None:
            # the freeze-time device-cache hit skipped the host scatter,
            # then the entry vanished (mesh swap dropped an indivisible
            # geometry, or LRU eviction): rebuild the grid from the raw
            # rows — unless prefetch() already dropped them
            if self._vals is None or st["flat"] is None:
                raise RuntimeError(
                    "grid device entry lost after prefetch dropped the "
                    "host rows (device mesh changed mid-query?)")
            st["arrays"] = self._scatter_grid(st["shape"], st["flat"])
            # a pending fused-decode plan is superseded by the host
            # scatter (encoded adds decode through _EncodedVals.__array__)
            st["encoded_plan"] = None
        vt, mt = st["arrays"]
        imat = None
        if with_imat:
            imat = st["imat"]
            if imat is None:
                imat = self._build_imat_np()
                st["imat"] = imat
        if mesh is not None:
            # multi-chip: series-run rows are independent — shard the S
            # axis, GSPMD partitions the sublane reduces, no collectives.
            # Keyed by mesh EPOCH: a hot config reload (runtime.set_mesh)
            # must never serve shards laid out for a dead mesh.
            from opengemini_tpu.parallel import distributed as _dist
            from opengemini_tpu.parallel import runtime as _prt

            epoch = _prt.mesh_epoch()
            if st.get("mesh_epoch") != epoch:
                st.pop("mesh_arrays", None)
                st.pop("mesh_imat", None)
                devobs.LEDGER.drop(st.pop("ledger", None))
                st["mesh_epoch"] = epoch
            if "mesh_arrays" not in st:
                st["mesh_arrays"] = _dist.shard_leading_axis(
                    mesh, vt, mt, xfer_site="grid-shard")
                st["ledger"] = devobs.LEDGER.register(
                    "grid_mesh", sum(int(a.nbytes)
                                     for a in st["mesh_arrays"]),
                    mesh_epoch=epoch, label="grid", anchor=self)
            vt, mt = st["mesh_arrays"]
            if with_imat:
                if "mesh_imat" not in st:
                    (st["mesh_imat"],) = _dist.shard_leading_axis(
                        mesh, imat, xfer_site="grid-shard")
                    devobs.LEDGER.update(
                        st.get("ledger"),
                        sum(int(a.nbytes) for a in st["mesh_arrays"])
                        + int(st["mesh_imat"].nbytes))
                imat = st["mesh_imat"]
        return vt, mt, imat

    def _launch(self, kind: str):
        """Dispatch one kernel group; returns unmaterialized device
        results (JAX dispatch is async — the host is free to keep
        decoding while the device reduces)."""
        st = self._state
        plan = st.get("encoded_plan")
        if plan is not None and kind == "basic":
            # fused cold path: compressed bytes -> device -> decode ->
            # scatter -> basic reduce in ONE jit program; the decoded
            # grid buffers come back for retention so ssd/selector
            # kernels (and identically-signed future scans through the
            # colcache device tier) reuse them without any transfer
            from opengemini_tpu.ops import device_decode
            from opengemini_tpu.query import offload

            plan_mesh = getattr(plan, "mesh", None)
            t0 = time.perf_counter()
            if plan_mesh is not None:
                stats, vt, mt, flat_d = \
                    device_decode.run_mesh_grid_plan(plan)
            else:
                stats, vt, mt, flat_d = device_decode.run_grid_plan(plan)
            offload.GLOBAL.observe(
                "grid_decode", (st["shape"], str(self.dtype)),
                "mesh" if plan_mesh is not None else "device",
                time.perf_counter() - t0)
            st["encoded_plan"] = None
            ent = None
            if self.device_cache_token is not None:
                from opengemini_tpu.storage import colcache

                ent = colcache.GLOBAL.device_put_grid(
                    self.device_cache_token, vt, mt,
                    shape=st["shape"], dtype=str(self.dtype),
                    mesh=plan_mesh)
            if ent is None:
                ent = {"vt": vt, "mt": mt, "imat": None,
                       "shape": st["shape"], "dtype": str(self.dtype),
                       "mesh": plan_mesh}
            # device-resident scatter slots, QUERY-scoped (on st, not
            # the retained cache entry — the cache's budget/ledger
            # accounting must not carry unaccounted buffers): this
            # query's selector imat builds from them on device
            # (device_decode.imat_from_flat) with no host grid
            # transfer; warm repeats reuse the retained imat instead
            st["flat_dev"] = flat_d
            st["device_entry"] = ent
            STATS.incr("executor", "grid_decode_fused")
            return stats
        vt, mt, imat = self._device_arrays(with_imat=(kind == "selectors"))
        t0 = devobs.t0()
        tw = time.perf_counter()
        if kind == "selectors":
            out = _grid_jit(vt.shape, str(vt.dtype), kind)(vt, mt, imat)
        else:
            out = _grid_jit(vt.shape, str(vt.dtype), kind)(vt, mt)
        if t0:
            devobs.note_exec(t0)
        if st.get("arrays") is not None or st.get("host_route_s") is not None:
            # host-route planner sample, one per kernel group: the first
            # launch carries the decode+scatter wall (freeze), every
            # launch adds its own H2D-and-reduce dispatch — together the
            # same span the fused device route's single sample covers
            from opengemini_tpu.query import offload

            base = st.pop("host_route_s", None)
            offload.GLOBAL.observe(
                "grid_decode", (st["shape"], str(self.dtype)), "host",
                (base or 0.0) + (time.perf_counter() - tw))
        return out

    supports_want_sel = True

    def prefetch(self, num_segments: int, agg_names,
                 want_sel: bool = False) -> None:
        """Sliced-scan overlap hook: freeze the grid and dispatch every
        kernel this batch's aggregates will need, then drop the host-side
        row lists and grid arrays — run() materializes the in-flight
        device results later. No-op when the grid refuses (bucketed
        fallback keeps its rows) or an agg outside GRID_AGGS is coming."""
        names = set(agg_names)
        if not names or not names <= GRID_AGGS:
            return
        st = self._freeze(num_segments)
        if st is None:
            return
        self._pending = getattr(self, "_pending", {})
        if "basic" not in self._pending:
            self._pending["basic"] = self._launch("basic")
        if "stddev" in names and "ssd" not in self._pending:
            self._pending["ssd"] = self._launch("ssd")
        need_sel_kernel = bool(names & {"first", "last"}) or (
            want_sel and bool(names & {"min", "max"}))
        if need_sel_kernel and "selectors" not in self._pending:
            self._pending["selectors"] = self._launch("selectors")
        # inputs are on device now; free the host copies
        st["arrays"] = None
        st["imat"] = None
        st["flat"] = None
        st.pop("mesh_arrays", None)
        st.pop("mesh_imat", None)
        devobs.LEDGER.drop(st.pop("ledger", None))
        self._vals = self._rel = self._seg = self._mask = self._sids = None
        self._bnds = None

    def _raw_stats(self, need_ssd: bool, need_selectors: bool) -> dict:
        st = self._state
        S = st["S"]
        pending = getattr(self, "_pending", {})

        def settle(kind):
            got = pending.pop(kind, None)
            if got is None:
                if (st["arrays"] is None and st.get("device_entry") is None
                        and st.get("encoded_plan") is None):
                    raise RuntimeError(
                        f"grid kernel {kind!r} needed after prefetch "
                        "dropped the host arrays")
                got = self._launch(kind)
            if kind == "ssd":
                self._raw["ssd"] = devobs.fetch_np(got)[:S, : self.W]
            else:
                self._raw.update(
                    {k: devobs.fetch_np(v)[:S, : self.W]
                     for k, v in got.items()})

        if "count" not in self._raw:
            settle("basic")
        if need_ssd and "ssd" not in self._raw:
            settle("ssd")
        if need_selectors and "sel_first" not in self._raw:
            settle("selectors")
        return self._raw

    def _combine_value_selector(self, st, raw, name, num_segments):
        """Per-segment row index of the selected min/max point. Value ties
        break by earliest timestamp then row order — the BucketedBatch /
        ops/segment.py rule."""
        order, starts = st["row_order"], st["gid_starts"]
        gids = st["gids_present"]
        G = num_segments // self.W
        rel = st["rel"]
        S = st["S"]
        v = raw[name][order]
        red = np.minimum if name == "min" else np.maximum
        ext = red.reduceat(v, starts, axis=0)
        ext_rep = np.repeat(ext, st["rows_per_gid"], axis=0)
        cnt = raw["count"][order]
        sel_sub = raw["sel_" + name][order]
        hit = (v == ext_rep) & (cnt > 0)
        t = np.where(hit, rel[sel_sub], np.iinfo(np.int64).max)
        tbest = np.repeat(np.minimum.reduceat(t, starts, axis=0),
                          st["rows_per_gid"], axis=0)
        hit &= t == tbest
        rows = np.arange(S, dtype=np.int64)[:, None]
        idx = np.where(hit, rows, S)
        pick = np.clip(np.minimum.reduceat(idx, starts, axis=0), 0, S - 1)
        sel = np.zeros(num_segments, dtype=np.int64)
        # result[g, w] = sel_sub[pick[g, w], w] — rows align with gids order
        sel.reshape(G, self.W)[gids] = np.take_along_axis(sel_sub, pick, axis=0)
        return sel

    def _combine_time_selector(self, st, raw, name, num_segments):
        """first/last across a gid's series rows: pick by extreme exact
        timestamp (ties by row order). Returns (values for present gids,
        sel array)."""
        order, starts = st["row_order"], st["gid_starts"]
        gids = st["gids_present"]
        G = num_segments // self.W
        rel = st["rel"]
        S = st["S"]
        cnt = raw["count"][order]
        sel_sub = raw["sel_" + name][order]
        vals_sub = raw[name][order]
        latest = name == "last"
        bad = np.iinfo(np.int64).min if latest else np.iinfo(np.int64).max
        t = np.where(cnt > 0, rel[sel_sub], bad)
        red = np.maximum if latest else np.minimum
        tbest = np.repeat(red.reduceat(t, starts, axis=0),
                          st["rows_per_gid"], axis=0)
        hit = (cnt > 0) & (t == tbest)
        # exact-time ties across series rows: larger value wins
        # (reference FirstReduce/LastReduce tie rule)
        v_best = np.repeat(np.maximum.reduceat(
            np.where(hit, vals_sub, -np.inf), starts, axis=0),
            st["rows_per_gid"], axis=0)
        hit &= vals_sub == v_best
        rows = np.arange(S, dtype=np.int64)[:, None]
        if latest:
            # time ties pick the LATEST row in scan order — the
            # ops/segment.py `smax(idx)` rule for last()
            idx = np.where(hit, rows, -1)
            pick = np.clip(np.maximum.reduceat(idx, starts, axis=0), 0, S - 1)
        else:
            idx = np.where(hit, rows, S)
            pick = np.clip(np.minimum.reduceat(idx, starts, axis=0), 0, S - 1)
        vals2d = np.take_along_axis(vals_sub, pick, axis=0)
        sel = np.zeros(num_segments, dtype=np.int64)
        sel.reshape(G, self.W)[gids] = np.take_along_axis(sel_sub, pick, axis=0)
        return vals2d, sel


def _stride_gcd(dd: np.ndarray, every_ns: int) -> int:
    """gcd of every within-run time diff and the window length.
    np.gcd.reduce is per-element microcode (~200ns/elt — 4s on a 20M-row
    scan); constant-stride data (the common TSBS shape) exits via one
    vectorized modulo pass instead."""
    m = int(dd.min())
    if m <= 0:
        return 0
    if not (dd % m).any():  # every diff is a multiple of the smallest
        return int(np.gcd(m, every_ns))
    return int(np.gcd(np.gcd.reduce(np.unique(dd)), every_ns))


def _pow2_at_least(n: int, floor: int) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


@functools.lru_cache(maxsize=1)
def _lane_quantum() -> int:
    """Lane-axis padding quantum: 128 on TPU (the native lane tile —
    anything less re-pads on device), 8 on CPU/GPU backends where a
    128-wide floor at W=20 meant computing 6.4x the cells for nothing
    (the measured grid-loses-to-bucketed regression in bench_e2e's
    cpu-smoke shape)."""
    import jax

    return 128 if jax.default_backend() == "tpu" else 8


def lane_quantum() -> int:
    """Public backend lane quantum — the PromQL tiled kernels pad their
    window (lane) axis with the same rule as the grid W axis."""
    return _lane_quantum()


def _pad_lanes(n: int, floor: int) -> int:
    """Pad the lane (W) axis to a multiple of the backend quantum
    instead of a power of two: at W=1667 that is 1792 rather than 2048
    on TPU (-12% cells). Shape count stays bounded for the compile
    cache: the fine non-TPU quantum applies only below 256 lanes
    (<= 32 small shapes), then 128-multiples to 2048, pow2 above."""
    q = _lane_quantum()
    if n <= floor:
        return floor
    if n <= 256:
        return (n + q - 1) // q * q
    if n <= 2048:
        return (n + 127) // 128 * 128
    return _pow2_at_least(n, 2048)


def _pad_rows(n: int, floor: int) -> int:
    """Pad the row (S) axis in 1.5x steps instead of 2x: the padded rows
    are pure zeros the kernels still reduce over."""
    p = floor
    while p < n:
        p = (p * 3 + 1) // 2
        p = (p + 7) // 8 * 8
    return p


@functools.lru_cache(maxsize=256)
def _grid_jit(shape: tuple, dtype: str, kind: str):
    """Compiled (S_pad, k, W_pad) grid kernels, cached per canonical shape.
    'basic' = one fused pass for count/sum/mean/min/max; 'ssd' = two-pass
    squared deviations (the one-pass formula cancels catastrophically);
    'selectors' = within-row argmin/argmax sample selection for
    min/max/first/last."""
    import jax
    import jax.numpy as jnp

    devobs.note_compile("grid_" + kind, (shape, dtype))

    if kind == "basic":
        # deliberately XLA, not the Pallas grid kernel: the recorded v5e
        # measurements (ops/pallas_segment.py module docstring) show XLA's
        # own fusion WINNING for the pure grid reductions (~28-55 vs
        # ~22-48 G rows/s) — only the selector lex-scans benefit from
        # Pallas. Measurement beats ideology; it also keeps GSPMD row
        # sharding working under a device mesh (pallas_call does not
        # auto-partition).

        @jax.jit
        def basic(v, m):
            from opengemini_tpu.ops import segment as seg

            return seg.grid_window_agg_t(v, m)

        return basic

    if kind == "ssd":

        @jax.jit
        def ssd(v, m):
            zero = jnp.zeros((), v.dtype)
            vz = jnp.where(m, v, zero)
            cnt = m.sum(axis=1)
            mean = vz.sum(axis=1) / jnp.maximum(cnt, 1).astype(v.dtype)
            dev = jnp.where(m, v - mean[:, None, :], zero)
            return (dev * dev).sum(axis=1)

        return ssd

    @jax.jit
    def selectors(v, m, imat):
        big = jnp.array(jnp.inf, v.dtype)
        k = v.shape[1]
        mn = jnp.where(m, v, big).min(axis=1)
        mx = jnp.where(m, v, -big).max(axis=1)
        # argmin/argmax tie -> lowest k index = earliest in-row timestamp
        r_min = jnp.argmin(jnp.where(m, v, big), axis=1)
        r_max = jnp.argmin(jnp.where(m, -v, big), axis=1)
        r_first = jnp.argmax(m, axis=1)
        r_last = (k - 1) - jnp.argmax(m[:, ::-1, :], axis=1)

        def take(mat, ridx):
            return jnp.take_along_axis(mat, ridx[:, None, :], axis=1)[:, 0, :]

        return {
            "sel_min": take(imat, r_min), "sel_max": take(imat, r_max),
            "sel_first": take(imat, r_first), "sel_last": take(imat, r_last),
            "first": take(v, r_first), "last": take(v, r_last),
        }

    return selectors
