"""InfluxQL front-end: lexer, AST, recursive-descent parser.

Reference: the lifted influxql yacc parser
(lib/util/lifted/influx/influxql, ~24k LoC). This is a from-scratch
hand-written parser for the InfluxQL surface the TPU engine executes;
the AST mirrors influxql node naming (SelectStatement, BinaryExpr, Call,
VarRef...) so the planner reads like the reference's.
"""
