"""InfluxQL lexer.

Reference: lib/util/lifted/influx/influxql scanner. Context-sensitive bits
(regex literals after =~ / !~ / FROM) are handled by the parser asking for
`allow_regex` on the next token.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

KEYWORDS = {
    "select", "from", "where", "group", "by", "fill", "limit", "offset",
    "slimit", "soffset", "order", "asc", "desc", "and", "or", "not", "show",
    "databases", "measurements", "tag", "values", "keys", "field", "fields",
    "series", "retention", "policies", "policy", "create", "drop", "alter",
    "database",
    "with", "key", "in", "on", "duration", "replication", "shard", "default",
    "into", "true", "false", "null", "none", "previous", "linear", "tz",
    "measurement", "delete", "as", "name", "continuous", "query", "queries",
    "begin", "end", "resample", "every", "for", "explain", "analyze",
    "user", "users", "password", "privileges", "grant", "grants", "revoke",
    "to", "set", "read", "write", "all", "cardinality", "exact",
    "stream", "streams", "delay", "shards", "stats", "diagnostics",
    "subscription", "subscriptions", "destinations", "any", "kill",
    "downsample", "downsamples", "ttl", "sampleinterval", "timeinterval",
    "cluster", "union", "join", "inner", "outer", "full", "left", "right",
}

_DUR_RE = re.compile(r"(\d+)(ns|u|µ|us|ms|s|m|h|d|w)")
_DUR_NS = {
    "ns": 1,
    "u": 1_000,
    "us": 1_000,
    "µ": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3_600 * 1_000_000_000,
    "d": 86_400 * 1_000_000_000,
    "w": 7 * 86_400 * 1_000_000_000,
}


def parse_duration_ns(text: str) -> int | None:
    """Whole-string duration ('90s', '1h30m') -> ns, else None. The single
    duration-unit table for every surface (SQL lexer, logstore intervals)."""
    text = text.strip()
    total, j, n = 0, 0, len(text)
    if not n:
        return None
    while j < n:
        m = _DUR_RE.match(text, j)
        if not m or m.start() != j:
            return None
        total += int(m.group(1)) * _DUR_NS[m.group(2)]
        j = m.end()
    return total


@dataclass
class Token:
    kind: str  # IDENT KEYWORD STRING NUMBER INTEGER DURATION REGEX OP EOF
    val: object
    pos: int


class LexError(ValueError):
    pass


class Lexer:
    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        # optimizer hints seen while skipping /*+ ... */ comments; the
        # parser drains these per statement (reference: influxql hint pass)
        self.hints: list[str] = []
        self._hint_seen: set[int] = set()

    def peek(self, allow_regex: bool = False) -> Token:
        save = self.pos
        tok = self._scan(allow_regex)
        self.pos = save
        return tok

    def next(self, allow_regex: bool = False) -> Token:
        return self._scan(allow_regex)

    def _skip_ws(self) -> None:
        n = len(self.text)
        while self.pos < n:
            c = self.text[self.pos]
            if c in " \t\r\n":
                self.pos += 1
            elif c == "-" and self.text[self.pos : self.pos + 2] == "--":
                nl = self.text.find("\n", self.pos)
                self.pos = n if nl < 0 else nl
            elif c == "/" and self.text[self.pos : self.pos + 2] == "/*":
                # block comment; /*+ ... */ records optimizer hints
                # (peek() re-scans, so dedupe by start offset)
                end = self.text.find("*/", self.pos + 2)
                if (self.text[self.pos + 2 : self.pos + 3] == "+"
                        and self.pos not in self._hint_seen):
                    self._hint_seen.add(self.pos)
                    body = self.text[self.pos + 3 : (n if end < 0 else end)]
                    self.hints.extend(body.split())
                self.pos = n if end < 0 else end + 2
            else:
                break

    def _scan(self, allow_regex: bool) -> Token:
        self._skip_ws()
        text, n = self.text, len(self.text)
        if self.pos >= n:
            return Token("EOF", None, self.pos)
        start = self.pos
        c = text[start]

        if allow_regex and c == "/":
            i = start + 1
            buf = []
            while i < n:
                if text[i] == "\\" and i + 1 < n:
                    if text[i + 1] == "/":
                        buf.append("/")
                    else:
                        buf.append(text[i])
                        buf.append(text[i + 1])
                    i += 2
                    continue
                if text[i] == "/":
                    self.pos = i + 1
                    return Token("REGEX", "".join(buf), start)
                buf.append(text[i])
                i += 1
            raise LexError(f"unterminated regex at {start}")

        if c == "'":
            i = start + 1
            buf = []
            while i < n:
                if text[i] == "\\" and i + 1 < n:
                    buf.append({"n": "\n", "t": "\t", "'": "'", "\\": "\\"}.get(text[i + 1], text[i + 1]))
                    i += 2
                    continue
                if text[i] == "'":
                    self.pos = i + 1
                    return Token("STRING", "".join(buf), start)
                buf.append(text[i])
                i += 1
            raise LexError(f"unterminated string at {start}")

        if c == '"':
            i = start + 1
            buf = []
            while i < n:
                if text[i] == "\\" and i + 1 < n and text[i + 1] in '"\\':
                    buf.append(text[i + 1])
                    i += 2
                    continue
                if text[i] == '"':
                    self.pos = i + 1
                    return Token("IDENT", "".join(buf), start)
                buf.append(text[i])
                i += 1
            raise LexError(f"unterminated quoted identifier at {start}")

        if c.isdigit() or (c == "." and start + 1 < n and text[start + 1].isdigit()):
            return self._scan_number(start)

        if c.isalpha() or c == "_":
            i = start
            while i < n and (text[i].isalnum() or text[i] == "_"):
                i += 1
            word = text[start:i]
            self.pos = i
            lw = word.lower()
            if lw in KEYWORDS:
                return Token("KEYWORD", lw, start)
            return Token("IDENT", word, start)

        for op in ("=~", "!~", "!=", "<>", "<=", ">=", "::"):
            if text.startswith(op, start):
                self.pos = start + len(op)
                return Token("OP", op, start)
        if c in "=<>+-*/%(),;.$":
            self.pos = start + 1
            return Token("OP", c, start)
        raise LexError(f"unexpected character {c!r} at {start}")

    def _scan_number(self, start: int) -> Token:
        text, n = self.text, len(self.text)
        i = start
        while i < n and text[i].isdigit():
            i += 1
        # duration?  e.g. 5m, 1h30m, 90s
        m = _DUR_RE.match(text, start)
        if m and (i >= n or not text[i] in ".eE"):
            total = 0
            j = start
            while True:
                m = _DUR_RE.match(text, j)
                if not m:
                    break
                total += int(m.group(1)) * _DUR_NS[m.group(2)]
                j = m.end()
            # guard: "1m30" without unit is invalid; only accept full matches
            if j > start and (j >= n or not (text[j].isalnum() or text[j] == ".")):
                self.pos = j
                return Token("DURATION", total, start)
        is_float = False
        if i < n and text[i] == ".":
            is_float = True
            i += 1
            while i < n and text[i].isdigit():
                i += 1
        if i < n and text[i] in "eE":
            k = i + 1
            if k < n and text[k] in "+-":
                k += 1
            if k < n and text[k].isdigit():
                is_float = True
                i = k
                while i < n and text[i].isdigit():
                    i += 1
        word = text[start:i]
        self.pos = i
        if is_float:
            return Token("NUMBER", float(word), start)
        return Token("INTEGER", int(word), start)
