"""JSON codec for sql.ast expression trees.

The distributed partial-aggregation protocol ships the coordinator's
already-split WHERE subtrees (tag filter, field filter) to peer data
nodes so they can run the same scan locally (reference: the serialized
plan fragments carried by engine/executor/rpc_transform.go — here the
nodes are plain dataclasses, so a name-tagged dict is the whole codec).

Only types defined in sql.ast are codable: the registry is built from
that module's namespace, so an unexpected object fails loudly instead of
round-tripping as something else.
"""

from __future__ import annotations

import dataclasses

from opengemini_tpu.sql import ast

_REGISTRY = {
    name: obj
    for name, obj in vars(ast).items()
    if dataclasses.is_dataclass(obj) and isinstance(obj, type)
}


def to_json(node):
    """AST node (or list/primitive) -> JSON-able doc."""
    if node is None or isinstance(node, (bool, int, float, str)):
        return node
    if isinstance(node, (list, tuple)):
        return [to_json(v) for v in node]
    cls = type(node)
    if cls.__name__ not in _REGISTRY or _REGISTRY[cls.__name__] is not cls:
        raise TypeError(f"not a sql.ast node: {cls.__name__}")
    doc = {"_n": cls.__name__}
    for f in dataclasses.fields(node):
        doc[f.name] = to_json(getattr(node, f.name))
    return doc


def from_json(doc):
    """Inverse of to_json."""
    if doc is None or isinstance(doc, (bool, int, float, str)):
        return doc
    if isinstance(doc, list):
        return [from_json(v) for v in doc]
    name = doc.get("_n")
    cls = _REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"unknown ast node {name!r}")
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in doc:
            continue
        v = from_json(doc[f.name])
        # JSON flattens tuples to lists; restore tuple-typed fields so
        # reconstructed nodes compare equal to parser output
        if isinstance(v, list) and "tuple" in str(f.type):
            v = tuple(tuple(x) if isinstance(x, list) else x for x in v)
        kwargs[f.name] = v
    return cls(**kwargs)
