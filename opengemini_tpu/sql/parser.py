"""InfluxQL recursive-descent parser.

Covers the surface the engine executes: SELECT (aggregates, selectors,
math expressions, WHERE with time/tag/field conditions, GROUP BY
time(...)/tags/*, FILL, ORDER BY time, LIMIT/OFFSET/SLIMIT/SOFFSET, INTO,
subqueries), SHOW {DATABASES, MEASUREMENTS, TAG KEYS/VALUES, FIELD KEYS,
SERIES, RETENTION POLICIES}, CREATE/DROP DATABASE, CREATE/DROP RETENTION
POLICY, DROP MEASUREMENT.

Reference grammar: lib/util/lifted/influx/influxql (yacc sql.y).
"""

from __future__ import annotations

import re

from opengemini_tpu.sql import ast
from opengemini_tpu.sql.lexer import Lexer, Token


class ParseError(ValueError):
    pass


# operator precedence, low to high (influxql)
_PRECEDENCE = {
    "or": 1,
    "and": 2,
    "=": 3, "!=": 3, "<>": 3, "<": 3, "<=": 3, ">": 3, ">=": 3, "=~": 3, "!~": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5,
}


def _attach_ctes(stmt, ctes: dict) -> None:
    """Make WITH bindings visible to the statement and every nested select
    (subqueries, join sides, IN-subqueries, and the CTE bodies themselves,
    so CTEs can reference other CTEs)."""
    seen: set[int] = set()

    def walk(s):
        if s is None or id(s) in seen:
            return
        seen.add(id(s))
        if isinstance(s, ast.UnionStatement):
            s.ctes = ctes
            for sel in s.selects:
                walk(sel)
            return
        if not isinstance(s, ast.SelectStatement):
            return
        s.ctes = ctes
        for src in s.sources:
            walk_source(src)
        walk_cond(s.condition)

    def walk_source(src):
        if isinstance(src, ast.SubQuery):
            walk(src.stmt)
        elif isinstance(src, ast.JoinSource):
            walk_source(src.left)
            walk_source(src.right)

    def walk_cond(e):
        if e is None:
            return
        if isinstance(e, ast.InSubquery):
            walk(e.stmt)
        elif isinstance(e, ast.BinaryExpr):
            walk_cond(e.lhs)
            walk_cond(e.rhs)
        elif isinstance(e, (ast.ParenExpr,)):
            walk_cond(e.expr)
        elif isinstance(e, ast.UnaryExpr):
            walk_cond(e.expr)

    walk(stmt)
    for body in ctes.values():
        walk(body)


def parse(text: str):
    """Parse one or more ;-separated statements; returns a list."""
    p = Parser(text)
    stmts = []
    while True:
        tok = p.lex.peek()
        if tok.kind == "EOF":
            break
        if tok.kind == "OP" and tok.val == ";":
            p.lex.next()
            continue
        stmts.append(p.parse_statement())
    return stmts


def parse_one(text: str):
    stmts = parse(text)
    if len(stmts) != 1:
        raise ParseError(f"expected exactly one statement, got {len(stmts)}")
    return stmts[0]


class Parser:
    def __init__(self, text: str):
        self.lex = Lexer(text)

    # -- helpers ------------------------------------------------------------

    def _expect_kw(self, *words: str) -> str:
        tok = self.lex.next()
        if tok.kind != "KEYWORD" or tok.val not in words:
            raise ParseError(f"expected {'/'.join(words).upper()}, got {tok.val!r}")
        return tok.val

    def _accept_kw(self, *words: str) -> str | None:
        tok = self.lex.peek()
        if tok.kind == "KEYWORD" and tok.val in words:
            self.lex.next()
            return tok.val
        return None

    def _duration_tok(self, clause: str) -> int:
        t = self.lex.next()
        if t.kind != "DURATION":
            raise ParseError(f"{clause} expects a duration")
        return t.val

    def _duration_list(self, clause: str) -> list[int]:
        out = [self._duration_tok(clause)]
        while self._accept_op(","):
            out.append(self._duration_tok(clause))
        return out

    def _expect_op(self, op: str) -> None:
        tok = self.lex.next()
        if tok.kind != "OP" or tok.val != op:
            raise ParseError(f"expected {op!r}, got {tok.val!r}")

    def _accept_op(self, op: str) -> bool:
        tok = self.lex.peek()
        if tok.kind == "OP" and tok.val == op:
            self.lex.next()
            return True
        return False

    def _accept_word(self, word: str) -> bool:
        """Contextual (non-reserved) keyword: matches an IDENT or KEYWORD
        token case-insensitively. MODEL/ALGORITHM/THRESHOLD stay usable as
        field/tag names this way."""
        tok = self.lex.peek()
        if tok.kind in ("IDENT", "KEYWORD") and tok.val.lower() == word:
            self.lex.next()
            return True
        return False

    def _expect_word(self, word: str) -> None:
        if not self._accept_word(word):
            raise ParseError(f"expected {word.upper()}")

    def _ident(self, allow_string: bool = False) -> str:
        tok = self.lex.next()
        if tok.kind == "IDENT":
            return tok.val
        # unreserved keywords usable as identifiers
        if tok.kind == "KEYWORD":
            return tok.val
        if allow_string and tok.kind == "STRING":
            # openGemini allows single-quoted aliases: AS 'name'
            # (TestServer_Query_Constant_Column)
            return tok.val
        raise ParseError(f"expected identifier, got {tok.val!r}")

    # -- statements ---------------------------------------------------------

    def parse_statement(self):
        # hints recorded before this statement's SELECT belong to nobody
        self.lex.hints.clear()
        tok = self.lex.peek()
        if tok.kind != "KEYWORD":
            raise ParseError(f"expected statement, got {tok.val!r}")
        if tok.val == "select":
            return self.parse_select_or_union()
        if tok.val == "with":
            return self.parse_with()
        if tok.val == "explain":
            self.lex.next()
            analyze = self._accept_kw("analyze") is not None
            return ast.ExplainStatement(self.parse_select(), analyze)
        if tok.val == "show":
            return self.parse_show()
        if tok.val == "create":
            return self.parse_create()
        if tok.val == "drop":
            return self.parse_drop()
        if tok.val == "alter":
            return self.parse_alter()
        if tok.val == "grant":
            return self.parse_grant()
        if tok.val == "revoke":
            return self.parse_revoke()
        if tok.val == "set":
            return self.parse_set_password()
        if tok.val == "delete":
            return self.parse_delete()
        if tok.val == "kill":
            self.lex.next()
            self._expect_kw("query")
            t = self.lex.next()
            if t.kind != "INTEGER":
                raise ParseError("KILL QUERY expects a query id")
            return ast.KillQuery(t.val)
        raise ParseError(f"unsupported statement start: {tok.val!r}")

    def parse_grant(self):
        self._expect_kw("grant")
        priv = self._expect_kw("read", "write", "all")
        self._accept_kw("privileges")
        if self._accept_kw("on"):
            db = self._ident()
            self._expect_kw("to")
            return ast.GrantStatement(priv.upper(), db, self._ident())
        self._expect_kw("to")  # GRANT ALL PRIVILEGES TO u -> admin
        return ast.GrantStatement(priv.upper(), "", self._ident())

    def parse_revoke(self):
        self._expect_kw("revoke")
        priv = self._expect_kw("read", "write", "all")
        self._accept_kw("privileges")
        if self._accept_kw("on"):
            db = self._ident()
            self._expect_kw("from")
            return ast.RevokeStatement(priv.upper(), db, self._ident())
        self._expect_kw("from")
        return ast.RevokeStatement(priv.upper(), "", self._ident())

    def parse_set_password(self):
        self._expect_kw("set")
        self._expect_kw("password")
        self._expect_kw("for")
        name = self._ident()
        self._expect_op("=")
        tok = self.lex.next()
        if tok.kind != "STRING":
            raise ParseError("SET PASSWORD expects a quoted string")
        return ast.SetPassword(name, tok.val)

    def parse_delete(self):
        self._expect_kw("delete")
        stmt = ast.DeleteSeries()
        if self._accept_kw("from"):
            stmt.measurement = self._ident()
        if self._accept_kw("where"):
            stmt.condition = self._parse_expr()
        return stmt

    def parse_with(self):
        """WITH name AS (SELECT ...), ... SELECT ... — common table
        expressions (reference: LogicalCTE, logic_plan.go:3769)."""
        self._expect_kw("with")
        ctes: dict = {}
        while True:
            name = self._ident()
            self._expect_kw("as")
            self._expect_op("(")
            ctes[name] = self.parse_select_or_union()
            self._expect_op(")")
            if not self._accept_op(","):
                break
        tok = self.lex.peek()
        if not (tok.kind == "KEYWORD" and tok.val == "select"):
            raise ParseError("WITH must be followed by SELECT")
        stmt = self.parse_select_or_union()
        _attach_ctes(stmt, ctes)
        return stmt

    def parse_select_or_union(self):
        first = self._parse_union_unit()
        tok = self.lex.peek()
        if not (tok.kind == "KEYWORD" and tok.val == "union"):
            return first
        selects, combines = [first], []
        while self._accept_kw("union"):
            all_ = bool(self._accept_kw("all"))
            by_name = False
            if self._accept_kw("by"):
                self._expect_kw("name")
                by_name = True
            selects.append(self._parse_union_unit())
            combines.append((all_, by_name))
        return ast.UnionStatement(selects, combines)

    def _parse_union_unit(self):
        tok = self.lex.peek()
        if tok.kind == "OP" and tok.val == "(":
            self.lex.next()
            inner = self.parse_select_or_union()
            self._expect_op(")")
            return inner
        return self.parse_select()

    def parse_select(self) -> ast.SelectStatement:
        self._expect_kw("select")
        stmt = ast.SelectStatement()
        stmt.fields = self._parse_fields()
        # hints appear between SELECT and the field list (/*+ ... */);
        # the lexer records them while skipping comments — drain them to
        # THIS statement so multi-statement inputs don't leak hints
        if self.lex.hints:
            stmt.hints = tuple(self.lex.hints)
            self.lex.hints.clear()
        if self._accept_kw("into"):
            stmt.into = self._parse_measurement()
        self._expect_kw("from")
        stmt.sources = self._parse_sources()
        if self._accept_kw("where"):
            stmt.condition = self._parse_expr()
        if self._accept_kw("group"):
            self._expect_kw("by")
            self._parse_group_by(stmt)
        if self._accept_kw("fill"):
            self._parse_fill(stmt)
        if self._accept_kw("order"):
            self._expect_kw("by")
            name = self._ident()
            if name.lower() != "time":
                raise ParseError("only ORDER BY time is supported")
            if self._accept_kw("desc"):
                stmt.ascending = False
            else:
                self._accept_kw("asc")
        stmt.limit = self._parse_int_clause("limit")
        stmt.offset = self._parse_int_clause("offset")
        stmt.slimit = self._parse_int_clause("slimit")
        stmt.soffset = self._parse_int_clause("soffset")
        if self._accept_kw("tz"):
            self._expect_op("(")
            tok = self.lex.next()
            if tok.kind != "STRING":
                raise ParseError("TZ expects a string")
            stmt.tz = tok.val
            self._expect_op(")")
        # hints only count between SELECT and the field list; any recorded
        # later in the statement are discarded so they can't leak into the
        # NEXT statement of a multi-statement input
        self.lex.hints.clear()
        return stmt

    def _parse_int_clause(self, kw: str) -> int:
        if self._accept_kw(kw):
            tok = self.lex.next()
            if tok.kind != "INTEGER":
                raise ParseError(f"{kw.upper()} expects an integer")
            return tok.val
        return 0

    def _parse_fields(self) -> list[ast.Field]:
        fields = []
        while True:
            expr = self._parse_expr()
            alias = ""
            if self._accept_kw("as"):
                alias = self._ident(allow_string=True)
            fields.append(ast.Field(expr, alias))
            if not self._accept_op(","):
                break
        return fields

    def _parse_sources(self) -> list:
        sources = [self._parse_source_join()]
        while self._accept_op(","):
            sources.append(self._parse_source_join())
        return sources

    def _parse_single_source(self):
        import dataclasses

        tok = self.lex.peek(allow_regex=True)
        if tok.kind == "REGEX":
            self.lex.next(allow_regex=True)
            src = ast.Measurement(regex=tok.val)
        elif tok.kind == "OP" and tok.val == "(":
            self.lex.next()
            sub = self.parse_select()
            self._expect_op(")")
            src = ast.SubQuery(sub)
        else:
            src = self._parse_measurement()
        if self._accept_kw("as"):
            src = dataclasses.replace(src, alias=self._ident())
        return src

    def _parse_source_join(self):
        src = self._parse_single_source()
        while True:
            kind = self._accept_join_kind()
            if kind is None:
                return src
            right = self._parse_single_source()
            self._expect_kw("on")
            on = self._parse_expr()
            src = ast.JoinSource(src, right, kind, on)

    def _accept_join_kind(self) -> str | None:
        """JOIN | INNER JOIN | LEFT [OUTER] JOIN | RIGHT [OUTER] JOIN |
        FULL [OUTER] JOIN | OUTER JOIN (reference: influxql.y join rules;
        `outer join` keeps nulls, `full join` zero-fills — observed
        server_test.go join tables)."""
        if self._accept_kw("join"):
            return "inner"
        if self._accept_kw("inner"):
            self._expect_kw("join")
            return "inner"
        for k in ("left", "right"):
            if self._accept_kw(k):
                self._accept_kw("outer")
                self._expect_kw("join")
                return k
        if self._accept_kw("full"):
            self._accept_kw("outer")
            self._expect_kw("join")
            return "full"
        if self._accept_kw("outer"):
            self._expect_kw("join")
            return "outer"
        return None

    def _parse_measurement(self) -> ast.Measurement:
        # [db [.rp]] . name   with each part optionally quoted; or name only
        parts = [self._ident()]
        while self._accept_op("."):
            tok = self.lex.peek(allow_regex=True)
            if tok.kind == "OP" and tok.val == ".":
                parts.append("")  # empty rp: db..measurement
                continue
            if tok.kind == "REGEX":
                self.lex.next(allow_regex=True)
                if len(parts) == 1:
                    return ast.Measurement(database=parts[0], regex=tok.val)
                return ast.Measurement(database=parts[0], rp=parts[1], regex=tok.val)
            parts.append(self._ident())
        if len(parts) == 1:
            return ast.Measurement(name=parts[0])
        if len(parts) == 2:
            return ast.Measurement(database=parts[0], name=parts[1])
        if len(parts) == 3:
            return ast.Measurement(database=parts[0], rp=parts[1], name=parts[2])
        raise ParseError("too many dots in measurement")

    def _parse_group_by(self, stmt: ast.SelectStatement) -> None:
        while True:
            tok = self.lex.peek(allow_regex=True)
            if tok.kind == "OP" and tok.val == "*":
                self.lex.next()
                stmt.group_by_all_tags = True
            elif tok.kind == "IDENT" and tok.val.lower() == "time":
                self.lex.next()
                self._expect_op("(")
                t = self.lex.next()
                if t.kind != "DURATION":
                    raise ParseError("time() expects a duration")
                offset = 0
                if self._accept_op(","):
                    t2 = self.lex.next()
                    sign = 1
                    if t2.kind == "OP" and t2.val == "-":
                        sign = -1
                        t2 = self.lex.next()
                    if t2.kind != "DURATION":
                        raise ParseError("time() offset expects a duration")
                    offset = sign * t2.val
                self._expect_op(")")
                stmt.group_by_time = ast.TimeDimension(t.val, offset)
            elif tok.kind in ("IDENT", "KEYWORD"):
                name = self._ident()
                stmt.group_by_tags.append(name)
            else:
                raise ParseError(f"bad GROUP BY element: {tok.val!r}")
            if not self._accept_op(","):
                break

    def _parse_fill(self, stmt: ast.SelectStatement) -> None:
        self._expect_op("(")
        tok = self.lex.next()
        if tok.kind == "KEYWORD" and tok.val in ("null", "none", "previous", "linear"):
            stmt.fill_option = tok.val
        elif tok.kind in ("NUMBER", "INTEGER"):
            stmt.fill_option = "number"
            stmt.fill_value = float(tok.val)
        elif tok.kind == "OP" and tok.val == "-":
            t2 = self.lex.next()
            if t2.kind not in ("NUMBER", "INTEGER"):
                raise ParseError("bad fill value")
            stmt.fill_option = "number"
            stmt.fill_value = -float(t2.val)
        else:
            raise ParseError(f"bad FILL option: {tok.val!r}")
        self._expect_op(")")

    # -- expressions --------------------------------------------------------

    def _parse_expr(self, min_prec: int = 1):
        lhs = self._parse_unary()
        while True:
            tok = self.lex.peek()
            op = None
            if tok.kind == "OP" and tok.val in _PRECEDENCE:
                op = tok.val
            elif tok.kind == "KEYWORD" and tok.val in ("and", "or"):
                op = tok.val
            if op is None:
                if tok.kind == "KEYWORD" and tok.val == "in" and min_prec <= 3:
                    self.lex.next()
                    lhs = self._parse_in(lhs)
                    continue
                return lhs
            prec = _PRECEDENCE[op]
            if prec < min_prec:
                return lhs
            self.lex.next()
            if op in ("=~", "!~"):
                rtok = self.lex.next(allow_regex=True)
                if rtok.kind != "REGEX":
                    raise ParseError(f"{op} expects a regex")
                rhs = ast.RegexLiteral(rtok.val)
            else:
                rhs = self._parse_expr(prec + 1)
            lhs = ast.BinaryExpr("AND" if op == "and" else ("OR" if op == "or" else op), lhs, rhs)

    def _parse_in(self, lhs):
        """<ref> IN (SELECT ...) or <ref> IN (lit, lit, ...) — the literal
        form desugars to an OR chain of equalities."""
        self._expect_op("(")
        tok = self.lex.peek()
        if tok.kind == "KEYWORD" and tok.val == "select":
            sub = self.parse_select()
            self._expect_op(")")
            return ast.InSubquery(lhs, sub)
        out = None
        while True:
            lit = self._parse_expr()
            eq = ast.BinaryExpr("=", lhs, lit)
            out = eq if out is None else ast.BinaryExpr("OR", out, eq)
            if not self._accept_op(","):
                break
        self._expect_op(")")
        return out

    def _parse_unary(self):
        tok = self.lex.peek()
        if tok.kind == "OP" and tok.val == "-":
            self.lex.next()
            return ast.UnaryExpr("-", self._parse_unary())
        if tok.kind == "OP" and tok.val == "+":
            self.lex.next()
            return self._parse_unary()
        return self._parse_primary()

    def _parse_primary(self):
        tok = self.lex.next()
        if tok.kind == "OP" and tok.val == "(":
            e = self._parse_expr()
            self._expect_op(")")
            return ast.ParenExpr(e)
        if tok.kind == "NUMBER":
            return ast.NumberLiteral(tok.val)
        if tok.kind == "INTEGER":
            return ast.IntegerLiteral(tok.val)
        if tok.kind == "DURATION":
            return ast.DurationLiteral(tok.val)
        if tok.kind == "STRING":
            return ast.StringLiteral(tok.val)
        if tok.kind == "OP" and tok.val == "*":
            return ast.Wildcard()
        if tok.kind == "KEYWORD" and tok.val == "true":
            return ast.BooleanLiteral(True)
        if tok.kind == "KEYWORD" and tok.val == "false":
            return ast.BooleanLiteral(False)
        if tok.kind == "OP" and tok.val == "$":
            # bind parameter — treated as identifier reference
            name = self._ident()
            return ast.VarRef("$" + name)
        if tok.kind in ("IDENT", "KEYWORD"):
            name = tok.val
            # influx alternate DISTINCT syntax (parser.go parseDistinct):
            # `SELECT DISTINCT value`, `COUNT(DISTINCT value)` — a bare
            # identifier right after `distinct` is its argument
            if name.lower() == "distinct":
                nxt = self.lex.peek()
                if nxt.kind == "IDENT":
                    self.lex.next()
                    return ast.Call("distinct", (ast.VarRef(nxt.val),))
            if self._accept_op("("):
                args = []
                if not self._accept_op(")"):
                    while True:
                        targ = self.lex.peek()
                        if targ.kind == "OP" and targ.val == "*":
                            self.lex.next()
                            args.append(ast.Wildcard())
                        else:
                            args.append(self._parse_expr())
                        if not self._accept_op(","):
                            break
                    self._expect_op(")")
                return ast.Call(name.lower(), tuple(args))
            # qualified references: alias.field / alias.* (join sources)
            while self.lex.peek().kind == "OP" and self.lex.peek().val == ".":
                self.lex.next()
                nxt = self.lex.peek()
                if nxt.kind == "OP" and nxt.val == "*":
                    self.lex.next()
                    name += ".*"
                    break
                name += "." + self._ident()
            # double-colon type cast: field::float — parsed, cast ignored
            if self._accept_op("::"):
                self._ident()
            return ast.VarRef(name)
        raise ParseError(f"unexpected token {tok.val!r} in expression")

    # -- SHOW ---------------------------------------------------------------

    def _name_or_regex(self) -> tuple[str, str]:
        """FROM target of a SHOW statement: identifier or /regex/."""
        tok = self.lex.peek(allow_regex=True)
        if tok.kind == "REGEX":
            self.lex.next(allow_regex=True)
            return "", tok.val
        return self._ident(), ""

    def _accept_show_order(self, s) -> None:
        """Trailing `ORDER BY value [ASC|DESC]` on SHOW TAG VALUES
        (reference: influxql.y showTagValuesStatement sort fields)."""
        if not self._accept_kw("order"):
            return
        self._expect_kw("by")
        col = self._ident()
        if col.lower() != "value":
            raise ParseError("SHOW ... ORDER BY supports only `value`")
        if self._accept_kw("desc"):
            s.order_desc = True
        else:
            self._accept_kw("asc")

    def parse_show(self):
        self._expect_kw("show")
        if self._accept_word("models"):
            return ast.ShowModels()
        kw = self.lex.next()
        if kw.kind != "KEYWORD":
            raise ParseError(f"bad SHOW: {kw.val!r}")
        if kw.val == "databases":
            return ast.ShowDatabases()
        if kw.val == "measurements":
            s = ast.ShowMeasurements()
            if self._accept_kw("on"):
                s.database = self._ident()
            if self._accept_kw("with"):
                self._expect_kw("measurement")
                tok = self.lex.next(allow_regex=True)
                if tok.kind == "OP" and tok.val == "=~":
                    rtok = self.lex.next(allow_regex=True)
                    s.regex = rtok.val
                elif tok.kind == "OP" and tok.val == "=":
                    name = self._ident()
                    s.regex = "^" + re.escape(name) + "$"  # exact match
                else:
                    raise ParseError("bad WITH MEASUREMENT")
            return s
        if kw.val == "tag":
            sub = self._expect_kw("keys", "values")
            if sub == "keys":
                s = ast.ShowTagKeys()
                if self._accept_kw("on"):
                    s.database = self._ident()
                if self._accept_kw("from"):
                    s.measurement, s.measurement_regex = self._name_or_regex()
                if self._accept_kw("where"):
                    s.condition = self._parse_expr()
                return s
            s = ast.ShowTagValues()
            if self._accept_kw("on"):
                s.database = self._ident()
            if self._accept_kw("from"):
                s.measurement, s.measurement_regex = self._name_or_regex()
            self._expect_kw("with")
            self._expect_kw("key")
            tok = self.lex.next(allow_regex=True)
            if tok.kind == "OP" and tok.val == "=":
                s.keys = [self._ident()]
            elif tok.kind == "OP" and tok.val == "=~":
                rtok = self.lex.next(allow_regex=True)
                if rtok.kind != "REGEX":
                    raise ParseError("bad WITH KEY regex")
                s.key_regex = rtok.val
            elif tok.kind == "KEYWORD" and tok.val == "in":
                self._expect_op("(")
                s.keys = [self._ident()]
                while self._accept_op(","):
                    s.keys.append(self._ident())
                self._expect_op(")")
            else:
                raise ParseError("bad WITH KEY")
            if self._accept_kw("where"):
                s.condition = self._parse_expr()
            self._accept_show_order(s)
            s.limit = self._parse_int_clause("limit")
            s.offset = self._parse_int_clause("offset")
            return s
        if kw.val == "field":
            self._expect_kw("keys")
            s = ast.ShowFieldKeys()
            if self._accept_kw("on"):
                s.database = self._ident()
            if self._accept_kw("from"):
                s.measurement, s.measurement_regex = self._name_or_regex()
            return s
        if kw.val == "measurement":
            self._expect_kw("cardinality")
            s = ast.ShowMeasurementCardinality()
            if self._accept_kw("on"):
                s.database = self._ident()
            return s
        if kw.val == "series":
            if self._accept_kw("exact"):
                self._expect_kw("cardinality")
                s = ast.ShowSeriesExactCardinality()
                if self._accept_kw("on"):
                    s.database = self._ident()
                if self._accept_kw("from"):
                    s.measurement, s.measurement_regex = self._name_or_regex()
                if self._accept_kw("where"):
                    s.condition = self._parse_expr()
                return s
            if self._accept_kw("cardinality"):
                s = ast.ShowSeriesCardinality()
                if self._accept_kw("on"):
                    s.database = self._ident()
                return s
            s = ast.ShowSeries()
            if self._accept_kw("on"):
                s.database = self._ident()
            if self._accept_kw("from"):
                s.measurement, s.measurement_regex = self._name_or_regex()
            if self._accept_kw("where"):
                s.condition = self._parse_expr()
            return s
        if kw.val == "retention":
            self._expect_kw("policies")
            s = ast.ShowRetentionPolicies()
            if self._accept_kw("on"):
                s.database = self._ident()
            return s
        if kw.val == "continuous":
            self._expect_kw("queries")
            return ast.ShowContinuousQueries()
        if kw.val == "users":
            return ast.ShowUsers()
        if kw.val == "streams":
            return ast.ShowStreams()
        if kw.val == "shards":
            return ast.ShowShards()
        if kw.val == "subscriptions":
            return ast.ShowSubscriptions()
        if kw.val == "queries":
            return ast.ShowQueries()
        if kw.val == "cluster":
            return ast.ShowCluster()
        if kw.val == "downsamples":
            stmt = ast.ShowDownsamples()
            if self._accept_kw("on"):
                stmt.database = self._ident()
            return stmt
        if kw.val == "stats":
            return ast.ShowStats()
        if kw.val == "diagnostics":
            return ast.ShowDiagnostics()
        if kw.val == "grants":
            self._expect_kw("for")
            return ast.ShowGrants(self._ident())
        raise ParseError(f"unsupported SHOW {kw.val!r}")

    # -- CREATE / DROP ------------------------------------------------------

    def parse_create(self):
        self._expect_kw("create")
        if self._accept_word("model"):
            kw = "model"
        else:
            kw = self._expect_kw(
                "database", "retention", "continuous", "user", "stream",
                "subscription", "downsample", "measurement",
            )
        if kw == "model":
            # CREATE MODEL name WITH ALGORITHM 'alg' [THRESHOLD x]
            #   FROM (SELECT field FROM ...): fit + persist (castor)
            stmt = ast.CreateModel(name=self._ident())
            self._expect_kw("with")
            self._expect_word("algorithm")
            tok = self.lex.next()
            if tok.kind != "STRING":
                raise ParseError("ALGORITHM expects a quoted name")
            stmt.algorithm = tok.val
            if self._accept_word("threshold"):
                ntok = self.lex.next()
                if ntok.kind not in ("NUMBER", "INTEGER"):
                    raise ParseError("THRESHOLD expects a number")
                stmt.threshold = float(ntok.val)
            self._expect_kw("from")
            self._expect_op("(")
            start_pos = self.lex.peek().pos
            stmt.select = self.parse_select()
            end_tok = self.lex.peek()
            stmt.select_text = self.lex.text[start_pos:end_tok.pos].strip()
            self._expect_op(")")
            return stmt
        if kw == "measurement":
            # CREATE MEASUREMENT name [WITH ...]: schema pre-declaration.
            # Our engine is schema-on-write, so the statement validates and
            # records nothing; shard-key/index clauses are accepted and
            # ignored (reference: influxql CreateMeasurementStatement).
            stmt = ast.CreateMeasurement(self._ident())
            while self.lex.peek().kind != "EOF" and not (
                self.lex.peek().kind == "OP" and self.lex.peek().val == ";"
            ):
                self.lex.next()
            return stmt
        if kw == "downsample":
            # CREATE DOWNSAMPLE ON [db.]rp (float(mean),integer(sum))
            #   WITH TTL 7d SAMPLEINTERVAL 1h,25h TIMEINTERVAL 5m,30m
            # (reference: influxql CreateDownSampleStatement, ast.go:11262)
            stmt = ast.CreateDownsample()
            if self._accept_kw("on"):
                first = self._ident()
                if self._accept_op("."):
                    stmt.database, stmt.rp = first, self._ident()
                else:
                    stmt.rp = first
            if self._accept_op("("):
                while True:
                    tname = self._ident().lower()
                    self._expect_op("(")
                    stmt.type_aggs[tname] = self._ident().lower()
                    self._expect_op(")")
                    if not self._accept_op(","):
                        break
                self._expect_op(")")
            self._expect_kw("with")
            self._expect_kw("ttl")
            stmt.ttl_ns = self._duration_tok("TTL")
            self._expect_kw("sampleinterval")
            stmt.sample_intervals = self._duration_list("SAMPLEINTERVAL")
            self._expect_kw("timeinterval")
            stmt.time_intervals = self._duration_list("TIMEINTERVAL")
            return stmt
        if kw == "subscription":
            # CREATE SUBSCRIPTION name ON db DESTINATIONS ALL|ANY 'url', ...
            name = self._ident()
            self._expect_kw("on")
            db = self._ident()
            self._expect_kw("destinations")
            mode = self._expect_kw("all", "any").upper()
            dests = []
            while True:
                tok = self.lex.next()
                if tok.kind != "STRING":
                    raise ParseError("destination must be a quoted URL")
                dests.append(tok.val)
                if not self._accept_op(","):
                    break
            return ast.CreateSubscription(name, db, mode, dests)
        if kw == "stream":
            # CREATE STREAM name INTO db..dest ON SELECT ... [DELAY 5s]
            # (reference: openGemini stream DDL, services/stream)
            name = self._ident()
            stmt = ast.CreateStream(name=name)
            self._expect_kw("on")
            start_pos = self.lex.peek().pos
            stmt.select = self.parse_select()
            stmt.select_text = self.lex.text[start_pos : self.lex.pos].strip()
            if self._accept_kw("delay"):
                t = self.lex.next()
                if t.kind != "DURATION":
                    raise ParseError("DELAY expects a duration")
                stmt.delay_ns = t.val
            if stmt.select.into is None:
                raise ParseError("stream requires an INTO clause")
            if stmt.select.group_by_time is None:
                raise ParseError("stream requires GROUP BY time(...)")
            return stmt
        if kw == "database":
            stmt = ast.CreateDatabase(self._ident())
            if self._accept_kw("with"):
                # WITH [DURATION d] [REPLICATION n] [SHARD DURATION d]
                #      [INDEX DURATION d] [NAME rp]  (influxql.y)
                stmt.has_rp_clause = True
                while True:
                    if self._accept_kw("duration"):
                        stmt.duration_ns = self._duration_tok("DURATION")
                    elif self._accept_kw("replication"):
                        t = self.lex.next()
                        if t.kind != "INTEGER":
                            raise ParseError("REPLICATION expects an integer")
                        stmt.replication = t.val
                    elif self._accept_kw("shard"):
                        self._expect_kw("duration")
                        stmt.shard_duration_ns = self._duration_tok("SHARD DURATION")
                    elif self._accept_kw("name"):
                        stmt.rp_name = self._ident()
                    else:
                        tok = self.lex.peek()
                        if tok.kind == "IDENT" and tok.val.lower() == "index":
                            self.lex.next()
                            self._expect_kw("duration")
                            self._duration_tok("INDEX DURATION")  # accepted, n/a
                        else:
                            break
            return stmt
        if kw == "user":
            name = self._ident()
            self._expect_kw("with")
            self._expect_kw("password")
            tok = self.lex.next()
            if tok.kind != "STRING":
                raise ParseError("CREATE USER expects a quoted password")
            stmt = ast.CreateUser(name, tok.val)
            if self._accept_kw("with"):
                self._expect_kw("all")
                self._expect_kw("privileges")
                stmt.admin = True
            return stmt
        if kw == "continuous":
            self._expect_kw("query")
            name = self._ident()
            self._expect_kw("on")
            db = self._ident()
            stmt = ast.CreateContinuousQuery(name=name, database=db)
            if self._accept_kw("resample"):
                while True:
                    if self._accept_kw("every"):
                        t = self.lex.next()
                        if t.kind != "DURATION":
                            raise ParseError("RESAMPLE EVERY expects a duration")
                        stmt.resample_every_ns = t.val
                    elif self._accept_kw("for"):
                        t = self.lex.next()
                        if t.kind != "DURATION":
                            raise ParseError("RESAMPLE FOR expects a duration")
                        stmt.resample_for_ns = t.val
                    else:
                        break
            self._expect_kw("begin")
            start_pos = self.lex.peek().pos
            stmt.select = self.parse_select()
            end_tok = self.lex.peek()
            stmt.select_text = self.lex.text[start_pos : end_tok.pos].strip()
            self._expect_kw("end")
            if stmt.select.into is None:
                raise ParseError("continuous query requires an INTO clause")
            if stmt.select.group_by_time is None:
                raise ParseError("continuous query requires GROUP BY time(...)")
            return stmt
        self._expect_kw("policy")
        name = self._ident()
        self._expect_kw("on")
        db = self._ident()
        self._expect_kw("duration")
        tok = self.lex.next()
        if tok.kind != "DURATION" and not (tok.kind == "INTEGER" and tok.val == 0):
            raise ParseError("DURATION expects a duration")
        duration = tok.val if tok.kind == "DURATION" else 0
        self._expect_kw("replication")
        rtok = self.lex.next()
        if rtok.kind != "INTEGER":
            raise ParseError("REPLICATION expects an integer")
        stmt = ast.CreateRetentionPolicy(
            database=db, name=name, duration_ns=duration, replication=rtok.val
        )
        while True:
            if self._accept_kw("shard"):
                self._expect_kw("duration")
                t = self.lex.next()
                if t.kind != "DURATION":
                    raise ParseError("SHARD DURATION expects a duration")
                stmt.shard_duration_ns = t.val
            elif self._accept_kw("default"):
                stmt.default = True
            else:
                break
        return stmt

    def parse_alter(self):
        """ALTER RETENTION POLICY name ON db with any subset of DURATION /
        REPLICATION / SHARD DURATION / DEFAULT, in any order (influxql
        allows that; reference parser.go:393)."""
        self._expect_kw("alter")
        self._expect_kw("retention")
        self._expect_kw("policy")
        name = self._ident()
        self._expect_kw("on")
        stmt = ast.AlterRetentionPolicy(database=self._ident(), name=name)
        saw = False
        while True:
            if self._accept_kw("duration"):
                tok = self.lex.next()
                if tok.kind == "DURATION":
                    stmt.duration_ns = tok.val
                elif tok.kind == "INTEGER" and tok.val == 0:
                    stmt.duration_ns = 0
                else:
                    raise ParseError("DURATION expects a duration")
            elif self._accept_kw("replication"):
                rtok = self.lex.next()
                if rtok.kind != "INTEGER":
                    raise ParseError("REPLICATION expects an integer")
                stmt.replication = rtok.val
            elif self._accept_kw("shard"):
                self._expect_kw("duration")
                t = self.lex.next()
                if t.kind != "DURATION":
                    raise ParseError("SHARD DURATION expects a duration")
                stmt.shard_duration_ns = t.val
            elif self._accept_kw("default"):
                stmt.default = True
            else:
                break
            saw = True
        if not saw:
            raise ParseError(
                "ALTER RETENTION POLICY requires at least one of "
                "DURATION/REPLICATION/SHARD DURATION/DEFAULT")
        return stmt

    def parse_drop(self):
        self._expect_kw("drop")
        if self._accept_word("model"):
            return ast.DropModel(self._ident())
        kw = self._expect_kw(
            "database", "retention", "measurement", "continuous", "user", "series",
            "stream", "subscription", "downsample", "downsamples",
        )
        if kw in ("downsample", "downsamples"):
            stmt = ast.DropDownsample()
            if self._accept_kw("on"):
                first = self._ident()
                if self._accept_op("."):
                    stmt.database, stmt.rp = first, self._ident()
                elif kw == "downsample":
                    stmt.rp = first
                else:  # DROP DOWNSAMPLES ON db: every rp of the database
                    stmt.database = first
            elif kw == "downsample":
                raise ParseError("DROP DOWNSAMPLE requires ON [db.]rp")
            return stmt
        if kw == "stream":
            return ast.DropStream(self._ident())
        if kw == "subscription":
            name = self._ident()
            self._expect_kw("on")
            return ast.DropSubscription(name, self._ident())
        if kw == "database":
            return ast.DropDatabase(self._ident())
        if kw == "measurement":
            return ast.DropMeasurement(self._ident())
        if kw == "user":
            return ast.DropUser(self._ident())
        if kw == "series":
            stmt = ast.DropSeries()
            if self._accept_kw("from"):
                stmt.measurement = self._ident()
            if self._accept_kw("where"):
                stmt.condition = self._parse_expr()
            return stmt
        if kw == "continuous":
            self._expect_kw("query")
            name = self._ident()
            self._expect_kw("on")
            return ast.DropContinuousQuery(name=name, database=self._ident())
        self._expect_kw("policy")
        name = self._ident()
        self._expect_kw("on")
        return ast.DropRetentionPolicy(database=self._ident(), name=name)
