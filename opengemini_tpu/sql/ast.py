"""InfluxQL AST nodes (naming mirrors the reference's influxql package)."""

from __future__ import annotations

from dataclasses import dataclass, field


# -- expressions -------------------------------------------------------------


@dataclass(frozen=True)
class VarRef:
    name: str

    def __str__(self):
        return f'"{self.name}"'


@dataclass(frozen=True)
class NumberLiteral:
    val: float

    def __str__(self):
        return repr(self.val)


@dataclass(frozen=True)
class IntegerLiteral:
    val: int

    def __str__(self):
        return str(self.val)


@dataclass(frozen=True)
class StringLiteral:
    val: str

    def __str__(self):
        return f"'{self.val}'"


@dataclass(frozen=True)
class BooleanLiteral:
    val: bool

    def __str__(self):
        return "true" if self.val else "false"


@dataclass(frozen=True)
class DurationLiteral:
    val_ns: int

    def __str__(self):
        return f"{self.val_ns}ns"


@dataclass(frozen=True)
class RegexLiteral:
    pattern: str

    def __str__(self):
        return f"/{self.pattern}/"


@dataclass(frozen=True)
class Wildcard:
    kind: str = ""  # "", "field", "tag"

    def __str__(self):
        return "*"


@dataclass(frozen=True)
class Call:
    name: str
    args: tuple

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class BinaryExpr:
    op: str
    lhs: object
    rhs: object

    def __str__(self):
        return f"({self.lhs} {self.op} {self.rhs})"


@dataclass(frozen=True)
class ParenExpr:
    expr: object

    def __str__(self):
        return f"({self.expr})"


@dataclass(frozen=True)
class UnaryExpr:
    op: str
    expr: object

    def __str__(self):
        return f"{self.op}{self.expr}"


# -- statement pieces --------------------------------------------------------


@dataclass(frozen=True)
class Field:
    expr: object
    alias: str = ""


@dataclass(frozen=True)
class Measurement:
    name: str = ""
    regex: str = ""
    database: str = ""
    rp: str = ""
    alias: str = ""


@dataclass(frozen=True)
class SubQuery:
    stmt: "SelectStatement"
    alias: str = ""


@dataclass(frozen=True)
class JoinSource:
    """A JOIN B ON <cond>. kind: inner|left|right|outer|full
    (reference: influxql.Join, LogicalJoin at logic_plan.go:3679)."""

    left: object  # Measurement | SubQuery | JoinSource
    right: object
    kind: str
    on: object  # condition expr


@dataclass(frozen=True)
class InSubquery:
    """<ref> IN (SELECT ...) in a WHERE clause."""

    ref: object  # VarRef
    stmt: "SelectStatement"


@dataclass(frozen=True)
class TimeDimension:
    every_ns: int
    offset_ns: int = 0


@dataclass
class SelectStatement:
    fields: list[Field] = field(default_factory=list)
    sources: list = field(default_factory=list)  # Measurement | SubQuery
    condition: object | None = None
    group_by_tags: list[str] = field(default_factory=list)
    group_by_time: TimeDimension | None = None
    group_by_all_tags: bool = False  # GROUP BY *
    fill_option: str = "null"  # null | none | previous | linear | <number>
    fill_value: float = 0.0
    limit: int = 0
    offset: int = 0
    slimit: int = 0
    soffset: int = 0
    ascending: bool = True
    tz: str = ""
    into: Measurement | None = None
    ctes: dict | None = None  # WITH name AS (...) bindings, shared by ref
    hints: tuple = ()  # optimizer hints: /*+ full_series */ etc.


@dataclass
class UnionStatement:
    """A UNION [ALL] [BY NAME] B [...]; selects with combine flags.
    combines[i] describes how selects[i+1] merges into the running result.
    (reference: influxql union statement, TestServer_Union_Table)."""

    selects: list = field(default_factory=list)
    combines: list = field(default_factory=list)  # (all: bool, by_name: bool)
    ctes: dict | None = None


# -- other statements --------------------------------------------------------


@dataclass
class ShowDatabases:
    pass


@dataclass
class ShowMeasurements:
    database: str = ""
    regex: str = ""


@dataclass
class ShowTagKeys:
    database: str = ""
    measurement: str = ""
    measurement_regex: str = ""
    condition: object | None = None


@dataclass
class ShowTagValues:
    database: str = ""
    measurement: str = ""
    measurement_regex: str = ""
    keys: list[str] = field(default_factory=list)
    key_regex: str = ""
    condition: object | None = None
    order_desc: bool = False
    limit: int = 0
    offset: int = 0


@dataclass
class ShowFieldKeys:
    database: str = ""
    measurement: str = ""
    measurement_regex: str = ""


@dataclass
class ShowSeries:
    database: str = ""
    measurement: str = ""
    measurement_regex: str = ""
    condition: object | None = None


@dataclass
class ShowSeriesExactCardinality:
    database: str = ""
    measurement: str = ""
    measurement_regex: str = ""
    condition: object | None = None


@dataclass
class CreateMeasurement:
    name: str = ""


@dataclass
class ShowRetentionPolicies:
    database: str = ""


@dataclass
class CreateDatabase:
    name: str = ""
    # optional WITH clause: creates/overrides the default retention policy
    rp_name: str = ""
    duration_ns: int = 0
    shard_duration_ns: int | None = None
    replication: int = 1
    has_rp_clause: bool = False


@dataclass
class DropDatabase:
    name: str = ""


@dataclass
class CreateRetentionPolicy:
    database: str = ""
    name: str = ""
    duration_ns: int = 0
    shard_duration_ns: int | None = None
    replication: int = 1
    default: bool = False


@dataclass
class AlterRetentionPolicy:
    """ALTER RETENTION POLICY name ON db [DURATION d] [REPLICATION n]
    [SHARD DURATION d] [DEFAULT] — None fields stay unchanged.
    Reference: lib/util/lifted/influx/influxql/parser.go:393
    (parseAlterRetentionPolicyStatement)."""

    database: str = ""
    name: str = ""
    duration_ns: int | None = None
    shard_duration_ns: int | None = None
    replication: int | None = None
    default: bool = False


@dataclass
class DropRetentionPolicy:
    database: str = ""
    name: str = ""


@dataclass
class DropMeasurement:
    name: str = ""


@dataclass
class CreateModel:
    """CREATE MODEL name WITH ALGORITHM 'mad' [THRESHOLD x] FROM (SELECT ...)
    — the castor fit pipeline (reference services/castor fit flow)."""

    name: str = ""
    algorithm: str = ""
    threshold: object = None
    select: object = None
    select_text: str = ""  # raw training-query text (provenance)


@dataclass
class ShowModels:
    pass


@dataclass
class DropModel:
    name: str = ""


@dataclass
class CreateContinuousQuery:
    name: str = ""
    database: str = ""
    select: "SelectStatement | None" = None
    select_text: str = ""  # raw SELECT source, persisted in meta
    resample_every_ns: int = 0
    resample_for_ns: int = 0


@dataclass
class DropContinuousQuery:
    name: str = ""
    database: str = ""


@dataclass
class ShowContinuousQueries:
    pass


@dataclass
class ExplainStatement:
    select: "SelectStatement | None" = None
    analyze: bool = False


@dataclass
class CreateUser:
    name: str = ""
    password: str = ""
    admin: bool = False


@dataclass
class DropUser:
    name: str = ""


@dataclass
class SetPassword:
    name: str = ""
    password: str = ""


@dataclass
class GrantStatement:
    privilege: str = ""  # READ | WRITE | ALL
    database: str = ""  # empty + ALL -> admin
    user: str = ""


@dataclass
class RevokeStatement:
    privilege: str = ""
    database: str = ""
    user: str = ""


@dataclass
class ShowUsers:
    pass


@dataclass
class ShowGrants:
    user: str = ""


@dataclass
class DeleteSeries:
    measurement: str = ""
    condition: object | None = None


@dataclass
class DropSeries:
    measurement: str = ""
    condition: object | None = None


@dataclass
class ShowMeasurementCardinality:
    database: str = ""


@dataclass
class ShowSeriesCardinality:
    database: str = ""


@dataclass
class CreateStream:
    name: str = ""
    select: "SelectStatement | None" = None
    select_text: str = ""
    delay_ns: int = 0


@dataclass
class DropStream:
    name: str = ""


@dataclass
class ShowStreams:
    pass


@dataclass
class CreateSubscription:
    name: str = ""
    database: str = ""
    mode: str = "ALL"
    destinations: list[str] = field(default_factory=list)


@dataclass
class DropSubscription:
    name: str = ""
    database: str = ""


@dataclass
class ShowSubscriptions:
    pass


@dataclass
class CreateDownsample:
    """Reference: influxql CreateDownSampleStatement (ast.go:11262) —
    SAMPLEINTERVAL[i] is the data-age threshold of level i, TIMEINTERVAL[i]
    the rewritten resolution, Ops the per-type aggregates."""

    database: str = ""
    rp: str = ""
    ttl_ns: int = 0
    sample_intervals: list[int] = field(default_factory=list)
    time_intervals: list[int] = field(default_factory=list)
    type_aggs: dict = field(default_factory=dict)  # "float"/"integer" -> agg


@dataclass
class DropDownsample:
    database: str = ""
    rp: str = ""  # empty: drop on every rp of the database


@dataclass
class ShowDownsamples:
    database: str = ""


@dataclass
class ShowCluster:
    pass


@dataclass
class ShowQueries:
    pass


@dataclass
class KillQuery:
    qid: int = 0


@dataclass
class ShowShards:
    pass


@dataclass
class ShowStats:
    pass


@dataclass
class ShowDiagnostics:
    pass
