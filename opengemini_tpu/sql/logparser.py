"""Log-search query grammar (PPL-style pipe syntax).

Reference: the yacc grammar at lib/util/lifted/logparser/sql.y — bare
terms are full-text matches on the ``content`` field, ``field: value``
is a phrase match, comparison and ``IN`` range operators apply to
numeric fields, adjacency means AND, ``|`` pipe segments AND-combine,
and at most one ``EXTRACT(field: "pattern") AS(aliases...)`` clause
derives new fields (reference Unnest/match_all, sql.y:246-273).

This parser is a hand-written tokenizer + recursive descent (same style
as sql/parser.py) producing a small AST that ``server/logstore.py``
compiles onto the InfluxQL executor: content terms become ``match()``
(text-index-pruned scans), field terms become equality/comparison
predicates, EXTRACT patterns run as Python regexes over result rows with
alias conditions applied post-extract.

Grammar summary::

    query    := segment ('|' segment)*
    segment  := EXTRACT '(' field ':' STRING ')' AS '(' ident (',' ident)* ')'
              | or_expr
    or_expr  := and_expr ('or' and_expr)*
    and_expr := adj_expr ('and' adj_expr)*
    adj_expr := primary primary*              # adjacency = AND
    primary  := '(' or_expr ')' | term
    term     := value                          # full-text on content
              | field ':' value                # phrase match
              | field op value                 # op: < <= > >= !=
              | field IN ('('|'[') value value (')'|']')
    value    := ident | 'string' | "string" | '*'
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field

DEFAULT_FIELD = "content"  # reference logparser DefaultFieldForFullText


class LogParseError(ValueError):
    pass


@dataclass
class Term:
    """One predicate. op: 'match' (phrase/full-text), 'eq', 'neq', 'lt',
    'lte', 'gt', 'gte'. field None = bare full-text term."""

    field: str | None
    op: str
    value: str | float


@dataclass
class Rng:
    field: str
    lo: float
    hi: float
    lo_incl: bool
    hi_incl: bool


@dataclass
class And:
    children: list


@dataclass
class Or:
    children: list


@dataclass
class Extract:
    source: str
    pattern: str
    aliases: list[str]


@dataclass
class MatchAll:
    """`*` — matches every log line."""


@dataclass
class LogQuery:
    cond: object | None = None
    extract: Extract | None = None
    aliases: list[str] = dc_field(default_factory=list)


# -- tokenizer ---------------------------------------------------------------

_TOK_RE = re.compile(
    r"""
    \s*(
        "(?:[^"\\]|\\.)*"         # double-quoted string
      | '(?:[^'\\]|\\.)*'         # single-quoted string
      | <= | >= | != | [:<>(),|\[\]]
      | [^\s:<>()\[\],|]+         # bare word
    )
    """,
    re.VERBOSE,
)

_KEYWORDS = {"and", "or", "in", "as", "extract"}


def _tokenize(text: str) -> list[str]:
    toks, pos = [], 0
    while pos < len(text):
        m = _TOK_RE.match(text, pos)
        if not m:
            if text[pos:].strip():
                raise LogParseError(f"bad token at {text[pos:pos + 20]!r}")
            break
        toks.append(m.group(1))
        pos = m.end()
    return toks


def _unquote(tok: str) -> str:
    if len(tok) >= 2 and tok[0] in "\"'" and tok[-1] == tok[0]:
        body = tok[1:-1]
        # unescape ONLY the quote char and backslash — other escapes
        # (\d, \s, ...) must survive for EXTRACT regex patterns
        return body.replace("\\" + tok[0], tok[0]).replace("\\\\", "\\")
    return tok


def _is_value(tok: str | None) -> bool:
    return tok is not None and tok not in (
        ":", "<", "<=", ">", ">=", "!=", "(", ")", "[", "]", ",", "|",
    ) and tok.lower() not in ("and", "or", "in", "as", "extract")


class _Parser:
    def __init__(self, toks: list[str]):
        self.toks = toks
        self.i = 0

    def peek(self) -> str | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise LogParseError("unexpected end of query")
        self.i += 1
        return tok

    def expect(self, want: str) -> str:
        tok = self.next()
        if tok.lower() != want.lower():
            raise LogParseError(f"expected {want!r}, got {tok!r}")
        return tok

    # -- segments ------------------------------------------------------------

    def parse_query(self) -> LogQuery:
        q = LogQuery()
        conds = []
        while True:
            tok = self.peek()
            if tok is None:
                break
            if tok.lower() == "extract":
                ex = self.parse_extract()
                if q.extract is not None:
                    raise LogParseError("only one EXTRACT clause is supported")
                q.extract = ex
            else:
                conds.append(self.parse_or())
            tok = self.peek()
            if tok == "|":
                self.next()
                continue
            if tok is not None:
                raise LogParseError(f"unexpected {tok!r}")
            break
        conds = [c for c in conds if not isinstance(c, MatchAll)]
        if conds:
            q.cond = conds[0] if len(conds) == 1 else And(conds)
        if q.extract:
            q.aliases = list(q.extract.aliases)
        return q

    def parse_extract(self) -> Extract:
        self.expect("extract")
        self.expect("(")
        src = _unquote(self.next())
        self.expect(":")
        pattern = _unquote(self.next())
        self.expect(")")
        self.expect("as")
        self.expect("(")
        aliases = [_unquote(self.next())]
        while self.peek() == ",":
            self.next()
            aliases.append(_unquote(self.next()))
        self.expect(")")
        try:
            ngroups = re.compile(pattern).groups
        except re.error as e:
            raise LogParseError(f"bad EXTRACT pattern: {e}") from None
        if ngroups != len(aliases):
            raise LogParseError(
                f"EXTRACT pattern has {ngroups} capture group(s) "
                f"but {len(aliases)} alias(es)"
            )
        return Extract(src, pattern, aliases)

    # -- conditions ----------------------------------------------------------

    def parse_or(self):
        left = self.parse_and()
        items = [left]
        while self.peek() is not None and self.peek().lower() == "or":
            self.next()
            items.append(self.parse_and())
        return items[0] if len(items) == 1 else Or(items)

    def parse_and(self):
        items = [self.parse_adj()]
        while self.peek() is not None and self.peek().lower() == "and":
            self.next()
            items.append(self.parse_adj())
        return items[0] if len(items) == 1 else And(items)

    def parse_adj(self):
        items = [self.parse_primary()]
        # adjacency = AND (reference BAND rule)
        while True:
            tok = self.peek()
            if tok == "(" or _is_value(tok):
                items.append(self.parse_primary())
            else:
                break
        items = [c for c in items if not isinstance(c, MatchAll)] or items[:1]
        return items[0] if len(items) == 1 else And(items)

    def parse_primary(self):
        tok = self.peek()
        if tok == "(":
            self.next()
            inner = self.parse_or()
            self.expect(")")
            return inner
        return self.parse_term()

    def parse_term(self):
        raw = self.next()
        first = _unquote(raw)
        nxt = self.peek()
        if nxt == ":":
            self.next()
            val = self.next()
            if val == "*" :
                # field:* — "field present / non-empty" (reference maps to
                # field != '')
                return Term(first, "neq", "")
            return Term(first, "match", _unquote(val))
        if nxt in ("<", "<=", ">", ">=", "!="):
            op = {"<": "lt", "<=": "lte", ">": "gt", ">=": "gte", "!=": "neq"}[
                self.next()
            ]
            return Term(first, op, _number_or_str(_unquote(self.next())))
        if nxt is not None and nxt.lower() == "in":
            self.next()
            open_tok = self.next()
            if open_tok not in ("(", "["):
                raise LogParseError(f"expected ( or [ after IN, got {open_tok!r}")
            lo = _number(_unquote(self.next()))
            hi = _number(_unquote(self.next()))
            close_tok = self.next()
            if close_tok not in (")", "]"):
                raise LogParseError(f"expected ) or ] closing IN, got {close_tok!r}")
            return Rng(first, lo, hi, open_tok == "[", close_tok == "]")
        if raw == "*":
            return MatchAll()
        return Term(None, "match", first)


def _number(s: str) -> float:
    try:
        return float(s)
    except ValueError:
        raise LogParseError(f"expected a number, got {s!r}") from None


def _number_or_str(s: str) -> float | str:
    try:
        return float(s)
    except ValueError:
        return s


def parse_log_query(text: str) -> LogQuery:
    """Parse a pipe-syntax log query. Empty/blank/'*' = match everything."""
    text = text.strip()
    if not text:
        return LogQuery()
    return _Parser(_tokenize(text)).parse_query()


# -- compilation to InfluxQL WHERE -------------------------------------------


def _quote_str(v: str) -> str:
    return "'" + v.replace("\\", "\\\\").replace("'", "\\'") + "'"


def _quote_ident(name: str) -> str:
    return '"' + name.replace('"', '\\"') + '"'


def to_influxql_where(node, aliases: set[str] | None = None) -> str | None:
    """Compile the condition tree to an InfluxQL WHERE fragment for the
    engine scan. Terms that reference EXTRACT aliases cannot run in the
    engine (the field does not exist in storage) — they are skipped here
    and enforced post-extract by ``alias_row_filter``. Returns None when
    nothing remains (scan everything)."""
    aliases = aliases or set()

    def emit(n) -> str | None:
        if isinstance(n, MatchAll):
            return None
        if isinstance(n, Term):
            if n.field in aliases:
                return None
            fld = n.field or DEFAULT_FIELD
            qf = _quote_ident(fld)
            if n.op == "match":
                # match values are always strings (parse_term builds them
                # via _unquote only)
                if not _has_tokens(n.value):
                    # no indexable tokens (punctuation-only): exact compare
                    return f"{qf} = {_quote_str(str(n.value))}"
                if fld == DEFAULT_FIELD:
                    return f"match({qf}, {_quote_str(n.value)})"
                # non-content fields: phrase match degenerates to equality
                # for tags/enum-ish fields, which is the common log shape
                # (level: error, host: web-1); content gets the text index
                return f"{qf} = {_quote_str(n.value)}"
            op = {"eq": "=", "neq": "!=", "lt": "<", "lte": "<=",
                  "gt": ">", "gte": ">="}[n.op]
            if isinstance(n.value, float):
                return f"{qf} {op} {n.value!r}"
            return f"{qf} {op} {_quote_str(n.value)}"
        if isinstance(n, Rng):
            if n.field in aliases:
                return None
            qf = _quote_ident(n.field)
            lo_op = ">=" if n.lo_incl else ">"
            hi_op = "<=" if n.hi_incl else "<"
            return f"({qf} {lo_op} {n.lo!r} AND {qf} {hi_op} {n.hi!r})"
        if isinstance(n, And):
            parts = [p for p in (emit(c) for c in n.children) if p]
            if not parts:
                return None
            return "(" + " AND ".join(parts) + ")"
        if isinstance(n, Or):
            parts = [emit(c) for c in n.children]
            if any(p is None for p in parts):
                # an un-pushable OR arm makes the whole OR un-pushable
                # (the engine would wrongly exclude rows the arm accepts)
                return None
            return "(" + " OR ".join(parts) + ")"
        raise LogParseError(f"unsupported node {n!r}")

    return emit(node) if node is not None else None


_TOKEN_RE = re.compile(r"[A-Za-z0-9_]+")


def _has_tokens(s: str) -> bool:
    return bool(_TOKEN_RE.search(s))


def alias_row_filter(node, aliases: set[str]):
    """Build a row-level predicate fn(rowdict) -> bool enforcing every
    part of the condition tree that references EXTRACT aliases (those are
    skipped by to_influxql_where). Non-alias terms evaluate True here —
    the engine already enforced them — EXCEPT inside OR nodes containing
    alias arms, where the whole OR is evaluated row-level (it was not
    pushed down)."""

    def _term_pred(n, row) -> bool:
        if isinstance(n, MatchAll):
            return True
        if isinstance(n, Term):
            v = row.get(n.field or DEFAULT_FIELD)
            if v is None:
                return False
            if n.op == "match":
                toks = _TOKEN_RE.findall(str(n.value).lower())
                if not toks:
                    return str(v) == str(n.value)
                hay = set(_TOKEN_RE.findall(str(v).lower()))
                return all(t.lower() in hay for t in toks)
            try:
                a = float(v)
                b = float(n.value)
            except (TypeError, ValueError):
                a, b = str(v), str(n.value)
            return {
                "eq": a == b, "neq": a != b, "lt": a < b,
                "lte": a <= b, "gt": a > b, "gte": a >= b,
            }[n.op]
        if isinstance(n, Rng):
            v = row.get(n.field)
            try:
                x = float(v)
            except (TypeError, ValueError):
                return False
            lo_ok = x >= n.lo if n.lo_incl else x > n.lo
            hi_ok = x <= n.hi if n.hi_incl else x < n.hi
            return lo_ok and hi_ok
        if isinstance(n, And):
            return all(_term_pred(c, row) for c in n.children)
        if isinstance(n, Or):
            return any(_term_pred(c, row) for c in n.children)
        return True

    def _needs_row_eval(n) -> bool:
        if isinstance(n, (Term, Rng)):
            f = n.field if isinstance(n, Rng) else (n.field or DEFAULT_FIELD)
            return f in aliases
        if isinstance(n, And):
            return any(_needs_row_eval(c) for c in n.children)
        if isinstance(n, Or):
            return any(_needs_row_eval(c) for c in n.children)
        return False

    def pred(row: dict) -> bool:
        def walk(n) -> bool:
            if isinstance(n, And):
                return all(walk(c) for c in n.children)
            if isinstance(n, Or):
                # ORs with any alias arm were not pushed down: evaluate fully
                if _needs_row_eval(n):
                    return _term_pred(n, row)
                return True
            if isinstance(n, (Term, Rng)):
                if _needs_row_eval(n):
                    return _term_pred(n, row)
                return True
            return True

        return walk(node) if node is not None else True

    return pred


def apply_extract(extract: Extract | None, rows: list[dict]) -> None:
    """Run the EXTRACT regex over each row's source field, attaching alias
    fields in place (reference Unnest/match_all). Non-matching rows keep
    the aliases absent."""
    if extract is None:
        return
    rx = re.compile(extract.pattern)
    for row in rows:
        src = row.get(extract.source)
        if src is None:
            continue
        m = rx.search(str(src))
        if m is None:
            continue
        for alias, val in zip(extract.aliases, m.groups()):
            if val is not None:
                row[alias] = val
