"""Distributed execution over a jax.sharding.Mesh.

The TPU-native replacement for the reference's entire cluster exchange
plane (SURVEY.md §2.8): where openGemini ships serialized plans over spdy
RPC to store nodes and merges chunk streams (LogicalExchange
logic_plan.go:2080, RPCReaderTransform rpc_transform.go:117,
merge_transform), this framework shards the scan batch over mesh axes and
lets XLA insert ICI collectives (psum/pmin/pmax/ppermute) for the merge.

Mesh axes (the parallelism inventory of SURVEY.md §2.10 mapped to axes):
  "shard" — node/PT/shard MPP fan-out -> batch-row sharding (data parallel)
  "time"  — the long-axis (time windows) -> sequence/context parallelism;
            window partials combine with the same collectives, so boundary
            windows need no special ring step for associative aggregates.
"""
