"""Process-wide device-mesh configuration.

When a mesh is set (multi-chip deployment, or the driver's virtual-CPU
dry run), the executor's general aggregate batch path runs as a
shard_map program over it: rows sharded across devices, per-segment
partials merged with XLA collectives (parallel/distributed.py). With no
mesh, everything runs single-device exactly as before.
"""

from __future__ import annotations

_mesh = None


def set_mesh(mesh) -> None:
    global _mesh
    _mesh = mesh


def get_mesh():
    return _mesh
