"""Process-wide device-mesh configuration.

When a mesh is set (server [device] config, or the driver's virtual-CPU
dry run), the executor's aggregate batches go multi-chip: the dense
layouts (models/grid.py, models/ragged.py) shard their independent row
axes over the mesh — GSPMD partitions the dense kernels with zero
collectives (distributed.shard_leading_axis) — the tiled PromQL engine
(ops/prom.py ShardedTiled) shards its series axis the same way, and
AggBatch's general path runs as a shard_map program with collective
merges (distributed.build_batch_agg). With no mesh, everything runs
single-device exactly as before.

Every mesh assignment bumps a process-wide EPOCH. Long-lived caches of
mesh-sharded buffers (a frozen batch's ``mesh_arrays``, the colcache
device tier) key on ``mesh_epoch()`` so a hot config reload that swaps
the mesh mid-process can never serve shards laid out for a dead mesh —
they reshard (donating the stale buffers) or rebuild on next access.
"""

from __future__ import annotations

_mesh = None
_mesh_epoch = 0


def set_mesh(mesh) -> None:
    global _mesh, _mesh_epoch
    if mesh is not _mesh:
        _mesh_epoch += 1
    _mesh = mesh


def get_mesh():
    return _mesh


def mesh_epoch() -> int:
    """Identity token of the CURRENT mesh assignment. Caches holding
    mesh-sharded device buffers must store it and treat a mismatch as
    stale (the mesh object may be dead — its devices reassigned)."""
    return _mesh_epoch
