"""Process-wide device-mesh configuration.

When a mesh is set (server [device] config, or the driver's virtual-CPU
dry run), the executor's aggregate batches go multi-chip: the dense
layouts (models/grid.py, models/ragged.py) shard their independent row
axes over the mesh — GSPMD partitions the dense kernels with zero
collectives (distributed.shard_leading_axis) — and AggBatch's general
path runs as a shard_map program with collective merges
(distributed.build_batch_agg). With no mesh, everything runs
single-device exactly as before.
"""

from __future__ import annotations

_mesh = None


def set_mesh(mesh) -> None:
    global _mesh
    _mesh = mesh


def get_mesh():
    return _mesh
