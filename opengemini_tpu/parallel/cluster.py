"""Multi-node data plane: shard-group placement, write routing, remote
scans.

Reference: coordinator/points_writer.go (MapShards + WritePointRows shard
routing) and the coordinator select exchange (remote readers feeding the
executor). The TPU-first data plane has two tiers: mergeable aggregates
push down — each peer computes dense per-(group, window) partials on its
own slice (query/partials.py) and ships O(groups x windows) arrays — and
everything else falls back to peers SERVING raw columns over
/internal/scan with aggregation on the coordinating node's device.

Placement is rendezvous (HRW) hashing over the registered data nodes:
stable under node add/remove (only ~1/N of groups move), no ring state
to replicate.
"""

from __future__ import annotations

import hashlib
import json
import threading
from opengemini_tpu.utils import lockdep
import urllib.error
import urllib.parse
import urllib.request

from opengemini_tpu.parallel import netfault
from opengemini_tpu.utils import peers

import numpy as np

from opengemini_tpu.index.inverted import SeriesIndex
from opengemini_tpu.record import Column, FieldType, Record
from opengemini_tpu.utils import tracing
from opengemini_tpu.utils.failpoint import inject as _fp
from opengemini_tpu.utils.governor import _env_float, _env_int
from opengemini_tpu.utils.stats import GLOBAL as STATS
from opengemini_tpu.utils.stats import observe_ns as _observe_ns

# a peer's cached health view older than this cannot vote in the quorum
# failure view (its probe loop stalled or has not run yet)
_MAX_VIEW_AGE_S = 90.0


class CircuitOpen(OSError):
    """Fast-failed by the per-node circuit breaker (peer is suspect)."""


class CircuitBreaker:
    """Per-peer consecutive-transport-failure breaker (the gossip
    suspicion state machine's RPC-side analogue): after `threshold`
    consecutive failures the peer is SUSPECT and every RPC to it fails
    fast (CircuitOpen, an OSError — callers classify it exactly like an
    unreachable node) instead of burning a full connect timeout per
    call.  After `cooldown_s` ONE half-open trial RPC is let through:
    success closes the breaker, failure re-opens it for a fresh
    cooldown.

    Pass-through when disabled (threshold <= 0, the default): allow()
    is one comparison, record() a no-op — bit-identical to an
    unwrapped transport (asserted by tests/test_netfault.py)."""

    def __init__(self, threshold: int = 0, cooldown_s: float = 5.0):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._lock = lockdep.Lock()
        # peer key -> [consecutive failures, opened-at walltime,
        #              half-open trial in flight]
        self._peers: dict[str, list] = {}

    def enabled(self) -> bool:
        return self.threshold > 0

    def allow(self, key: str) -> bool:
        """May an RPC to `key` proceed?  False = fail fast (open, and
        either cooling down or a half-open trial is already out)."""
        if self.threshold <= 0:
            return True
        import time as _t

        with self._lock:
            st = self._peers.get(key)
            if st is None or st[0] < self.threshold:
                return True
            if st[2]:
                return False  # one half-open probe at a time
            if _t.perf_counter() - st[1] >= self.cooldown_s:
                st[2] = True  # this caller becomes the trial probe
                return True
            return False

    def record(self, key: str, ok: bool) -> None:
        """Outcome of an RPC to `key`.  An HTTP status error counts as
        OK here — the peer answered, the circuit is about transport
        reachability, not application health."""
        if self.threshold <= 0:
            return
        import time as _t

        with self._lock:
            st = self._peers.setdefault(key, [0, 0.0, False])
            st[2] = False
            if ok:
                st[0] = 0
            else:
                st[0] += 1
                if st[0] >= self.threshold:
                    st[1] = _t.perf_counter()  # (re)open: fresh cooldown

    def state(self, key: str) -> str:
        if self.threshold <= 0:
            return "closed"
        import time as _t

        with self._lock:
            st = self._peers.get(key)
            if st is None or st[0] < self.threshold:
                return "closed"
            if st[2] or _t.perf_counter() - st[1] >= self.cooldown_s:
                return "half-open"
            return "open"

    def is_open(self, key: str) -> bool:
        """Suspect right now (open or mid-trial)?  Feeds node_up() so
        the quorum failure view sees breaker-detected deaths between
        probe ticks."""
        if self.threshold <= 0:
            return False
        with self._lock:
            st = self._peers.get(key)
            return st is not None and st[0] >= self.threshold

    def snapshot(self) -> dict:
        with self._lock:
            peers_ = {k: {"failures": st[0]} for k, st in self._peers.items()
                      if st[0] > 0}
        for k in peers_:
            peers_[k]["state"] = self.state(k)
        return {"threshold": self.threshold, "cooldown_s": self.cooldown_s,
                "peers": peers_}


def owners(nodes: list[str], db: str, rp: str, group_start: int,
           rf: int = 1) -> list[str]:
    """Rendezvous hash: the rf nodes with the highest keyed digests own
    the shard group, primary first (deterministic on every node, no
    coordination; node add/remove moves ~1/N of groups)."""
    scored = []
    for n in sorted(nodes):
        h = hashlib.blake2b(
            f"{n}|{db}|{rp}|{group_start}".encode(), digest_size=8
        ).digest()
        scored.append((int.from_bytes(h, "big"), n))
    scored.sort(reverse=True)
    return [n for _s, n in scored[: max(1, rf)]]


def owner(nodes: list[str], db: str, rp: str, group_start: int) -> str:
    return owners(nodes, db, rp, group_start, 1)[0]


def encode_points(points: list) -> list:
    """Structured points -> JSON-able wire shape (single definition:
    forward_points, hints, and /internal/write all share it)."""
    return [
        [mst, list(map(list, tags)), int(t),
         {name: [ft.name, v] for name, (ft, v) in fields.items()}]
        for mst, tags, t, fields in points
    ]


def decode_points(doc: list) -> list:
    return [
        (mst, tuple(tuple(t) for t in tags), int(t_ns),
         {name: (FieldType[ft], v) for name, (ft, v) in fields.items()})
        for mst, tags, t_ns, fields in doc
    ]


class RemoteScanError(Exception):
    """A data node required for a complete answer was unreachable."""


class PartialsRetry(Exception):
    """A peer died between the metadata round and the partial-aggregate
    round: the caller must rebuild its plan against a fresh live set
    (primary assignment shifted) and retry the whole statement."""


class PartialsUnavailable(Exception):
    """A live peer answered the partial round with an HTTP error (e.g. a
    not-yet-upgraded node 404ing the endpoint): the caller should fall
    back to the raw column exchange instead of retrying or failing."""


class _NodeDown(Exception):
    """Internal: one specific peer failed (drives replica failover)."""

    def __init__(self, nid: str, msg: str):
        super().__init__(msg)
        self.nid = nid


class _RemoteMem:
    """Memtable stand-in: carries the remote data range so the executor's
    data-driven range clamp sees remote extents; never holds rows."""

    def __init__(self, min_time, max_time):
        self.min_time = min_time
        self.max_time = max_time

    def record_for(self, sid):
        return None


class RemoteShard:
    """In-memory shard proxy built from a peer's /internal/scan response.

    Duck-types the slice of the Shard surface the query paths touch
    (index / schema / read_series / measurements / file_chunks / mem);
    the pre-aggregation fast path is disabled for remote data
    (supports_preagg) because chunk metadata never leaves the owner.
    """

    supports_preagg = False

    def __init__(self, mst: str, payload: dict):
        self.index = SeriesIndex()  # in-memory
        self._mst = mst
        self._schema: dict[str, FieldType] = {
            name: FieldType[t] for name, t in payload.get("schema", {}).items()
        }
        self._records: dict[int, Record] = {}
        tmin = tmax = None
        for s in payload.get("series", []):
            tags = tuple((k, v) for k, v in sorted(s["tags"].items()))
            sid = self.index.get_or_create(mst, tags)
            times = np.asarray(s["times"], dtype=np.int64)
            cols = {}
            for name, col in s.get("fields", {}).items():
                ftype = FieldType[col["type"]]
                if ftype == FieldType.STRING:
                    values = np.asarray(col["values"], dtype=object)
                elif ftype == FieldType.INT:
                    values = np.asarray(col["values"], dtype=np.int64)
                elif ftype == FieldType.BOOL:
                    values = np.asarray(col["values"], dtype=bool)
                else:
                    values = np.asarray(col["values"], dtype=np.float64)
                valid = np.asarray(col["valid"], dtype=bool)
                cols[name] = Column(ftype, values, valid)
            self._records[sid] = Record(times, cols)
            if len(times):
                t0, t1 = int(times[0]), int(times[-1])
                tmin = t0 if tmin is None else min(tmin, t0)
                tmax = t1 if tmax is None else max(tmax, t1)
        self.tmin = tmin if tmin is not None else 0
        self.tmax = (tmax + 1) if tmax is not None else 0
        self.mem = _RemoteMem(tmin, tmax)

    def measurements(self):
        return [self._mst] if self._records else []

    def schema(self, mst):
        return dict(self._schema) if mst == self._mst else {}

    def file_chunks(self, mst, sids=None, tmin=None, tmax=None):
        return []

    def read_series(self, mst, sid, tmin=None, tmax=None, fields=None):
        rec = self._records.get(sid)
        if rec is None or mst != self._mst:
            return Record.empty()
        times = rec.times
        lo = 0 if tmin is None else int(np.searchsorted(times, tmin, "left"))
        hi = len(times) if tmax is None else int(np.searchsorted(times, tmax, "left"))
        cols = {
            k: Column(c.ftype, c.values[lo:hi], c.valid[lo:hi])
            for k, c in rec.columns.items()
            if fields is None or k in fields
        }
        return Record(times[lo:hi], cols)


class _MetaIndex(SeriesIndex):
    """Empty index that still reports the remote measurement's tag keys
    (GROUP BY * and WHERE classification need them); every posting lookup
    legitimately returns nothing — remote series are represented by the
    partial arrays, not by local sids."""

    def __init__(self, tag_keys_by_mst: dict):
        super().__init__()
        self._tk = tag_keys_by_mst

    def tag_keys(self, mst):
        return set(self._tk.get(mst, ()))


class MetaShard:
    """Metadata-only stand-in for remote data during aggregate pushdown
    (reference: the shard-mapper prepare round that fetches schema/tag
    metadata before store-side execution). Contributes tag keys, field
    schema, and the data time extent to scan planning; owns no rows."""

    supports_preagg = False

    def __init__(self, mst: str, tag_keys: set, schema: dict,
                 dmin: int, dmax: int):
        self._mst = mst
        self.index = _MetaIndex({mst: set(tag_keys)})
        self._schema = {n: FieldType[t] for n, t in schema.items()}
        self.tmin = dmin
        self.tmax = dmax + 1
        self.mem = _RemoteMem(dmin, dmax)

    def measurements(self):
        return [self._mst]

    def schema(self, mst):
        return dict(self._schema) if mst == self._mst else {}

    def file_chunks(self, mst, sids=None, tmin=None, tmax=None):
        return []

    def read_series(self, mst, sid, tmin=None, tmax=None, fields=None):
        return Record.empty()


def payload_to_points(mst: str, payload: dict) -> list:
    """/internal/scan payload -> engine points (structured write shape)."""
    from opengemini_tpu.record import FieldType

    points = []
    for s in payload.get("series", []):
        tags = tuple(sorted(s["tags"].items()))
        times = s["times"]
        per_field = []
        for name, col in s["fields"].items():
            ftype = FieldType[col["type"]] if isinstance(col["type"], str) \
                else FieldType(col["type"])
            per_field.append((name, ftype, col["values"], col["valid"]))
        for i, t in enumerate(times):
            fields = {}
            for name, ftype, values, valid in per_field:
                if valid[i]:
                    v = values[i]
                    if hasattr(v, "item"):
                        v = v.item()
                    fields[name] = (ftype, v)
            if fields:
                points.append((mst, tags, int(t), fields))
    return points


def serialize_select_meta(engine, db, rp, mst, tmin, tmax,
                          shard_filter=None) -> dict:
    """Peer side of the pushdown metadata round: tag keys, schema, and
    data extent of `mst` within the range on THIS node."""
    shards = engine.shards_for_range(db, rp, tmin, tmax)
    if shard_filter is not None:
        shards = [sh for sh in shards if shard_filter(sh)]
    tag_keys: set[str] = set()
    schema: dict[str, str] = {}
    dmin = dmax = None
    for sh in shards:
        tag_keys.update(sh.index.tag_keys(mst))
        for name, ftype in sh.schema(mst).items():
            schema.setdefault(name, ftype.name)
        for r, c in sh.file_chunks(mst):
            dmin = c.tmin if dmin is None else min(dmin, c.tmin)
            dmax = c.tmax if dmax is None else max(dmax, c.tmax)
        # frozen flush snapshots count as in-memory rows too (lazy
        # import: qhelpers imports this module at load time)
        from opengemini_tpu.query.qhelpers import _shard_mem_time_range

        m_lo, m_hi = _shard_mem_time_range(sh)
        if m_lo is not None:
            dmin = m_lo if dmin is None else min(dmin, m_lo)
            dmax = m_hi if dmax is None else max(dmax, m_hi)
    return {"tag_keys": sorted(tag_keys), "schema": schema,
            "dmin": dmin, "dmax": dmax}


# explicit little-endian wire dtypes: a big-endian peer must not emit
# native-order buffers a little-endian coordinator misreads
_BIN_DTYPES = {"FLOAT": "<f8", "INT": "<i8", "BOOL": "u1"}
_PAD_DTYPES = {"FLOAT": np.float64, "INT": np.int64, "BOOL": bool}


def _collect_series(engine, db, rp, mst, tmin, tmax, shard_filter=None):
    """Shared scan collector: (schema, [{tags, times(ndarray),
    fields: {name: (type, values(ndarray), valid(ndarray))}}]) — column
    arrays stay numpy end to end (no per-value Python boxing); invalid
    slots are zeroed so neither wire format leaks stale memory."""
    shards = engine.shards_for_range(db, rp, tmin, tmax)
    if shard_filter is not None:
        shards = [sh for sh in shards if shard_filter(sh)]
    schema: dict[str, str] = {}
    by_key: dict[tuple, dict] = {}
    rows = 0
    with tracing.current().span("scan") as _sp:
        for sh in sorted(shards, key=lambda s: s.tmin):
            for name, ftype in sh.schema(mst).items():
                schema.setdefault(name, ftype.name)
            for sid in sorted(sh.index.series_ids(mst)):
                rec = sh.read_series(mst, sid, tmin, tmax)
                if len(rec) == 0:
                    continue
                rows += len(rec)
                tags = sh.index.tags_of(sid)
                key = tuple(sorted(tags.items()))
                entry = by_key.setdefault(
                    key, {"tags": dict(tags), "chunks": []}
                )
                entry["chunks"].append(
                    (rec.times,
                     {n: (c.values, c.valid) for n, c in rec.columns.items()})
                )
        _sp.add_field("rows", rows)
        _sp.add_field("series", len(by_key))
    out = []
    for entry in by_key.values():
        chunks = entry["chunks"]
        times = np.concatenate([c[0] for c in chunks])
        fnames = sorted({n for _t, cols in chunks for n in cols})
        fields = {}
        for name in fnames:
            ftype = schema.get(name, "FLOAT")
            pad_dt = _PAD_DTYPES.get(ftype, object)
            parts_v, parts_m = [], []
            for c_times, cols in chunks:
                got = cols.get(name)
                if got is None:  # field absent from this shard's chunk
                    parts_v.append(np.zeros(len(c_times), pad_dt))
                    parts_m.append(np.zeros(len(c_times), bool))
                else:
                    parts_v.append(got[0])
                    parts_m.append(got[1])
            values = np.concatenate(parts_v)
            valid = np.concatenate(parts_m).astype(bool)
            if ftype == "STRING":
                values = np.asarray(
                    [v if b else 0 for v, b in zip(values, valid)], object
                )
            else:
                values = np.where(valid, values, 0)
            fields[name] = (ftype, values, valid)
        out.append({"tags": entry["tags"], "times": times, "fields": fields})
    return schema, out


def serialize_series(engine, db, rp, mst, tmin, tmax,
                     shard_filter=None, trace_ctx=None,
                     node: str = "") -> dict:
    """JSON /internal/scan body (fallback wire format): every series of
    `mst` in range, merged across local shards. `shard_filter(shard)`
    restricts to groups this node is PRIMARY for (rf>1 reads)."""
    t, cm = tracing.start_remote_activated("internal_scan", trace_ctx,
                                           node=node)
    with cm:
        schema, series = _collect_series(engine, db, rp, mst, tmin, tmax,
                                         shard_filter)
    out = []
    for s in series:
        fields = {}
        for name, (ftype, values, valid) in s["fields"].items():
            fields[name] = {"type": ftype, "values": values.tolist(),
                            "valid": valid.tolist()}
        out.append({"tags": s["tags"], "times": s["times"].tolist(),
                    "fields": fields})
    doc = {"schema": schema, "series": out}
    sub = tracing.ship_subtree(t)
    if sub is not None:
        doc["trace"] = sub
    return doc


def serialize_series_binary(engine, db, rp, mst, tmin, tmax,
                            shard_filter=None, trace_ctx=None,
                            node: str = "") -> bytes:
    """Binary /internal/scan payload: [u32 header_len][header JSON]
    [raw column buffers]. Numeric columns and times travel as raw
    LITTLE-ENDIAN ndarrays (memcpy in, frombuffer out) instead of JSON
    number lists — the data-plane wire bottleneck. String columns stay
    JSON inside the header (rare, variable-width)."""
    import struct as _struct

    t, cm = tracing.start_remote_activated("internal_scan", trace_ctx,
                                           node=node)
    with cm:
        schema, series = _collect_series(engine, db, rp, mst, tmin, tmax,
                                         shard_filter)
    buffers: list[bytes] = []
    off = 0

    def _add(arr: np.ndarray, dtype: str) -> list[int]:
        nonlocal off
        b = np.ascontiguousarray(arr.astype(dtype, copy=False)).tobytes()
        buffers.append(b)
        loc = [off, len(b)]
        off += len(b)
        return loc

    header = {"schema": schema, "series": []}
    for s in series:
        entry = {"tags": s["tags"],
                 "times": _add(s["times"], "<i8"), "fields": {}}
        for name, (ftype, values, valid) in s["fields"].items():
            f = {"type": ftype, "valid": _add(valid, "u1")}
            dt = _BIN_DTYPES.get(ftype)
            if dt is not None:
                f["values"] = _add(values, dt)
            else:  # STRING: JSON in the header
                f["strings"] = values.tolist()
            entry["fields"][name] = f
        header["series"].append(entry)
    sub = tracing.ship_subtree(t)
    if sub is not None:
        header["trace"] = sub
    hbuf = json.dumps(header, separators=(",", ":")).encode()
    return _struct.pack("<I", len(hbuf)) + hbuf + b"".join(buffers)


def parse_series_binary(data: bytes) -> dict:
    """Inverse of serialize_series_binary -> the JSON-shaped doc
    RemoteShard consumes (arrays stay numpy, no per-value boxing)."""
    import struct as _struct

    (hlen,) = _struct.unpack_from("<I", data, 0)
    header = json.loads(data[4 : 4 + hlen])
    base = 4 + hlen
    payload = memoryview(data)[base:]

    def _arr(loc, dtype):
        o, ln = loc
        return np.frombuffer(payload[o : o + ln], dtype=dtype)

    out = {"schema": header["schema"], "series": []}
    if "trace" in header:
        out["trace"] = header["trace"]
    for s in header["series"]:
        fields = {}
        for name, f in s["fields"].items():
            t = f["type"]
            valid = _arr(f["valid"], "u1").astype(bool)
            if "values" in f:
                values = _arr(f["values"], _BIN_DTYPES[t])
                if t == "BOOL":
                    values = values.astype(bool)
            else:
                values = f["strings"]
            fields[name] = {"type": t, "values": values, "valid": valid}
        out["series"].append({
            "tags": s["tags"],
            "times": _arr(s["times"], "<i8"),
            "fields": fields,
        })
    return out


class DataRouter:
    """Coordinator-side routing: which node owns a shard group, forward
    writes there, and pull raw columns back for queries."""

    def __init__(self, engine, meta_store, self_id: str, self_addr: str,
                 token: str = "", timeout_s: float = 10.0, rf: int = 1,
                 write_consistency: str = "one"):
        self.engine = engine
        self.meta_store = meta_store
        self.self_id = self_id
        self.self_addr = self_addr
        self.token = token
        self.timeout_s = timeout_s
        # replication factor: every shard group lives on the rf top
        # rendezvous owners; reads are primary-filtered so replicas never
        # double-count (HA ops analogue of the reference's replication)
        self.rf = max(1, rf)
        # rf>1 write acknowledgment level (reference: the consistency-mode
        # choice its HA policies give operators; influx /write
        # consistency=any|one|quorum|all): how many synchronous owner
        # copies each point needs before the write ACKs — the rest ride
        # hinted handoff. "all" is the strict mode: every replica
        # synchronously or the write errors.
        if write_consistency not in ("any", "one", "quorum", "all"):
            raise ValueError(
                f"bad write consistency {write_consistency!r}")
        self.write_consistency = write_consistency
        # strict replication mode (parallel/datarep.DataReplication) when
        # [cluster] ha-policy = "replication"; None = write-available
        self.datarep = None
        # RPC hardening knobs (cluster torture forces all of these):
        # liveness-probe timeout (was hardcoded 2s), transient-retry
        # count + jittered exponential backoff base for data-plane
        # RPCs, and the per-node circuit breaker (off by default —
        # bit-identical pass-through, like the netfault transport)
        self.probe_timeout_s = _env_float("OGT_PROBE_TIMEOUT_S", 2.0)
        self.rpc_retries = max(0, _env_int("OGT_RPC_RETRIES", 0))
        self.rpc_backoff_ms = _env_float("OGT_RPC_BACKOFF_MS", 50.0)
        self.breaker = CircuitBreaker(
            threshold=_env_int("OGT_CB_THRESHOLD", 0),
            cooldown_s=_env_float("OGT_CB_COOLDOWN_S", 5.0))
        self._hint_lock = lockdep.Lock()
        # last health-probe results: node id -> bool (True = reachable)
        self.health: dict[str, bool] = {}
        self.health_ts: float = 0.0  # walltime of the last local probe
        # quorum-aggregated failure view (gossip equivalent): node id ->
        # bool agreed by a majority of live observers; plus first-seen-down
        # walltime for failover grace decisions
        self.shared_health: dict[str, bool] = {}
        self.down_since: dict[str, float] = {}
        # elastic-membership introspection (POST /debug/ctrl?mod=cluster
        # op=decommission|drain): last drain/decommission progress doc
        self.decommission_state: dict = {"phase": "idle"}

    def probe_health(self) -> dict[str, bool]:
        """Ping every registered data node (reference: the cluster
        manager's member health checks); results land in self.health and
        surface through SHOW CLUSTER."""
        def probe(nid, addr):
            if not addr:
                return (nid, False)
            try:
                netfault.check(self.self_id, "/ping", nid, addr)
                with peers.urlopen(peers.url(addr, "/ping"),
                                   timeout=self.probe_timeout_s) as r:
                    ok = r.status in (200, 204)
            except urllib.error.HTTPError:
                # the peer ANSWERED (just not 2xx): unhealthy for the
                # probe, but transport-reachable for the breaker —
                # mirrors _post_raw's taxonomy
                self.breaker.record(addr, True)
                return (nid, False)
            except OSError:
                self.breaker.record(addr, False)
                return (nid, False)
            # a completed probe round-trip is transport evidence either
            # way; probes bypass allow() so they remain the breaker's
            # half-open recovery signal even while it is open
            self.breaker.record(addr, True)
            return (nid, ok)

        import time as _t

        results = dict(self._fanout(probe))
        results[self.self_id] = True
        self.health = results
        self.health_ts = _t.time()  # ogtlint: disable=OGT040 (wall stamp, 0.0 sentinel)
        return results

    def exchange_health(self) -> dict[str, bool]:
        """Shared failure view: probe locally, then exchange views with
        reachable peers and agree by majority (the serf-gossip equivalent,
        reference app/ts-meta/meta/cluster_manager.go:323 checkFailedNode;
        SWIM-style indirect observation without the gossip protocol — the
        membership roster is already raft-replicated, only liveness needs
        agreement).

        A node counts DOWN only when >half of the live observers (self +
        peers whose view we could fetch) say so — one coordinator with a
        broken route cannot wrongly demote a healthy replica, and one
        flaky link cannot flap SHOW CLUSTER for everyone."""
        import time as _t

        local = dict(self.probe_health())
        now = _t.time()  # ogtlint: disable=OGT040 (down_since display stamp)

        def fetch(nid, addr):
            # fetch from EVERY peer, including ones our local probe lost:
            # a reachable view from a "down" peer is the SWIM-style
            # refutation (our route is broken, the node is fine)
            if not addr:
                return None
            req = urllib.request.Request(
                peers.url(addr, "/cluster/health"),
                headers={"X-Ogt-Token": self.token},
            )
            try:
                netfault.check(self.self_id, "/cluster/health", nid, addr)
                with peers.urlopen(req, timeout=self.probe_timeout_s) as r:
                    got = json.loads(r.read())
                view = got.get("health")
                if isinstance(view, dict):
                    age = got.get("age_s")
                    return (nid, {str(k): bool(v) for k, v in view.items()},
                            float(age) if age is not None else None)
            except (OSError, ValueError, TypeError):
                pass
            return None

        views: dict[str, dict[str, bool]] = {}
        for got in self._fanout(fetch):
            if got is None:
                continue
            nid, view, age = got
            # completing an HTTP round-trip to nid IS liveness evidence —
            # it corrects a stale/failed local ping before the tally (the
            # 2-node tie case: our broken route must not outvote the
            # refutation we just received)
            local[nid] = True
            if age is not None and age <= _MAX_VIEW_AGE_S:
                # stale cached views (peer's probe loop stalled or hasn't
                # run yet) don't get to outvote fresh observations; the
                # age is peer-relative so clock skew cannot disqualify it
                views[nid] = view
        views[self.self_id] = local
        agreed: dict[str, bool] = {}
        for nid in self.data_nodes():
            votes = [v[nid] for v in views.values() if nid in v]
            up = sum(votes) * 2 >= len(votes) if votes else local.get(nid, False)
            agreed[nid] = up
        agreed[self.self_id] = True
        for nid, up in agreed.items():
            if up:
                self.down_since.pop(nid, None)
            else:
                self.down_since.setdefault(nid, now)
        # roster changes: drop grace timestamps for decommissioned nodes so
        # a later re-join with the same id starts a fresh grace window
        for nid in list(self.down_since):
            if nid not in agreed:
                del self.down_since[nid]
        self.shared_health = agreed
        return agreed

    def node_up(self, nid: str) -> bool:
        """Best failure signal available: the quorum view when one has
        been computed, else the local probe, defaulting optimistic (an
        unknown node is treated reachable so writes try it and hint on
        failure rather than silently skipping).  An OPEN circuit
        breaker overrides both — K consecutive transport failures is
        fresher evidence than the last probe tick, and gating here
        keeps migrations/anti-entropy off a node the breaker is
        fast-failing anyway."""
        if nid != self.self_id and self.breaker.enabled():
            addr = self.data_nodes().get(nid, "")
            if addr and self.breaker.is_open(addr):
                return False
        if nid in self.shared_health:
            return self.shared_health[nid]
        return self.health.get(nid, True)

    def data_nodes(self) -> dict[str, str]:
        nodes = {
            nid: info.get("addr", "")
            for nid, info in self.meta_store.fsm.nodes.items()
            if info.get("role") == "data"
        }
        nodes.setdefault(self.self_id, self.self_addr)
        return nodes

    def group_owners(self, db: str, rp_name: str, group_start: int,
                     rf: int | None = None,
                     nodes: list[str] | None = None) -> list[str]:
        """Owner list for one shard group: a load-balancer placement
        override from the meta FSM wins (filtered to nodes that still
        exist — a removed node must not black-hole a group), else
        rendezvous. Reference: balance_manager.go moving ownership away
        from hot nodes."""
        ids = sorted(self.data_nodes()) if nodes is None else nodes
        over = getattr(self.meta_store.fsm, "placement", None)
        if over:
            got = over.get(f"{db}|{rp_name}|{group_start}")
            if got:
                live_set = set(ids)
                kept = [n for n in got if n in live_set]
                if kept:
                    return kept[: max(1, rf or self.rf)]
        return owners(ids, db, rp_name, group_start, rf or self.rf)

    def _group_start(self, db: str, rp: str | None, t_ns: int) -> int:
        from opengemini_tpu.storage.engine import DatabaseNotFound, WriteError

        d = self.engine.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        rp_meta = d.rps.get(rp or d.default_rp)
        if rp_meta is None:
            raise WriteError(f"retention policy not found: {db}.{rp}")
        from opengemini_tpu.storage.engine import shard_group_start

        return shard_group_start(t_ns, rp_meta.shard_duration_ns)

    def split_points(self, db: str, rp: str | None, points: list):
        """points -> (local, {node_id: [points]}): every point goes to ALL
        rf owners of its shard group (replicas get their own copy)."""
        from opengemini_tpu.storage.engine import DatabaseNotFound

        d = self.engine.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        rp_name = rp or d.default_rp
        ids = sorted(self.data_nodes())
        local, remote = [], {}
        for p in points:
            dest = self.group_owners(
                db, rp_name, self._group_start(db, rp, p[2]), nodes=ids)
            for o in dest:
                if o == self.self_id:
                    local.append(p)
                else:
                    remote.setdefault(o, []).append(p)
        return local, remote

    def is_primary(self, db: str, rp: str | None, group_start: int,
                   live: list[str]) -> bool:
        """Is this node the group's PRIMARY among `live` owners? Reads
        with rf>1 include each group exactly once via this filter."""
        d = self.engine.databases.get(db)
        rp_name = rp or (d.default_rp if d else "autogen")
        got = self.group_owners(db, rp_name, group_start, rf=1,
                                nodes=sorted(live))
        return got[0] == self.self_id

    def routed_write(self, db: str, rp: str | None, points: list,
                     consistency: str | None = None) -> int:
        """The one coordinator-write sequence (used by HTTP /write and
        SELECT INTO): split by owner, write the local slice structurally,
        forward the rest as STRUCTURED JSON — line-protocol text cannot
        carry arbitrary content (e.g. newlines in string fields).

        rf>1 acknowledges at the configured consistency level (reference:
        the HA-policy consistency choice; influx consistency=any|one|
        quorum|all): each point needs that many SYNCHRONOUS owner copies
        before the write ACKs; copies for unreachable replicas queue as
        hints and replay when the node returns. "all" is the strict mode
        — every replica synchronously or the write errors, nothing is
        hinted. Reads stay correct at every level because failover makes
        a LIVE owner primary — and a live owner holds its synchronous
        copy. rf=1 keeps all-or-error: there is no second copy to lean
        on."""
        level = consistency or self.write_consistency
        if level not in ("any", "one", "quorum", "all"):
            raise ValueError(f"bad consistency level {level!r}")
        if self.datarep is not None:
            # strict replication HA policy: every batch raft-commits on
            # its owner set before the ACK (parallel/datarep.py); the
            # validated consistency param is subsumed by raft majority
            return self.datarep.write(db, rp, points)
        local, remote = self.split_points(db, rp, points)
        n = 0
        if local:
            n += self.engine.write_rows(db, local, rp=rp)
        import urllib.error

        failed: list[tuple[str, list, Exception]] = []
        for node_id, pts in sorted(remote.items()):
            _fp("cluster-write-before-forward")  # per-replica fan-out edge
            try:
                self.forward_points(node_id, db, rp, pts)
                n += len(pts)
            except urllib.error.HTTPError as e:
                if e.code == 400:
                    # the replica deterministically rejected the payload
                    # (unparseable points): hinting would retry forever —
                    # surface it as a hard failure instead
                    raise RemoteScanError(
                        f"replica {node_id!r} rejected write: {e}"
                    ) from e
                # 429 write backpressure, 403 during a cluster-token
                # rotation, 5xx: transient — count the replica as
                # unreachable so the copy rides the hint queue and the
                # consistency-level accounting (same classification as
                # replay_hints), instead of failing the whole batch hard
                failed.append((node_id, pts, e))
            except (OSError, RemoteScanError) as e:
                failed.append((node_id, pts, e))
        if failed:
            if level == "any" and self.rf > 1:
                # influx 'any': the durable local hint queue IS the ack —
                # accept even when no owner was synchronously reachable
                for node_id, pts, _e in failed:
                    _fp("cluster-write-before-hint")
                    self.hint(node_id, db, rp, pts)
                    n += len(pts)
                return n
            need = {
                "one": 1,
                "quorum": self.rf // 2 + 1,
                "all": self.rf,
            }.get(level, 1)
            if self.rf <= 1 or not self._covered(db, rp, points, failed,
                                                 need):
                raise RemoteScanError(
                    f"write failed at consistency={level}: {failed[0][2]}"
                ) from failed[0][2]
            for node_id, pts, _e in failed:
                _fp("cluster-write-before-hint")
                self.hint(node_id, db, rp, pts)
                n += len(pts)
        return n

    def _covered(self, db, rp, points, failed, need: int) -> bool:
        """Did every point land on at least `need` owners? (failed
        targets excluded)."""
        dead = {nid for nid, _pts, _e in failed}
        d = self.engine.databases.get(db)
        rp_name = rp or (d.default_rp if d else "autogen")
        ids = sorted(self.data_nodes())
        for p in points:
            dest = self.group_owners(
                db, rp_name, self._group_start(db, rp, p[2]), nodes=ids)
            if sum(1 for o in dest if o not in dead) < need:
                return False
        return True

    # -- hinted handoff ----------------------------------------------------

    def _hints_dir(self) -> str:
        import os

        d = os.path.join(self.engine.root, "hints")
        os.makedirs(d, exist_ok=True)
        return d

    def hint(self, node_id: str, db: str, rp: str | None,
             points: list) -> None:
        """Queue replica copies for a down node (jsonl per target)."""
        import os

        rec = {"db": db, "rp": rp, "points": encode_points(points)}
        path = os.path.join(self._hints_dir(), f"{node_id}.jsonl")
        _fp("cluster-hint-before-append")  # copy owed, nothing durable yet
        with self._hint_lock:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
        _fp("cluster-hint-after-append")  # hint durable, ack not yet sent

    def pending_hint_nodes(self) -> set[str]:
        """Nodes with queued hints FROM THIS coordinator: excluded from
        this coordinator's read live-set so a just-recovered replica is
        not made primary before its copies arrive (other coordinators'
        hints are invisible here — a documented per-coordinator bound)."""
        import os

        with self._hint_lock:
            try:
                names = os.listdir(self._hints_dir())
            except OSError:
                return set()
        # .inflight files (crash mid-replay) still hold undelivered copies
        # for their node — it must stay excluded until they are merged
        # back (replay_hints start) and delivered
        return {f[:-6] for f in names if f.endswith(".jsonl")} | {
            f[: -len(".jsonl.inflight")]
            for f in names
            if f.endswith(".jsonl.inflight")
        }

    def replay_hints(self) -> int:
        """Deliver queued hints to recovered nodes; returns points
        delivered. Idempotent (timestamp last-write-wins), so a crash
        mid-replay at worst re-delivers. The live file is atomically
        RENAMED before processing: writes arriving mid-replay append to
        a fresh file and can never be lost to a stale-snapshot rewrite."""
        import os
        import urllib.error

        from opengemini_tpu.storage.engine import WriteError

        delivered = 0
        d = self._hints_dir()
        with self._hint_lock:
            # merge back any .inflight orphaned by a crash mid-replay:
            # prepend its lines to the node's live queue (idempotent LWW
            # delivery makes the worst case a re-delivery, never a loss)
            try:
                leftover = sorted(os.listdir(d))
            except OSError:
                return 0
            for fname in leftover:
                if not fname.endswith(".jsonl.inflight"):
                    continue
                infl = os.path.join(d, fname)
                live = os.path.join(d, fname[: -len(".inflight")])
                try:
                    with open(infl, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                extra = b""
                try:
                    with open(live, "rb") as f:
                        extra = f.read()
                except OSError:
                    pass
                tmp = live + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                    if data and not data.endswith(b"\n"):
                        f.write(b"\n")
                    f.write(extra)
                os.replace(tmp, live)
                try:
                    os.remove(infl)
                except OSError:
                    pass
            files = sorted(os.listdir(d))
        for fname in files:
            if not fname.endswith(".jsonl"):
                continue
            node_id = fname[:-6]
            path = os.path.join(d, fname)
            inflight = path + ".inflight"
            with self._hint_lock:
                try:
                    os.replace(path, inflight)  # atomic capture
                except OSError:
                    continue
            try:
                with open(inflight, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                continue
            remaining = list(lines)
            # hints owed to a node that left the roster (decommission)
            # are RE-ROUTED through the normal write path: the rows land
            # on the group's CURRENT owners instead — an acked hinted
            # copy must never vanish just because its target did
            reroute = node_id not in self.data_nodes()
            for i, line in enumerate(lines):
                try:
                    rec = json.loads(line)
                    points = decode_points(rec["points"])
                    _fp("cluster-replay-before-forward")
                    if reroute:
                        self.routed_write(rec["db"], rec.get("rp"),
                                          points, "one")
                    else:
                        self.forward_points(node_id, rec["db"],
                                            rec.get("rp"), points)
                    delivered += len(points)
                    remaining[i] = None
                except urllib.error.HTTPError as e:
                    if e.code == 400:
                        # the replica deterministically rejected the
                        # payload (unparseable points): replaying can
                        # never succeed — poison, drop this hint only
                        remaining[i] = None
                        continue
                    # anything else (429 backpressure, 403 during a
                    # cluster-token rotation, 5xx) can clear: a hinted
                    # copy may BE the ack at consistency=any, so keep
                    # the rest queued and retry next tick rather than
                    # destroy acked durability
                    break
                except (OSError, RemoteScanError):
                    break  # node still down: keep the rest queued
                except WriteError:
                    # re-route target gone too (database/rp dropped since
                    # the hint was queued): deterministically
                    # undeliverable — poison, drop this hint only
                    remaining[i] = None
                except (ValueError, KeyError, TypeError):
                    remaining[i] = None  # corrupt hint: drop it
            kept = [l for l in remaining if l is not None]
            _fp("cluster-replay-before-requeue")  # undelivered tail in
            with self._hint_lock:                 # .inflight only
                if kept:
                    # re-queue BEFORE any hints appended mid-replay: append
                    # the live file (if any) after the kept prefix
                    extra = b""
                    try:
                        with open(path, "rb") as f:
                            extra = f.read()
                    except OSError:
                        pass
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(("\n".join(kept) + "\n").encode())
                        f.write(extra)
                    os.replace(tmp, path)
                try:
                    os.remove(inflight)
                except OSError:
                    pass
        return delivered

    # -- shard migration / rebalancing --------------------------------------

    MIGRATE_CHUNK = 20_000  # points per forwarded batch

    # -- load-aware balancing (reference: balance_manager.go) --------------

    def collect_loads(self, deadline: float | None = None) -> dict[str, dict]:
        """{node_id: disk_usage doc} for every reachable data node
        (local node measured directly).  `deadline` is an absolute
        time.perf_counter() stamp: once past it the poll stops early so
        one slow peer cannot stretch a balance pass past its budget
        (breaker-open peers already fail fast via CircuitOpen)."""
        import time as _time
        out: dict[str, dict] = {}
        for nid, addr in sorted(self.data_nodes().items()):
            if nid == self.self_id:
                out[nid] = self.engine.disk_usage()
                continue
            if deadline is not None and _time.perf_counter() >= deadline:
                break  # budget spent: decide on what we have
            try:
                out[nid] = self._post(addr, "/internal/load", {"db": "_"})
            except (OSError, RemoteScanError, ValueError):
                continue  # unreachable node: skip this round
        return out

    def balance_round(self, min_skew_bytes: int = 64 << 20,
                      skew_ratio: float = 1.3,
                      budget_s: float | None = None) -> dict | None:
        """ONE load-balancing decision (meta-leader only): when the
        heaviest data node carries skew_ratio x the lightest (and at
        least min_skew_bytes more), move the largest group whose PRIMARY
        is the heavy node to the light one via a raft-replicated
        placement override — every node's group_owners() then excludes
        the heavy node and its own migrate_round() streams the data over
        the existing two-phase machinery. Returns the decision or None.
        Reference: app/ts-meta/meta/balance_manager.go /
        master_pt_balance_manager.go (load-reactive PT moves; rendezvous
        handles membership-change moves already)."""
        import time as _time
        deadline = (None if budget_s is None
                    else _time.perf_counter() + budget_s)
        loads = self.collect_loads(deadline)
        if len(loads) < 2:
            return None
        self._prune_placements(loads)
        hot = max(loads, key=lambda n: loads[n].get("total", 0))
        cold = min(loads, key=lambda n: loads[n].get("total", 0))
        hot_b = loads[hot].get("total", 0)
        cold_b = loads[cold].get("total", 0)
        if hot == cold or hot_b < cold_b * skew_ratio + min_skew_bytes:
            return None
        ids = sorted(self.data_nodes())
        over = getattr(self.meta_store.fsm, "placement", {}) or {}
        best = None
        for key, size in sorted(loads[hot].get("groups", {}).items(),
                                key=lambda kv: -kv[1]):
            try:
                db, rp, start = key.split("|")
                start_i = int(start)
            except ValueError:
                continue  # name containing '|' (legacy data): skip
            cur = self.group_owners(db, rp, start_i, nodes=ids)
            if cur and cur[0] == hot and cold not in cur:
                # moving more than half the skew would just flip it
                if size <= (hot_b - cold_b) * 0.75 and size > 0:
                    best = (key, size, cur)
                    break
        if best is None:
            return None
        key, size, cur = best
        if cold != self.self_id and self.breaker.enabled():
            cold_addr = self.data_nodes().get(cold, "")
            if cold_addr and self.breaker.is_open(cold_addr):
                # the chosen destination stopped answering since its load
                # report: proposing the override would strand the group
                # behind migrate_round retries against a dead peer
                return None
        new_owners = self._propose_owner_swap(key, cur, hot, cold)
        if new_owners is None:
            return None
        STATS.incr("cluster", "balance_moves")
        return {"group": key, "bytes": size, "from": hot, "to": cold,
                "owners": new_owners, "prior": over.get(key)}

    def _propose_owner_swap(self, key: str, cur: list[str], out_node: str,
                            dest: str) -> list[str] | None:
        """Raft-propose a placement override moving group `key` off
        `out_node` onto `dest`.  Retained current owners stay FIRST:
        with rf>1 the primary must keep holding the data while migration
        is still in flight, or the primary-filtered reads would
        black-hole the group until out_node's next migrate_round (the
        new owner has no rows yet); with rf=1 the list is just [dest]
        and unfiltered reads keep serving out_node's copy until the move
        commits.  Returns the new owner list, or None (rf already
        saturated by data-holding owners, or the proposal failed)."""
        new_owners = [n for n in cur if n != out_node] + [dest]
        new_owners = new_owners[: max(1, self.rf)]
        if dest not in new_owners:
            return None
        if not self._propose_placement(key, new_owners):
            return None
        return new_owners

    def _prune_placements(self, loads: dict) -> None:
        """Overrides must not pin groups forever: drop entries whose group
        no longer exists on any reporting node (retention expired /
        dropped) or whose owner list already equals plain rendezvous
        (membership change caught up). Without this, the placement map
        grows monotonically and defeats rendezvous self-balancing."""
        over = dict(getattr(self.meta_store.fsm, "placement", {}) or {})
        if not over:
            return
        held: set[str] = set()
        for doc in loads.values():
            held.update(doc.get("groups", {}))
        ids = sorted(self.data_nodes())
        for key, owner_list in over.items():
            try:
                db, rp, start = key.split("|")
                start_i = int(start)
            except ValueError:
                continue
            stale = key not in held or \
                owner_list == owners(ids, db, rp, start_i, self.rf)
            if stale:
                self.meta_store.propose_and_wait(
                    {"op": "drop_placement", "key": key})

    def force_move(self, db: str | None = None,
                   dest: str | None = None) -> dict | None:
        """Deterministic balancer decision for operators and the cluster
        torture harness (POST /debug/ctrl?mod=cluster&op=move): pick the
        largest shard group this node owns and propose a placement
        override moving it to a node outside the current owner set — no
        byte skew required.  `dest` pins the destination (elastic node
        add: rebalance onto a JOINING node instead of whichever node
        sorts first); default is the first non-owner.  Like
        balance_round, retained data-holding owners stay FIRST so rf>1
        primary-filtered reads never black-hole the group mid-move; the
        data streams when this node's next migrate_round observes the
        lost ownership.  Returns the decision or None (nothing movable /
        unknown dest / not the meta leader)."""
        ids = sorted(self.data_nodes())
        if len(ids) < 2:
            return None
        if dest is not None and dest not in ids:
            return None  # unknown destination: not in the roster (yet)
        usage = self.engine.disk_usage()
        best = None
        for key, _size in sorted(usage.get("groups", {}).items(),
                                 key=lambda kv: (-kv[1], kv[0])):
            try:
                gdb, rp, start = key.split("|")
                start_i = int(start)
            except ValueError:
                continue
            if db and gdb != db:
                continue
            cur = self.group_owners(gdb, rp, start_i, nodes=ids)
            if self.self_id not in cur:
                continue
            if dest is not None:
                if dest in cur:
                    continue  # already an owner of this group
                best = (key, cur, dest)
                break
            others = [n for n in ids if n not in cur]
            if not others:
                continue
            best = (key, cur, others[0])
            break
        if best is None:
            return None
        key, cur, dest = best
        new_owners = self._propose_owner_swap(key, cur, self.self_id, dest)
        if new_owners is None:
            return None
        STATS.incr("cluster", "forced_moves")
        return {"group": key, "from": self.self_id, "to": dest,
                "owners": new_owners}

    # -- elastic membership (online node add / decommission) ----------------

    def _leader_post(self, path: str, body: dict) -> bool:
        """Forward a roster/placement mutation to the meta leader over
        HTTP (any node may initiate; raft serializes at the leader).
        True on a 200 — anything else, including no known leader, is a
        clean False for the caller to retry."""
        hint = self.meta_store.leader_hint()
        addr = self.meta_store.meta_members().get(hint or "", "")
        if not addr:
            return False
        doc = dict(body)
        doc["token"] = self.token
        req = urllib.request.Request(
            peers.url(addr, path), data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with peers.urlopen(req, timeout=self.timeout_s) as r:
                return r.status == 200
        except OSError:
            return False

    def _propose_placement(self, key: str, new_owners: list[str]) -> bool:
        """Raft-replicate one placement override, proposing locally on
        the leader and forwarding through /cluster/placement otherwise
        (drain and force_move must work when issued on a follower)."""
        if self.meta_store.is_leader():
            return bool(self.meta_store.propose_and_wait(
                {"op": "set_placement", "key": key, "owners": new_owners}))
        return self._leader_post("/cluster/placement",
                                 {"key": key, "owners": new_owners})

    def add_node(self, node_id: str, addr: str, role: str = "data") -> dict:
        """Operator-driven roster add (POST /debug/ctrl?mod=cluster&
        op=add).  A node started with [meta] join registers itself
        (server/app.py joiner + registrar); this op covers
        pre-registration, repair after a lost registration, and tests.
        Placement onto the new node follows from rendezvous plus
        balancer moves — data streams over the ordinary two-phase
        migration, nothing special-cased for joins."""
        if not node_id or not addr:
            return {"ok": False, "error": "id and addr required"}
        if self.meta_store.is_leader():
            ok = bool(self.meta_store.propose_and_wait(
                {"op": "register_node", "id": node_id, "addr": addr,
                 "role": role}))
        else:
            ok = self._leader_post("/cluster/register", {
                "id": node_id, "addr": addr, "role": role})
        if ok:
            STATS.incr("cluster", "nodes_added")
        return {"ok": ok, "node": node_id, "addr": addr,
                "nodes": sorted(self.data_nodes())}

    def _roster_remove(self, node_id: str) -> bool:
        if self.meta_store.is_leader():
            return bool(self.meta_store.propose_and_wait(
                {"op": "remove_node", "id": node_id}))
        return self._leader_post("/cluster/deregister", {"id": node_id})

    def _conf_remove(self, node_id: str) -> bool:
        """Drop a node from the raft voter set (no-op for data-only
        nodes that never joined the meta group)."""
        if node_id not in self.meta_store.meta_members():
            return True
        if self.meta_store.is_leader():
            return bool(
                self.meta_store.propose_conf_change("remove", node_id))
        return self._leader_post("/raft/remove", {"id": node_id})

    def drain_round(self) -> dict:
        """ONE drain pass moving this node's data off (POST /debug/ctrl?
        mod=cluster&op=drain): (1) raft-replicated placement overrides
        disown every locally-held group — every coordinator re-routes at
        the same committed index, so no peer's migrate_round can push
        the group BACK mid-drain; (2) migrate_round streams the disowned
        groups over the existing durable two-phase machinery; (3)
        replay_hints drains copies owed to peers.  Writes that land here
        mid-pass (the old placement still routed them) are picked up by
        the next pass — they re-route or hint, never vanish.  Returns
        progress counters; repeat until remaining_groups == 0 and
        pending_hints is empty."""
        ids = sorted(self.data_nodes())
        others = [n for n in ids if n != self.self_id]
        doc: dict = {"overridden": 0, "migrated": 0, "hints_replayed": 0,
                     "dead_dests": []}
        if not others:
            doc["error"] = "cannot drain the last data node"
            doc["remaining_groups"] = len(self.engine._shards)
            doc["pending_hints"] = sorted(self.pending_hint_nodes())
            return doc
        for (db, rp, start) in sorted(self.engine._shards):
            cur = self.group_owners(db, rp, start, nodes=ids)
            if self.self_id not in cur:
                continue
            # retained data-holding owners stay FIRST (primary-filtered
            # reads keep a data-holding primary mid-move), then fill
            # from the post-removal rendezvous order so the override
            # equals plain rendezvous once the roster drops this node —
            # _prune_placements then retires it automatically
            post = owners(others, db, rp, start, self.rf)
            new = [n for n in cur if n != self.self_id]
            new += [n for n in post if n not in new]
            new = new[: max(1, min(self.rf, len(others)))]
            if new and self._propose_placement(f"{db}|{rp}|{start}", new):
                doc["overridden"] += 1
        if self.breaker.enabled():
            # migration already fails fast against these (CircuitOpen is
            # never retried in _commit_with_retry); surfacing them lets
            # the decommission loop stop early instead of spinning
            doc["dead_dests"] = sorted(
                n for n, a in self.data_nodes().items()
                if n != self.self_id and a and self.breaker.is_open(a))
        doc["migrated"] = self.migrate_round()
        doc["hints_replayed"] = self.replay_hints()
        doc["remaining_groups"] = len(self.engine._shards)
        doc["pending_hints"] = sorted(self.pending_hint_nodes())
        STATS.incr("cluster", "drain_rounds")
        return doc

    def decommission(self, node: str | None = None,
                     deadline_s: float = 60.0) -> dict:
        """Drain-then-remove (POST /debug/ctrl?mod=cluster&
        op=decommission).  For THIS node: (0) with rf>1, one
        anti-entropy round repairs this node's replicas while it still
        owns them, so the copies it sheds are complete even if a peer
        replica dies later; (1) drain passes under a perf_counter
        deadline — each pass re-disowns any NEW groups full traffic
        created meanwhile; (2) roster removal (remove_node through the
        meta store): every coordinator's rendezvous excludes this node
        from the committed index on, and late hints for it re-route
        through replay_hints; (3) raft conf-change removal when this
        node is a meta voter; (4) final drain passes for in-flight
        writes that raced the removal.  Idempotent — re-issue after a
        mid-drain crash or partition and it resumes from the durable
        state (placements, staging, hint queues).

        With `node` set to a PEER id, this is the forced path for a
        node that died and cannot drain itself: roster + conf-change
        removal only.  Acked rows survive on their rf>1 replicas;
        anti-entropy re-replicates them to the new owners and local
        hints for the dead node re-route on the next replay."""
        import time as _time

        if node and node != self.self_id:
            return self._force_remove(node)
        t0 = _time.perf_counter()
        deadline = t0 + max(1.0, deadline_s)
        state: dict = {"phase": "draining", "node": self.self_id,
                       "rounds": 0, "overridden": 0, "migrated": 0,
                       "done": False}
        self.decommission_state = state
        if self.rf > 1:
            try:
                state["repaired"] = self.anti_entropy_round()
            except Exception as e:  # noqa: BLE001 — repair best-effort
                state["repair_error"] = str(e)
        drained = False
        while _time.perf_counter() < deadline:
            doc = self.drain_round()
            state["rounds"] += 1
            state["overridden"] += doc["overridden"]
            state["migrated"] += doc["migrated"]
            state["last_round"] = doc
            if doc.get("error"):
                state["phase"] = "failed"
                state["error"] = doc["error"]
                return state
            if doc["remaining_groups"] == 0 and not doc["pending_hints"]:
                drained = True
                break
            if doc["dead_dests"] and not doc["migrated"]:
                # every blocked group waits on a breaker-open dest: fail
                # fast rather than pinning the drain until the deadline
                state["phase"] = "blocked"
                state["blocked_on"] = doc["dead_dests"]
                return state
            _time.sleep(
                min(0.2, max(0.0, deadline - _time.perf_counter())))
        if not drained:
            state["phase"] = "deadline"
            return state
        state["phase"] = "removing"
        state["roster_removed"] = self._roster_remove(self.self_id)
        state["conf_removed"] = self._conf_remove(self.self_id)
        # in-flight writes that the pre-removal placement routed here
        # land in fresh local groups: push them off too (bounded — the
        # write sources saw the roster change at the commit index)
        for _ in range(3):
            final = self.drain_round()
            state["final_round"] = final
            if (final["remaining_groups"] == 0
                    and not final["pending_hints"]):
                break
            if _time.perf_counter() >= deadline:
                break
        state["done"] = bool(state["roster_removed"])
        state["phase"] = "done" if state["done"] else "failed"
        state["elapsed_s"] = round(_time.perf_counter() - t0, 3)
        if state["done"]:
            STATS.incr("cluster", "decommissions")
        return state

    def _force_remove(self, node_id: str) -> dict:
        """Forced removal of a peer that cannot drain itself (died
        mid-drain, lost hardware).  See decommission()."""
        state: dict = {"phase": "removing", "node": node_id,
                       "forced": True, "done": False}
        self.decommission_state = state
        known = node_id in self.data_nodes()
        state["roster_removed"] = (
            self._roster_remove(node_id) if known else True)
        state["conf_removed"] = self._conf_remove(node_id)
        state["hints_replayed"] = self.replay_hints()
        state["done"] = bool(state["roster_removed"])
        state["phase"] = "done" if state["done"] else "failed"
        if state["done"]:
            STATS.incr("cluster", "decommissions")
        return state

    def migrate_round(self) -> int:
        """Rebalancing after membership change — TWO-PHASE (reference:
        app/ts-meta/meta/migrate_state_machine.go + engine/engine_ha.go
        PreAssign/Assign/Rollback): for each shard group held locally
        whose rendezvous owners no longer include this node, every
        destination opens an INVISIBLE staging area (begin), rows stream
        into it, and only a commit folds them into the live shard —
        queries never observe a half-migrated copy. Any failure aborts
        the staging best-effort; a pusher that dies mid-stream leaves
        staging dirs that the destinations TTL-expire (the rollback that
        survives coordinator death). The local copy drops only after
        every destination commits. Returns groups migrated."""
        import uuid

        ids = sorted(self.data_nodes())
        moved = 0
        for (db, rp, start), sh in sorted(self.engine._shards.items()):
            dest = self.group_owners(db, rp, start, nodes=ids)
            if self.self_id in dest:
                continue
            if not all(self.node_up(peer) for peer in dest):
                continue  # owner down (quorum view): retry when healed
            mig_id = f"mig-{self.self_id}-{uuid.uuid4().hex[:12]}"
            begun: list[str] = []
            try:
                for peer in dest:
                    _fp("cluster-migrate-before-begin")
                    self._migrate_rpc(peer, {
                        "phase": "begin", "mig_id": mig_id, "db": db,
                        "rp": rp, "group_start": start})
                    begun.append(peer)
                for peer in dest:
                    self._push_shard(peer, db, rp, sh, mig_id)
                for peer in dest:
                    _fp("cluster-migrate-before-commit")
                    self._commit_with_retry(peer, mig_id, db)
                _fp("cluster-migrate-after-commit")  # all acks in, local
            except Exception:                        # copy still present
                # Rollback: ANY failure — transport, a peer's rejection,
                # or a payload encode/decode fault (ValueError/KeyError,
                # which previously ESCAPED this handler and left staging
                # un-rolled-back until TTL) — aborts every begun peer
                # best-effort; TTL expiry covers peers the abort cannot
                # reach.  The local copy stays, so nothing is lost.
                STATS.incr("cluster", "migrate_aborts")
                for peer in begun:
                    try:
                        _fp("cluster-migrate-before-abort")
                        self._migrate_rpc(peer, {
                            "phase": "abort", "mig_id": mig_id, "db": db})
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                continue
            # drop-local ONLY here, after every destination acked its
            # commit — a kill at the site above leaves the group held by
            # a non-owner, which the next migrate_round re-pushes (LWW
            # fold into the already-live rows: convergent, no dupes)
            _fp("cluster-migrate-before-drop-local")
            self.engine.drop_shard(db, rp, start)
            moved += 1
            STATS.incr("cluster", "groups_migrated")
        return moved

    COMMIT_RETRIES = 3

    def _commit_with_retry(self, peer: str, mig_id: str, db: str) -> None:
        """Commit with bounded retries: the server side is idempotent (a
        committed-marker answers a re-commit with ok), so a commit whose
        ACK was lost in transit is safely retried here instead of
        aborting — and then re-streaming — a fully staged migration."""
        import random as _random
        import time as _time

        last: Exception | None = None
        for i in range(self.COMMIT_RETRIES):
            try:
                self._migrate_rpc(peer, {
                    "phase": "commit", "mig_id": mig_id, "db": db})
                return
            except urllib.error.HTTPError:
                raise  # the peer ANSWERED (e.g. 400 unknown migration):
                       # its classification is final, never retried
            except CircuitOpen:
                raise  # fail fast means fail fast: backing off against
                       # a peer the breaker already classified dead
                       # would just burn the migrate round's time
            except (OSError, RemoteScanError) as e:
                last = e
                if i + 1 < self.COMMIT_RETRIES:
                    base = max(self.rpc_backoff_ms, 20.0) / 1000.0
                    _time.sleep(min(base * (2 ** i) * (1 + _random.random()),
                                    2.0))
        raise last

    def _migrate_rpc(self, peer: str, body: dict) -> None:
        addr = self.data_nodes().get(peer, "")
        if not addr:
            raise RemoteScanError(f"no address for data node {peer!r}")
        try:
            # commit folds the whole staged group into the live shard
            # synchronously — far longer than a data-plane RPC
            timeout = 300.0 if body.get("phase") == "commit" else None
            got = self._post(addr, "/internal/migrate", body,
                             timeout=timeout)
        except OSError as e:
            raise RemoteScanError(
                f"data node {peer!r} ({addr}) migrate "
                f"{body.get('phase')} failed: {e}") from e
        if not got.get("ok"):
            raise RemoteScanError(
                f"data node {peer!r} rejected migrate {body.get('phase')}")

    def _push_shard(self, peer: str, db: str, rp, sh, mig_id: str) -> None:
        """Stream every row of one local shard into `peer`'s staging area
        in bounded structured-write batches (extraction shared with
        engine.commit_staging via iter_structured_batches)."""
        from opengemini_tpu.storage.shard import iter_structured_batches

        for batch in iter_structured_batches(sh, self.MIGRATE_CHUNK):
            _fp("cluster-migrate-before-push")
            self._migrate_rpc(peer, {
                "phase": "write", "mig_id": mig_id, "db": db,
                "points": encode_points(batch)})

    # -- anti-entropy (rf>1 replica convergence) ----------------------------

    def anti_entropy_round(self) -> int:
        """One digest-exchange round (reference raft-replicated shards
        keep replicas consistent by construction,
        engine/engine_replication.go; the rendezvous+LWW data plane needs
        this read-repair instead): for every shard group this node owns,
        compare per-measurement content digests with the other live
        owners and pull any diverged measurement's rows back for LWW
        merge.  Symmetric rounds on each owner converge both ways.
        Returns the number of repaired (group, measurement) pairs."""
        if self.rf <= 1:
            return 0
        import os as _os

        from opengemini_tpu.record import FieldType

        ids = sorted(self.data_nodes())
        nodes = self.data_nodes()
        pending = self.pending_hint_nodes()
        repaired = 0

        # candidate groups: everything held locally PLUS groups the other
        # owners hold that we should — a replica that lost its whole
        # shard directory must still notice and re-pull
        candidates: dict[tuple, object] = {
            key: sh for key, sh in self.engine._shards.items()
        }
        peer_addrs: dict[str, str] = {}
        for peer in ids:
            if peer == self.self_id:
                continue
            if peer in pending or not self.node_up(peer):
                continue  # hints still owed / peer down: not divergence
            addr = nodes.get(peer, "")
            if not addr:
                continue
            peer_addrs[peer] = addr
            try:
                got = self._post(addr, "/internal/groups", {"db": "_"})
            except (OSError, ValueError):
                peer_addrs.pop(peer, None)
                continue
            for db, rp, start in got.get("groups", []):
                candidates.setdefault((db, rp, int(start)), None)

        for (db, rp, start), sh in sorted(candidates.items()):
            dest = self.group_owners(db, rp, start, nodes=ids)
            if self.self_id not in dest:
                continue
            local_digest = sh.content_digest() if sh is not None else {}
            if sh is not None:
                tmin, tmax = sh.tmin, sh.tmax
            else:
                d = self.engine.databases.get(db)
                rp_meta = d.rps.get(rp) if d else None
                dur = rp_meta.shard_duration_ns if rp_meta else 0
                tmin, tmax = start, start + (dur or 2**62 - start)
            for peer in dest:
                if peer == self.self_id or peer not in peer_addrs:
                    continue
                addr = peer_addrs[peer]
                try:
                    _fp("cluster-antientropy-before-digest")
                    got = self._post(addr, "/internal/digest", {
                        "db": db, "rp": rp, "group_start": start,
                    })
                except (OSError, ValueError):
                    continue
                theirs = got.get("digest", {})
                for mst in sorted(set(theirs) | set(local_digest)):
                    if theirs.get(mst) == local_digest.get(mst):
                        continue
                    if mst not in theirs:
                        continue  # peer missing data: ITS round pulls ours
                    try:
                        _fp("cluster-antientropy-before-pull")
                        n = self._pull_measurement(
                            addr, db, rp, mst, tmin, tmax)
                    except (OSError, RemoteScanError, ValueError):
                        continue
                    if n:
                        repaired += 1
                        STATS.incr("cluster", "anti_entropy_repairs")
        return repaired

    def _pull_measurement(self, addr: str, db: str, rp, mst: str,
                          tmin: int, tmax: int) -> int:
        """Fetch a peer's rows for one (group, measurement) and LWW-merge
        them locally via the structured write path."""
        payload = self._post_scan(addr, {
            "db": db, "rp": rp, "mst": mst, "tmin": tmin, "tmax": tmax,
            "fmt": "bin",
        })
        points = payload_to_points(mst, payload)
        if not points:
            return 0
        _fp("cluster-antientropy-before-merge")  # pulled, not yet merged
        return self.engine.write_rows(db, points, rp=rp)

    def forward_points(self, node_id: str, db: str, rp: str | None,
                       points: list) -> None:
        """POST structured points to the owner's /internal/write."""
        addr = self.data_nodes().get(node_id, "")
        if not addr:
            raise RemoteScanError(f"no address for data node {node_id!r}")
        body = {"db": db, "rp": rp, "points": encode_points(points)}
        tctx = tracing.current_ctx()
        if tctx is not None:
            body["trace"] = tctx
        try:
            out = self._post(addr, "/internal/write", body)
            if isinstance(out, dict):
                # replica applied under a child span and shipped it back
                tracing.current().graft(out.get("trace"))
        except urllib.error.HTTPError:
            # status errors carry the replica's classification (429 =
            # transient write backpressure vs 4xx = hard rejection);
            # HTTPError is an OSError, so without this re-raise the
            # clause below would flatten both into RemoteScanError and
            # callers could not tell them apart
            raise
        except OSError as e:
            raise RemoteScanError(
                f"data node {node_id!r} ({addr}) write failed: {e}"
            ) from e

    def forward_write(self, node_id: str, db: str, rp: str | None,
                      lines: str) -> None:
        from urllib.parse import quote

        addr = self.data_nodes().get(node_id, "")
        if not addr:
            raise RemoteScanError(f"no address for data node {node_id!r}")
        url = peers.url(addr, f"/write?db={quote(db, safe='')}")
        if rp:
            url += f"&rp={quote(rp, safe='')}"
        netfault.check(self.self_id, "/write", node_id, addr)
        req = urllib.request.Request(
            url, data=lines.encode("utf-8"),
            headers={"X-Ogt-Internal": "1", "X-Ogt-Token": self.token},
            method="POST",
        )
        peers.urlopen(req, timeout=self.timeout_s).read()

    def _post_raw(self, addr: str, path: str, body: dict,
                  timeout: float | None = None):
        """One internal-POST implementation (token injection, per-RPC
        deadline, netfault hook, circuit breaker, transient retries);
        returns (bytes, content_type).

        Retry policy (OGT_RPC_RETRIES, default 0 = single attempt):
        only transport-level OSErrors retry, with jittered exponential
        backoff — every /internal/* RPC is idempotent (LWW structured
        writes, marker-idempotent migration commits, read-only scans),
        so a retried request can duplicate effort but never data.  An
        HTTPError is the peer ANSWERING and is never retried here: the
        status carries the peer's classification and the caller's
        error taxonomy must see it unchanged."""
        import random as _random
        import time as _time

        data = json.dumps(dict(body, token=self.token)).encode("utf-8")
        attempts = self.rpc_retries + 1
        for i in range(attempts):
            if not self.breaker.allow(addr):
                # fail fast means fail fast: never retried, not a new
                # failure observation
                raise CircuitOpen(
                    f"circuit open to {addr} "
                    f"({self.breaker.threshold} consecutive failures)")
            req = urllib.request.Request(
                peers.url(addr, path), data=data,
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = _time.perf_counter_ns()
            try:
                # inside the try: an injected drop/delay/error behaves
                # exactly like the real transport fault it simulates
                # (drops retry, injected statuses classify as answers)
                netfault.check(self.self_id, path, addr)
                with peers.urlopen(req, timeout=timeout or self.timeout_s) as r:
                    out = r.read(), r.headers.get("Content-Type", "")
            except urllib.error.HTTPError:
                self.breaker.record(addr, True)  # the peer answered
                _observe_ns("rpc_seconds",
                            _time.perf_counter_ns() - t0,
                            peer=addr, path=path)
                raise
            except OSError:
                self.breaker.record(addr, False)
                if i + 1 >= attempts:
                    raise
                base = self.rpc_backoff_ms / 1000.0
                _time.sleep(min(base * (2 ** i) * (1 + _random.random()),
                                2.0))
                continue
            self.breaker.record(addr, True)
            # per-(peer, path) latency: the straggler-attribution gauge —
            # which node ate the time when a fan-out query is slow
            _observe_ns("rpc_seconds", _time.perf_counter_ns() - t0,
                        peer=addr, path=path)
            return out

    def _post(self, addr: str, path: str, body: dict,
              timeout: float | None = None) -> dict:
        data, _ct = self._post_raw(addr, path, body, timeout=timeout)
        return json.loads(data)

    def _post_scan(self, addr: str, body: dict) -> dict:
        """Scan post accepting the binary wire format (raw ndarray
        buffers) with JSON fallback for peers that ignore fmt."""
        data, ctype = self._post_raw(addr, "/internal/scan", body)
        if ctype.startswith("application/octet-stream"):
            return parse_series_binary(data)
        return json.loads(data)

    def scan_shards(self, db: str, rp: str | None, mst: str,
                    tmin: int, tmax: int):
        """(remote shards, live node set). With rf>1 each group is served
        exactly once by its primary AMONG THE LIVE SET: dead peers are
        dropped from `live` (all at once — one retry round) and the
        group's next owner becomes primary (replica failover). At most
        rf-1 dead nodes are tolerable: every group has rf distinct
        owners, so with >= rf nodes down SOME group may have lost every
        copy — the query fails rather than answer partially. rf=1
        tolerates none for the same reason."""
        live = self._initial_live()
        dropped: list[str] = []
        while True:
            payloads, dead = self._fetch_once(db, rp, mst, tmin, tmax, live)
            if not dead:
                cur = tracing.current()
                for p in payloads:
                    # stitch each peer's scan subtree (shipped in the
                    # response header) under the span issuing this round
                    cur.graft(p.pop("trace", None) if isinstance(p, dict)
                              else None)
                out = [RemoteShard(mst, p) for p in payloads
                       if p.get("series")]
                return out, live
            _fp("cluster-scan-failover")  # dead peers leave the live set
            dropped.extend(sorted(dead))
            if len(dropped) >= self.rf:
                raise RemoteScanError(
                    f"{len(dropped)} data nodes unreachable "
                    f"({', '.join(dropped)}) with replication factor "
                    f"{self.rf}: some shard groups may have no live copy"
                )
            live = [n for n in live if n not in dead]

    def _initial_live(self) -> list[str]:
        """Starting live set for a read fan-out: every registered data
        node, minus (rf>1) recovered replicas still missing OUR hinted
        copies — they must not serve as primary until the queue drains."""
        live = sorted(self.data_nodes())
        if self.rf > 1:
            pending = self.pending_hint_nodes() - {self.self_id}
            if pending and len(live) - len(pending & set(live)) >= 1:
                live = [n for n in live if n not in pending]
        return live

    def has_peers(self) -> bool:
        return any(nid != self.self_id for nid in self.data_nodes())

    def select_meta(self, db: str, rp: str | None, mst: str,
                    tmin: int, tmax: int):
        """Pushdown metadata round: merged (tag_keys, schema, dmin, dmax)
        across peers, with the same replica-failover semantics as
        scan_shards. Returns (merged doc | None, live)."""
        STATS.incr("cluster", "meta_fanouts")
        live = self._initial_live()
        dropped: list[str] = []
        while True:
            def fetch(nid, addr):
                if nid not in live:
                    return {}
                if not addr:
                    return _NodeDown(nid, f"no address for data node {nid!r}")
                try:
                    return self._post(addr, "/internal/select_meta", {
                        "db": db, "rp": rp, "mst": mst,
                        "tmin": tmin, "tmax": tmax,
                        "live": live, "rf": self.rf,
                    })
                except urllib.error.HTTPError as e:
                    # the peer is ALIVE but rejected the round (governor
                    # shed / rolling upgrade): not a node-down — treating
                    # it as dead would fail the query "unreachable" at
                    # rf=1 and evict a merely-overloaded replica from
                    # the live set at rf>1.  PartialsUnavailable makes
                    # the executor fall back to the raw column exchange.
                    return PartialsUnavailable(
                        f"data node {nid!r} ({addr}) cannot serve "
                        f"metadata: {e}")
                except OSError as e:
                    return _NodeDown(
                        nid, f"data node {nid!r} ({addr}) unreachable: {e}")

            metas, dead = [], set()
            for got in self._fanout(fetch):
                if isinstance(got, PartialsUnavailable):
                    raise got
                if isinstance(got, _NodeDown):
                    dead.add(got.nid)
                elif got:
                    metas.append(got)
            if not dead:
                break
            _fp("cluster-scan-failover")
            dropped.extend(sorted(dead))
            if len(dropped) >= self.rf:
                raise RemoteScanError(
                    f"{len(dropped)} data nodes unreachable "
                    f"({', '.join(dropped)}) with replication factor "
                    f"{self.rf}: some shard groups may have no live copy")
            live = [n for n in live if n not in dead]
        tag_keys: set[str] = set()
        schema: dict[str, str] = {}
        dmin = dmax = None
        for m in metas:
            tag_keys.update(m.get("tag_keys", []))
            for n, t in m.get("schema", {}).items():
                schema.setdefault(n, t)
            if m.get("dmin") is not None:
                dmin = m["dmin"] if dmin is None else min(dmin, m["dmin"])
                dmax = m["dmax"] if dmax is None else max(dmax, m["dmax"])
        if not schema and dmin is None and not tag_keys:
            return None, live
        return ({"tag_keys": tag_keys, "schema": schema,
                 "dmin": dmin, "dmax": dmax}, live)

    def select_partials(self, req: dict, live: list[str]) -> list[dict]:
        """Partial-aggregate round against the live set pinned by the
        metadata round. Any death here shifts primary ownership, which
        invalidates the coordinator's whole plan — raise PartialsRetry
        so the statement rebuilds, instead of silently merging a
        now-inconsistent primary view."""
        from opengemini_tpu.query.partials import parse_partials

        STATS.incr("cluster", "partials_fanouts")
        body = dict(req, live=live, rf=self.rf)
        # wire trace ctx captured HERE, on the query thread — the fetch
        # closures run on fan-out workers with no thread-local trace
        tctx = tracing.current_ctx()
        if tctx is not None:
            body["trace"] = tctx

        def fetch(nid, addr):
            if nid not in live:
                return {}
            if not addr:
                return _NodeDown(nid, f"no address for data node {nid!r}")
            try:
                raw, _ct = self._post_raw(addr, "/internal/select_partials", body)
                return (nid, parse_partials(raw))
            except urllib.error.HTTPError as e:
                # the peer is ALIVE but errored (bad request / missing
                # endpoint during a rolling upgrade): not a node-down
                return PartialsUnavailable(
                    f"data node {nid!r} ({addr}) cannot serve partials: {e}")
            except OSError as e:
                return _NodeDown(
                    nid, f"data node {nid!r} ({addr}) unreachable: {e}")

        docs = []
        for got in self._fanout(fetch):
            if isinstance(got, PartialsUnavailable):
                raise got
            if isinstance(got, _NodeDown):
                raise PartialsRetry(str(got))
            if got:
                docs.append(got)
        docs.sort(key=lambda p: p[0])  # deterministic tie-break order
        return [d for _nid, d in docs]

    def _fetch_once(self, db, rp, mst, tmin, tmax, live):
        """One fan-out round. Returns (payloads, dead node ids) —
        collecting EVERY dead peer in the round so failover retries once,
        not once per dead node."""
        STATS.incr("cluster", "scan_fanouts")
        tctx = tracing.current_ctx()  # captured on the query thread

        def fetch(nid, addr):
            if nid not in live:
                return {}
            if not addr:
                return _NodeDown(nid, f"no address for data node {nid!r}")
            body = {
                "db": db, "rp": rp, "mst": mst,
                "tmin": tmin, "tmax": tmax,
                "live": live, "rf": self.rf, "fmt": "bin",
            }
            if tctx is not None:
                body["trace"] = tctx
            try:
                return self._post_scan(addr, body)
            except urllib.error.HTTPError as e:
                if e.code in (429, 503):
                    # alive peer SHED the scan (governor admission or
                    # backpressure): the raw exchange is the bottom of
                    # the fallback chain, so this is a clean retryable
                    # query error — NOT a node-down, which would
                    # misreport "unreachable" and evict a merely-
                    # overloaded replica from the live set
                    raise RemoteScanError(
                        f"data node {nid!r} ({addr}) rejected scan: {e}"
                    ) from e
                # any other status (500 disk fault, 404 rolling
                # upgrade): the peer cannot serve this scan — fail over
                # to a replica like an unreachable node, else one sick-
                # but-alive node fails every query touching its shards
                return _NodeDown(
                    nid, f"data node {nid!r} ({addr}) cannot scan: {e}"
                )
            except OSError as e:
                return _NodeDown(
                    nid, f"data node {nid!r} ({addr}) unreachable: {e}"
                )

        payloads, dead = [], set()
        for got in self._fanout(fetch):
            if isinstance(got, _NodeDown):
                dead.add(got.nid)
            else:
                payloads.append(got)
        return payloads, dead

    def _fanout(self, fetch):
        """Run fetch(nid, addr) against every peer concurrently; one slow
        peer bounds latency instead of summing across the cluster."""
        from concurrent.futures import ThreadPoolExecutor

        peers = [(nid, addr) for nid, addr in sorted(self.data_nodes().items())
                 if nid != self.self_id]
        if not peers:
            return []
        with ThreadPoolExecutor(max_workers=min(16, len(peers))) as pool:
            return list(pool.map(lambda p: fetch(*p), peers))

    def remote_measurements(self, db: str, rp: str | None) -> set[str]:
        """Measurement names across peers, with the same rf-1 dead-node
        tolerance as scans (names are replicated with the data)."""
        def fetch(nid, addr):
            if not addr:
                return _NodeDown(nid, f"no address for data node {nid!r}")
            try:
                return self._post(addr, "/internal/measurements",
                                  {"db": db, "rp": rp})
            except OSError as e:
                return _NodeDown(
                    nid, f"data node {nid!r} ({addr}) unreachable: {e}"
                )

        names: set[str] = set()
        dead: list[_NodeDown] = []
        for got in self._fanout(fetch):
            if isinstance(got, _NodeDown):
                dead.append(got)
            else:
                names.update(got.get("measurements", []))
        if len(dead) >= self.rf:
            raise RemoteScanError(str(dead[0]))
        return names
