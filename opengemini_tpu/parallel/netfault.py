"""Deterministic network-fault injection for the cluster data plane.

The cluster torture harness (tools/cluster_torture.py) needs partitions,
black holes, and slow links it can create and heal WITHOUT real network
tooling (iptables/tc are unavailable in test containers and nondetermin-
istic anyway).  This module is the failpoint analogue for the transport:
rules keyed by (src, dst, path) glob patterns are consulted by every
outbound peer call the DataRouter makes (``_post_raw``, liveness probes,
health-view fetches, line-protocol forwards) and either drop the request
(an ``OSError`` indistinguishable from an unreachable peer), delay it,
or answer it with an injected HTTP error status.

Pass-through contract: with no rules armed the hook is one truthiness
check of an empty list — bit-identical behavior to an unwrapped
transport (asserted by tests/test_netfault.py).

Rules are matched CLIENT-side, so a rule armed on node A affects only
A's OUTBOUND traffic: a one-way partition is a single rule; a full
partition is the mirrored pair (the torture harness arms both ends via
``POST /debug/ctrl?mod=netfault``).  The meta-raft plane has its own
transport and is deliberately out of scope — this module partitions the
DATA plane (routed writes, hints, migration, anti-entropy, scans).

Rule shape — three glob patterns and an action:

  src    matched against the calling router's node id
  dst    matched against the target node id AND its host:port address
         (call sites pass whichever they have; either may match)
  path   matched against the URL path (e.g. ``/internal/*``)

Actions:

  drop             raise NetFault (an OSError: looks unreachable)
  delay:<seconds>  sleep, then pass the request through
  error[:<status>] raise urllib.error.HTTPError (default 503)

Arming:

  env:      OGT_NETFAULT="src|dst|path=action;..."
  runtime:  POST /debug/ctrl?mod=netfault&src=...&dst=...&path=...&action=...
            (action=off clears one rule; clear=1 clears all)

Hit counts per rule are recorded for test assertions (``hits()``).
"""

from __future__ import annotations

import fnmatch
import os
import threading
from opengemini_tpu.utils import lockdep
import time

_lock = lockdep.Lock()
# armed rules: (src, dst, path, action) — first match wins, in arming order
_rules: list[tuple[str, str, str, str]] = []
_hits: dict[str, int] = {}


class NetFault(OSError):
    """Injected transport fault (presents as an unreachable peer)."""


def validate(action: str) -> None:
    """Reject malformed actions at arming time — a typo must fail the
    ctrl call, not silently pass traffic through (or crash a later
    check() deep inside a write path)."""
    if action == "drop":
        return
    if action.startswith("delay:"):
        secs = float(action.split(":", 1)[1])  # ValueError on garbage
        if not 0 <= secs < float("inf"):  # also rejects nan
            raise ValueError(f"bad netfault delay {secs}")
        return
    if action == "error":
        return
    if action.startswith("error:"):
        status = int(action.split(":", 1)[1])
        if not 100 <= status <= 599:
            raise ValueError(f"bad netfault error status {status}")
        return
    raise ValueError(f"unknown netfault action {action!r}")


def _load_env() -> None:
    spec = os.environ.get("OGT_NETFAULT", "")
    for part in spec.split(";"):
        part = part.strip()
        if not part or "=" not in part:
            continue
        key, _, action = part.partition("=")
        bits = key.split("|")
        if len(bits) != 3:
            continue
        try:
            validate(action.strip())
        except ValueError:
            continue
        _rules.append((bits[0].strip() or "*", bits[1].strip() or "*",
                       bits[2].strip() or "*", action.strip()))


_load_env()


def _key(src: str, dst: str, path: str, action: str) -> str:
    return f"{src}|{dst}|{path}={action}"


def set_rule(src: str, dst: str, path: str, action: str) -> None:
    validate(action)
    with _lock:
        _rules[:] = [r for r in _rules if r[:3] != (src, dst, path)]
        _rules.append((src, dst, path, action))


def clear_rule(src: str, dst: str, path: str) -> bool:
    with _lock:
        before = len(_rules)
        _rules[:] = [r for r in _rules if r[:3] != (src, dst, path)]
        return len(_rules) != before


def clear_all() -> None:
    with _lock:
        _rules.clear()
        _hits.clear()


def rules() -> list[dict]:
    with _lock:
        return [{"src": s, "dst": d, "path": p, "action": a}
                for s, d, p, a in _rules]


def hits() -> dict[str, int]:
    with _lock:
        return dict(_hits)


def check(src: str, path: str, *dsts: str) -> None:
    """The transport hook: no-op unless a rule matches (src, any of
    dsts, path).  Raises NetFault (drop), sleeps (delay), or raises
    urllib.error.HTTPError (error) per the first matching rule."""
    if not _rules:  # fast path: nothing armed
        return
    with _lock:
        action = None
        for rs, rd, rp, act in _rules:
            if not fnmatch.fnmatch(src or "", rs):
                continue
            if not any(fnmatch.fnmatch(d or "", rd) for d in dsts if d):
                continue
            if not fnmatch.fnmatch(path, rp):
                continue
            key = _key(rs, rd, rp, act)
            _hits[key] = _hits.get(key, 0) + 1
            action = act
            break
    if action is None:
        return
    if action == "drop":
        raise NetFault(
            f"netfault: dropped {src or '?'} -> {dsts[0] if dsts else '?'} "
            f"{path}")
    if action.startswith("delay:"):
        # audited blocking: delay: exists to stall RPCs mid-flight,
        # deliberately wherever the consult point sits
        with lockdep.allow_blocking("netfault delay action"):
            time.sleep(float(action.split(":", 1)[1]))
        return
    # error[:status]
    import io
    import urllib.error

    status = int(action.split(":", 1)[1]) if ":" in action else 503
    raise urllib.error.HTTPError(
        path, status, "netfault injected error", hdrs=None,
        fp=io.BytesIO(b""))
