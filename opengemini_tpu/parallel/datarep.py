"""Strict-consistency data replication: raft-committed writes.

Reference: the replication HA policy's etcd-raft data path — one raft
group per replica group, writes commit through its log before the client
ACKs (lib/raftconn/node.go:108 StartNode, engine/partition_raft.go).
Here a replica group is a DISTINCT rf-owner set from rendezvous
placement; its members run one RaftNode (the same from-scratch raft as
the meta plane, meta/raft.py) whose FSM applies committed write batches
to each member's local engine. Engine writes are LWW-idempotent, so
restart log replay needs no applied markers — re-applying a batch
converges to the same state.

Contrast with the default write-available policy (hinted handoff +
anti-entropy, parallel/cluster.py): replication trades availability for
consistency — a write ACKs only after a RAFT MAJORITY of the owner set
has durably logged it, and with rf=2 one dead owner blocks writes to its
groups (the strict mode's defining property). Reads stay primary-
filtered; replicas are consistent by construction.

Catch-up beyond log compaction: the write FSM's raft snapshot carries no
rows (state lives in the engine), so a straggler needing compacted
entries converges through the rf>1 anti-entropy digest repair instead —
the compact threshold is set high to make that rare.

Membership changes: owner sets are a pure function of the roster, so a
roster change simply routes new writes to a NEW group over the new set;
the old group idles (its log compacts to a marker) and the data itself
moves via the two-phase migration service. SHOW DIAGNOSTICS lists the
live groups with their raft state.
"""

from __future__ import annotations

import logging
import os
import threading
from opengemini_tpu.utils import lockdep
import time as _time

from opengemini_tpu.meta.raft import LEADER, RaftNode
from opengemini_tpu.meta.service import HttpTransport
from opengemini_tpu.parallel.cluster import (
    RemoteScanError, decode_points, encode_points, owners,
)
from opengemini_tpu.utils.stats import GLOBAL as STATS

logger = logging.getLogger("opengemini_tpu.datarep")

_TICK_S = 0.05
_COMPACT = 4096


def gid_of(owner_set: tuple) -> str:
    return "rg:" + ",".join(owner_set)


class _WriteFSM:
    """apply = engine.write_rows. A batch a replica cannot apply (schema
    conflict discovered only here) is logged and skipped — the group must
    keep applying; the coordinator validated against its own engine
    before proposing, so divergence means operator intervention either
    way and anti-entropy will surface it."""

    def __init__(self, engine):
        self.engine = engine
        self.applied = 0

    def apply(self, index: int, cmd: dict) -> None:
        if cmd.get("op") == "write":
            try:
                self.engine.write_rows(
                    cmd["db"], decode_points(cmd["points"]),
                    rp=cmd.get("rp") or None)
            except Exception:  # noqa: BLE001 — the group must advance
                logger.exception("datarep apply failed at index %d", index)
        self.applied = index

    def snapshot(self) -> dict:
        # rows live in the engine; the snapshot is only a compaction
        # marker (see module docstring re: straggler catch-up)
        return {"applied": self.applied}

    def restore(self, state: dict) -> None:
        self.applied = int(state.get("applied", 0))


class ReplicaGroup:
    """One raft group over one owner set: RaftNode + write FSM + ticker."""

    def __init__(self, gid: str, self_id: str, owner_set: tuple,
                 addr_of: dict, engine, token: str, self_addr: str):
        self.gid = gid
        self.owner_set = owner_set
        self.fsm = _WriteFSM(engine)
        safe = gid.replace(":", "_").replace(",", "-")
        storage_dir = os.path.join(engine.root, "raftdata")
        os.makedirs(storage_dir, exist_ok=True)
        transport = _GroupTransport(gid, owner_set, addr_of, token,
                                    self_addr)
        self.node = RaftNode(
            self_id, sorted(owner_set), transport,
            apply_fn=self.fsm.apply,
            storage_path=os.path.join(storage_dir, safe + ".log"),
            restore_fn=self.fsm.restore,
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"datarep-{gid}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(_TICK_S):
            self.node.tick()
            if len(self.node.log) > _COMPACT:
                self.node.take_snapshot(self.fsm.snapshot)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def is_leader(self) -> bool:
        return self.node.state == LEADER

    def propose_and_wait(self, cmd: dict, timeout_s: float = 10.0) -> bool:
        got = self.node.propose_with_term(cmd)
        if got is None:
            return False
        idx, term = got
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            t = self.node.entry_term(idx)
            if t == term:
                if self.node.last_applied >= idx:
                    # re-check the term AFTER observing applied: an
                    # overwrite + apply can land between the two reads
                    # (compacted-now reads None -> conservative False)
                    return self.node.entry_term(idx) == term
            else:
                # t different: overwritten after a leader change — an
                # applied-first order would falsely ACK once the
                # OVERWRITING entry applies (a lost write acked). t None:
                # compacted before we confirmed the term — conservatively
                # report False; the retry is LWW-idempotent, a false ACK
                # is not recoverable.
                return False
            _time.sleep(_TICK_S / 2)
        return False


class _GroupTransport(HttpTransport):
    """Raft messages for one replica group ride /internal/raftdata with
    the group id + owner set attached (the receiver creates its member
    lazily on first delivery)."""

    def __init__(self, gid: str, owner_set: tuple, addr_of: dict,
                 token: str, self_addr: str):
        super().__init__(addr_of, timeout_s=0.5, token=token,
                         self_addr=self_addr, path="/internal/raftdata")
        self._gid = gid
        self._owners = list(owner_set)

    def send(self, peer: str, msg: dict) -> None:
        super().send(peer, dict(msg, group=self._gid,
                                owners=self._owners))


class DataReplication:
    """Manager: lazy replica groups + the strict write path."""

    def __init__(self, router, token: str = ""):
        self.router = router
        self.engine = router.engine
        self.token = token
        self.groups: dict[str, ReplicaGroup] = {}
        self._lock = lockdep.Lock()
        # live address book shared (by reference) with every group
        # transport; refreshed from the roster on ensure/deliver
        self._addr_of: dict[str, str] = {}

    def _refresh_addrs(self) -> None:
        for nid, addr in self.router.data_nodes().items():
            if addr:
                self._addr_of[nid] = addr

    def ensure_group(self, owner_set: tuple) -> ReplicaGroup:
        gid = gid_of(owner_set)
        with self._lock:
            grp = self.groups.get(gid)
            if grp is None:
                self._refresh_addrs()
                grp = ReplicaGroup(
                    gid, self.router.self_id, owner_set, self._addr_of,
                    self.engine, self.token, self.router.self_addr)
                self.groups[gid] = grp
        return grp

    def deliver(self, msg: dict) -> bool:
        owner_set = tuple(msg.pop("owners", ()))
        gid = msg.pop("group", "")
        if self.router.self_id not in owner_set or gid != gid_of(owner_set):
            return False
        self._refresh_addrs()
        self.ensure_group(owner_set).node.deliver(msg)
        return True

    def stop(self) -> None:
        with self._lock:
            for grp in self.groups.values():
                grp.stop()
            self.groups.clear()

    def group_status(self) -> list[list]:
        """Snapshot rows for SHOW DIAGNOSTICS (taken under the lock —
        lazy group creation mutates self.groups concurrently)."""
        with self._lock:
            items = list(self.groups.items())
        return [
            [gid, ",".join(g.owner_set), g.node.state,
             g.node.leader_id or "", len(g.node.log), g.node.last_applied]
            for gid, g in sorted(items)
        ]

    # -- write path -------------------------------------------------------

    def write(self, db: str, rp, points: list) -> int:
        """Raft-committed write: every point's batch commits through its
        owner set's raft group before the ACK. Raises RemoteScanError
        when any group cannot commit (strict mode: no hints)."""
        d = self.engine.databases.get(db)
        if d is None:
            from opengemini_tpu.storage.engine import DatabaseNotFound

            raise DatabaseNotFound(db)
        rp_name = rp or d.default_rp
        ids = sorted(self.router.data_nodes())
        buckets: dict[tuple, list] = {}
        for p in points:
            start = self.router._group_start(db, rp, p[2])
            # SORTED owner set: rendezvous order varies per group start,
            # and order-variant tuples must share ONE raft group per
            # distinct membership (not rf! of them). group_owners (not
            # raw rendezvous): a balancer placement override must steer
            # writes to the same owners migration moves the data to
            own = tuple(sorted(self.router.group_owners(
                db, rp_name, start, nodes=ids)))
            buckets.setdefault(own, []).append(p)
        # buckets commit through INDEPENDENT raft groups: run them
        # concurrently (a serial walk would multiply cold-group election
        # waits by the bucket count), all-or-error semantics unchanged
        errors: list[Exception] = []

        def commit(owner_set: tuple, pts: list) -> None:
            cmd = {"op": "write", "db": db, "rp": rp_name,
                   "points": encode_points(pts)}
            try:
                if self.router.self_id in owner_set:
                    if not self._commit_local(owner_set, cmd):
                        raise RemoteScanError(
                            f"replication commit failed for group "
                            f"{gid_of(owner_set)} (no quorum?)")
                else:
                    self._commit_remote(owner_set, cmd)
                STATS.incr("cluster", "raft_write_batches")
            except Exception as e:  # noqa: BLE001 — re-raised below
                errors.append(e)

        items = sorted(buckets.items())
        if len(items) == 1:
            commit(*items[0])
        else:
            threads = [
                threading.Thread(target=commit, args=(own, pts),
                                 daemon=True)
                for own, pts in items
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            raise errors[0] if isinstance(
                errors[0], RemoteScanError) else RemoteScanError(
                str(errors[0]))
        return sum(len(pts) for _own, pts in items)

    def _commit_local(self, owner_set: tuple, cmd: dict) -> bool:
        grp = self.ensure_group(owner_set)
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            if grp.is_leader():
                remaining = max(deadline - _time.monotonic(), 0.5)
                if grp.propose_and_wait(cmd, timeout_s=remaining):
                    return True
                # deposed between check and propose (or the entry was
                # overwritten): retrying is LWW-idempotent — keep going
                # until the deadline instead of failing a live group
                continue
            hint = grp.node.leader_id
            if hint and hint != self.router.self_id:
                addr = self._addr_of.get(hint)
                try:
                    if addr and self._propose_at(addr, owner_set, cmd):
                        return True
                except OSError:
                    pass  # hinted leader died: re-election is in flight
            _time.sleep(0.1)  # election in progress: wait, re-check
        return False

    def _commit_remote(self, owner_set: tuple, cmd: dict) -> None:
        self._refresh_addrs()
        last = None
        deadline = _time.monotonic() + 10.0
        while _time.monotonic() < deadline:
            # retry across members until the group's (possibly FIRST)
            # election settles — a cold group answers not-leader from
            # every member for ~1s
            for peer in owner_set:
                addr = self._addr_of.get(peer)
                if not addr:
                    continue
                try:
                    if self._propose_at(addr, owner_set, cmd):
                        return
                except OSError as e:
                    last = e
            _time.sleep(0.2)
        raise RemoteScanError(
            f"no owner of {gid_of(owner_set)} accepted the raft write"
            + (f": {last}" if last else ""))

    def _propose_at(self, addr: str, owner_set: tuple, cmd: dict,
                    hops: int = 3) -> bool:
        """POST the proposal to a member; follow leader redirects."""
        body = dict(cmd, owners=list(owner_set), token=self.token)
        for _ in range(hops):
            got = self.router._post(addr, "/internal/raftdata_propose",
                                    body, timeout=15.0)
            if got.get("ok"):
                return True
            nxt = got.get("leader_addr")
            if not nxt or nxt == addr:
                return False
            addr = nxt
        return False

    def handle_propose(self, req: dict) -> dict:
        """Server side of /internal/raftdata_propose."""
        owner_set = tuple(req.get("owners", ()))
        if self.router.self_id not in owner_set:
            return {"ok": False, "error": "not an owner"}
        grp = self.ensure_group(owner_set)
        cmd = {"op": "write", "db": req["db"], "rp": req.get("rp"),
               "points": req.get("points", [])}
        if grp.is_leader():
            return {"ok": grp.propose_and_wait(cmd)}
        hint = grp.node.leader_id
        self._refresh_addrs()
        return {"ok": False,
                "leader_addr": self._addr_of.get(hint or "", "")}
