"""shard_map distributed segmented window aggregation.

Design (SURVEY.md §7 step 4): each device owns a row-slice of the scan
batch (its "shards"), computes dense per-segment partial aggregates
locally — the store-side partial agg of the reference
(engine/aggregate_cursor.go) — and the cross-device merge that the
reference does with RPC + merge transforms becomes one XLA collective:
  sum/count -> psum,  min -> pmin,  max -> pmax,
  first/last -> lexicographic (hi, lo, idx) combine via psum of one-hot
                winners (associative, rides ICI).

Everything is jit-compatible and partitions over an arbitrary 1D/2D mesh;
multi-host meshes work unchanged because shard_map + collectives are
device-count agnostic (DCN vs ICI is the runtime's concern).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from opengemini_tpu.ops import segment as seg

_BIG_I32 = 2**31 - 1


def _shard_map(fn, mesh, in_specs, out_specs):
    """Version-compat shard_map: the `jax.shard_map` alias (with its
    `check_vma` kwarg) only exists on newer jax; older releases ship it
    as `jax.experimental.shard_map.shard_map` with the equivalent kwarg
    named `check_rep`.  Replication checking stays OFF either way — the
    collectives here produce replicated outputs by construction and the
    checker rejects the one-hot winner combines."""
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        try:
            return impl(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)
        except TypeError:  # alias exists but still takes check_rep
            return impl(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)
    from jax.experimental.shard_map import shard_map as _impl

    return _impl(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=False)


def _axis_size(ax: str):
    """jax.lax.axis_size is newer than the oldest supported jax; psum of
    a per-device 1 is the portable spelling of the same number."""
    impl = getattr(jax.lax, "axis_size", None)
    if impl is not None:
        return impl(ax)
    return jax.lax.psum(1, ax)


def make_mesh(n_devices: int | None = None, axes: tuple[str, ...] = ("shard",),
              shape: tuple[int, ...] | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    devs = devs[:n_devices]
    if shape is None:
        shape = (n_devices,) if len(axes) == 1 else _factor(n_devices, len(axes))
    arr = np.array(devs).reshape(shape)
    return Mesh(arr, axes)


def _factor(n: int, k: int) -> tuple[int, ...]:
    """Split n into k roughly-even factors (8, 2 axes -> (4, 2))."""
    shape = [1] * k
    i = 0
    d = 2
    while n > 1:
        while n % d:
            d += 1
        shape[i % k] *= d
        n //= d
        i += 1
    shape.sort(reverse=True)
    return tuple(shape)


def _local_partials(values, rel_hi, rel_lo, seg_ids, mask, num_segments):
    """Per-device dense partial aggregates over the local row slice."""
    s = seg.seg_sum(values, seg_ids, num_segments, mask)
    c = seg.seg_count(seg_ids, num_segments, mask)
    mn = seg.seg_min(values, seg_ids, num_segments, mask)
    mx = seg.seg_max(values, seg_ids, num_segments, mask)
    # local first: (hi, lo) of earliest valid row + its value
    fv, fsel = seg.seg_first(values, rel_hi, rel_lo, seg_ids, num_segments, mask)
    safe = jnp.clip(fsel, 0, values.shape[0] - 1)
    f_hi = jnp.where(c > 0, rel_hi[safe], _BIG_I32)
    f_lo = jnp.where(c > 0, rel_lo[safe], _BIG_I32)
    lv, lsel = seg.seg_last(values, rel_hi, rel_lo, seg_ids, num_segments, mask)
    safe_l = jnp.clip(lsel, 0, values.shape[0] - 1)
    l_hi = jnp.where(c > 0, rel_hi[safe_l], -_BIG_I32)
    l_lo = jnp.where(c > 0, rel_lo[safe_l], -_BIG_I32)
    return s, c, mn, mx, (fv, f_hi, f_lo), (lv, l_hi, l_lo)


def _merge_time_extreme(value, hi, lo, axes, earliest: bool):
    """Cross-device lexicographic (hi, lo) winner — exact int32 compares,
    no float encoding (f32 cannot order ns pairs). Two collective rounds:
    pmin/pmax on hi, then on the hi-masked lo. Devices holding the winning
    timestamp contribute value via psum; identical timestamps on several
    devices are averaged deterministically (they tie in the reference too,
    where scan order decides)."""
    if earliest:
        red = jax.lax.pmin
        big = _BIG_I32
    else:
        red = jax.lax.pmax
        big = -_BIG_I32
    hi_best = hi
    for ax in axes:
        hi_best = red(hi_best, ax)
    cand = hi == hi_best
    lo_masked = jnp.where(cand, lo, big)
    lo_best = lo_masked
    for ax in axes:
        lo_best = red(lo_best, ax)
    cand &= lo == lo_best
    # exact-time ties: larger value wins (reference FirstReduce/LastReduce)
    fbig = jnp.array(jnp.inf, value.dtype)
    v_best = jnp.where(cand, value, -fbig)
    for ax in axes:
        v_best = jax.lax.pmax(v_best, ax)
    cand &= value == v_best
    # remaining ties across devices: lowest device rank wins (deterministic,
    # one actual row's value — never an average of tied rows)
    rank = jnp.zeros((), jnp.int32)
    for ax in axes:
        rank = rank * _axis_size(ax) + jax.lax.axis_index(ax)
    rank_masked = jnp.where(cand, rank, _BIG_I32)
    rank_best = rank_masked
    for ax in axes:
        rank_best = jax.lax.pmin(rank_best, ax)
    is_winner = cand & (rank == rank_best)
    wsum = value * is_winner
    for ax in axes:
        wsum = jax.lax.psum(wsum, ax)
    return wsum


def build_dist_agg(mesh: Mesh, num_segments: int):
    """Compile the distributed query step: sharded batch -> replicated
    {sum, count, mean, min, max, first, last} per segment.

    The jitted function takes row-sharded arrays (padded to a multiple of
    the mesh size) and returns replicated outputs — the equivalent of the
    reference's store-scan + exchange + merge pipeline as ONE XLA program.
    """
    axes = mesh.axis_names
    row_spec = P(axes)  # rows sharded over every mesh axis

    def step(values, rel_hi, rel_lo, seg_ids, mask):
        s, c, mn, mx, first_t, last_t = _local_partials(
            values, rel_hi, rel_lo, seg_ids, mask, num_segments
        )
        for ax in axes:
            s = jax.lax.psum(s, ax)
            c = jax.lax.psum(c, ax)
            mn = jax.lax.pmin(mn, ax)
            mx = jax.lax.pmax(mx, ax)
        fv = _merge_time_extreme(*first_t, axes, earliest=True)
        lv = _merge_time_extreme(*last_t, axes, earliest=False)
        mean = s / jnp.maximum(c, 1).astype(s.dtype)
        return {
            "sum": s, "count": c, "mean": mean,
            "min": mn, "max": mx, "first": fv, "last": lv,
        }

    sharded = _shard_map(step, mesh, (row_spec,) * 5, P())
    return jax.jit(sharded)


# aggregates the mesh batch step can serve (everything the executor's
# device path computes except rank-based ones — median/percentile — and
# stddev, which keep the single-device kernels)
MESH_AGGS = {"count", "sum", "mean", "min", "max", "first", "last", "spread"}

_BIG_F = jnp.inf


def _reduce(x, axes, op):
    for ax in axes:
        x = op(x, ax)
    return x


def _winner(keys, valid, axes):
    """Cross-device lexicographic winner one-hot. keys: [(array,
    minimize)], narrowed key by key; ties resolve to the lowest device
    rank — exactly one device wins per segment, deterministically."""
    cand = valid
    for arr, minimize in keys:
        if jnp.issubdtype(arr.dtype, jnp.floating):
            sent = _BIG_F if minimize else -_BIG_F
        else:
            sent = _BIG_I32 if minimize else -_BIG_I32
        masked = jnp.where(cand, arr, sent)
        best = _reduce(masked, axes, jax.lax.pmin if minimize else jax.lax.pmax)
        cand = cand & (masked == best)
    rank = jnp.zeros((), jnp.int32)
    for ax in axes:
        rank = rank * _axis_size(ax) + jax.lax.axis_index(ax)
    rank_masked = jnp.where(cand, rank, _BIG_I32)
    rank_best = _reduce(rank_masked, axes, jax.lax.pmin)
    return cand & (rank == rank_best)


def _pick(x, w, axes):
    """Replicate the winning device's x (w: winner one-hot). where, not
    multiply: inf * 0 would poison the psum with NaN."""
    return _reduce(jnp.where(w, x, jnp.zeros((), x.dtype)), axes, jax.lax.psum)


def build_batch_agg(mesh: Mesh, num_segments: int,
                    sel_names: tuple = ()):
    """The executor's aggregate batch step over a device mesh: the exact
    multi-chip equivalent of templates.AggBatch's single-device kernels.

    Takes row-sharded (values, rel_hi, rel_lo, seg_ids, mask, global_idx)
    and returns replicated per-segment outputs. count/sum/mean and
    min/max/spread VALUES are plain psum/pmin/pmax; the winner one-hot
    machinery (several collective rounds each) is built only for the
    selectors in `sel_names` — their `<name>_sel` outputs are global row
    indices the executor resolves against host-side ns times exactly like
    the single-device sel contract (reference: the store-side aggregate
    cursors + coordinator merge collapsed into one SPMD program)."""
    axes = mesh.axis_names

    def step(values, rel_hi, rel_lo, seg_ids, mask, gidx):
        n_rows = values.shape[0]

        def tkeys(sel):
            safe = jnp.clip(sel, 0, n_rows - 1)
            return rel_hi[safe], rel_lo[safe], gidx[safe]

        c = seg.seg_count(seg_ids, num_segments, mask)
        s = seg.seg_sum(values, seg_ids, num_segments, mask)
        valid = c > 0
        totc = _reduce(c, axes, jax.lax.psum)
        tots = _reduce(s, axes, jax.lax.psum)
        mn = _reduce(seg.seg_min(values, seg_ids, num_segments, mask),
                     axes, jax.lax.pmin)
        mx = _reduce(seg.seg_max(values, seg_ids, num_segments, mask),
                     axes, jax.lax.pmax)
        out = {
            "count": totc,
            "sum": tots,
            "mean": tots / jnp.maximum(totc, 1).astype(tots.dtype),
            "min": mn,
            "max": mx,
            "spread": mx - mn,
        }
        local_sel = {
            "min": lambda: seg.seg_min_selector(
                values, rel_hi, rel_lo, seg_ids, num_segments, mask),
            "max": lambda: seg.seg_max_selector(
                values, rel_hi, rel_lo, seg_ids, num_segments, mask),
            "first": lambda: seg.seg_first(
                values, rel_hi, rel_lo, seg_ids, num_segments, mask),
            "last": lambda: seg.seg_last(
                values, rel_hi, rel_lo, seg_ids, num_segments, mask),
        }
        for name in sel_names:
            v, sel = local_sel[name]()
            th, tl, gsel = tkeys(sel)
            if name == "min":
                keys = [(v, True), (th, True), (tl, True)]
            elif name == "max":
                keys = [(v, False), (th, True), (tl, True)]
            elif name == "first":
                # time ties take the larger value (reference FirstReduce)
                keys = [(th, True), (tl, True), (v, False)]
            else:
                keys = [(th, False), (tl, False), (v, False)]
            w = _winner(keys, valid, axes)
            out[name] = _pick(v, w, axes)
            out[name + "_sel"] = _pick(gsel, w, axes)
        return out

    sharded = _shard_map(step, mesh, (P(axes),) * 6, P())
    return jax.jit(sharded)


_BATCH_AGG_CACHE: dict = {}


def batch_agg_jit(mesh: Mesh, num_segments: int, sel_names: tuple = ()):
    key = (mesh, num_segments, sel_names)
    fn = _BATCH_AGG_CACHE.get(key)
    if fn is None:
        from opengemini_tpu.utils import devobs

        devobs.note_compile("mesh_batch_agg",
                            (mesh.size, num_segments, sel_names))
        fn = _BATCH_AGG_CACHE[key] = build_batch_agg(
            mesh, num_segments, sel_names)
    return fn


def shard_rows(mesh: Mesh, *arrays, xfer_site: str = "agg-batch"):
    """Pad 1D row arrays to a multiple of the mesh size (padding masked
    out by callers via the mask array convention) and device_put them with
    the row sharding — the 1D special case of shard_leading_axis."""
    return shard_leading_axis(mesh, *arrays, xfer_site=xfer_site)


def shard_leading_axis(mesh: Mesh, *arrays, xfer_site: str = "mesh-shard"):
    """device_put matrices with their LEADING axis sharded over every mesh
    axis (remaining axes replicated per device). This is how the dense
    layouts (models/ragged.py bucket matrices, models/grid.py grids) go
    multi-chip: their rows are independent — one segment/series-run lives
    in exactly one row — so the per-row dense reduces partition with ZERO
    collectives; GSPMD compiles the same kernels row-parallel and the host
    gathers (num_rows,)-shaped outputs. The reference needs an exchange +
    merge pipeline here (rpc_transform.go:117); the dense layout makes the
    merge a no-op by construction.

    Rows are padded (zeros -> masked out by the kernels' mask plane or
    sliced off by the [:g] caller convention) to a multiple of mesh.size.
    """
    import time as _time

    from opengemini_tpu.utils import devobs
    from opengemini_tpu.utils.stats import GLOBAL as _STATS

    n_dev = mesh.size
    n = arrays[0].shape[0]
    npad = (n + n_dev - 1) // n_dev * n_dev
    out = []
    nbytes = 0
    t0 = _time.perf_counter_ns()
    for a in arrays:
        if npad != n:
            pad = np.zeros((npad - n,) + a.shape[1:], dtype=a.dtype)
            a = np.concatenate([a, pad])
        out.append(jax.device_put(a, leading_axis_sharding(mesh, a.ndim)))
        nbytes += int(a.nbytes)
    _STATS.incr("device", "mesh_dense_batches")
    # every byte here is a host->device transfer a warm mesh query should
    # NOT repeat (the colcache device tier retains the sharded buffers);
    # the multichip bench asserts this counter is flat across warm runs
    _STATS.incr("device", "mesh_h2d_bytes", nbytes)
    devobs.note_transfer("h2d", xfer_site, nbytes,
                         (_time.perf_counter_ns() - t0) / 1e9)
    return tuple(out)


def leading_axis_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """The explicit NamedSharding of shard_leading_axis: leading axis
    partitioned over EVERY mesh axis, remaining axes replicated."""
    return NamedSharding(mesh, P(mesh.axis_names, *([None] * (ndim - 1))))


@functools.lru_cache(maxsize=64)
def _reshard_jit(out_shardings, avals):
    """Compiled identity resharding program, cached per (target sharding,
    shapes/dtypes). donate_argnums frees the stale source layout as the
    new one materializes — a mesh swap never holds both copies resident
    (donation is a no-op on backends that don't implement it, e.g. the
    CPU virtual mesh; the warning is suppressed at the call site)."""
    from opengemini_tpu.utils import devobs

    devobs.note_compile("reshard", avals)
    n = len(avals)
    return jax.jit(
        lambda *xs: xs,
        out_shardings=(out_shardings,) * n,
        donate_argnums=tuple(range(n)),
    )


def donate_reshard(target_sharding, *arrays):
    """Device-to-device relayout of already-resident arrays onto
    ``target_sharding``, DONATING the inputs. This is how the colcache
    device tier follows a runtime.set_mesh() change: the retained grid
    buffers move to the new mesh layout without a host round trip and
    without doubling resident bytes.

    jit only accepts donation when source and target span the SAME
    device set; a mesh shrink/grow (8 -> 4 devices) relayouts via
    jax.device_put instead — no donation there, the stale buffers free
    by refcount the moment the caller swaps them out."""
    import time as _time
    import warnings

    from opengemini_tpu.utils import devobs
    from opengemini_tpu.utils.stats import GLOBAL as _STATS

    _STATS.incr("device", "mesh_reshards")
    nbytes = sum(int(a.nbytes) for a in arrays)
    t0 = _time.perf_counter_ns()
    same_devices = all(
        set(a.sharding.device_set) == set(target_sharding.device_set)
        for a in arrays)
    if not same_devices:
        out = tuple(jax.device_put(a, target_sharding) for a in arrays)
        devobs.note_transfer("reshard", "reshard", nbytes,
                             (_time.perf_counter_ns() - t0) / 1e9)
        return out
    avals = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
    fn = _reshard_jit(target_sharding, avals)
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message=".*donated buffers were not usable.*")
        out = fn(*arrays)
    devobs.note_transfer("reshard", "reshard", nbytes,
                         (_time.perf_counter_ns() - t0) / 1e9)
    return out
