"""Full-text index binding (native/textindex.cpp) with python fallback.

Reference: engine/index/textindex (C++ via cgo: AddDocument,
RetrievePostingList) powering log-search. Query integration: the
`match(field, 'token')` WHERE function tokenizes string field values;
shard-persistent text indexes layer on top of this in the logstore round.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "..", "native", "libogttextindex.so")
    )
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.ogt_text_index_new.restype = ctypes.c_void_p
        lib.ogt_text_index_free.argtypes = [ctypes.c_void_p]
        lib.ogt_text_index_add.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64
        ]
        lib.ogt_text_index_search.restype = ctypes.c_int64
        lib.ogt_text_index_search.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.ogt_text_index_tokens.restype = ctypes.c_int64
        lib.ogt_text_index_tokens.argtypes = [ctypes.c_void_p]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


class TextIndex:
    """Inverted token index over documents; C++ when built, dict fallback."""

    def __init__(self) -> None:
        self._lib = _load()
        if self._lib is not None:
            self._h = self._lib.ogt_text_index_new()
        else:
            self._post: dict[str, list[int]] = {}

    def add(self, doc_id: int, text: str) -> None:
        if self._lib is not None:
            b = text.encode("utf-8", errors="replace")
            self._lib.ogt_text_index_add(self._h, doc_id, b, len(b))
        else:
            for tok in set(tokenize(text)):
                self._post.setdefault(tok, []).append(doc_id)

    def search(self, token: str) -> np.ndarray:
        """Doc ids matching a term. Multi-gram terms (CJK strings, mixed
        script) intersect their grams' postings — the per-character index
        scheme query_grams() documents. ASCII lowercases; non-ASCII is
        byte-exact (the index never case-folds it)."""
        grams = query_grams(token)
        if len(grams) > 1:
            out = None
            for g in grams:
                if g.isascii():
                    continue  # ASCII fragments may sit inside longer tokens
                ids = set(self.search(g).tolist())
                out = ids if out is None else out & ids
            if out is None:  # pure-ASCII multi-token term: all must match
                for g in grams:
                    ids = set(self.search(g).tolist())
                    out = ids if out is None else out & ids
            return np.asarray(sorted(out or ()), dtype=np.int64)
        token = token.lower() if token.isascii() else token
        if self._lib is not None:
            b = token.encode("utf-8", errors="replace")
            cap = 1024
            while True:
                out = np.empty(cap, dtype=np.int64)
                n = self._lib.ogt_text_index_search(self._h, b, len(b),
                                                    out.ctypes.data, cap)
                if n <= cap:
                    return out[:n].copy()
                cap = int(n)
        return np.asarray(sorted(self._post.get(token, [])), dtype=np.int64)

    def token_count(self) -> int:
        if self._lib is not None:
            return int(self._lib.ogt_text_index_tokens(self._h))
        return len(self._post)

    def close(self) -> None:
        if self._lib is not None and self._h:
            self._lib.ogt_text_index_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def tokenize(text: str) -> list[str]:
    """ASCII alnum runs >= 2 chars lowercased, plus one gram per
    non-ASCII character (reference SimpleGramTokenizer's split-table
    walk, FullTextIndex.cpp:19-40 — CJK indexes per character). Matches
    the C++ tokenizer byte-for-byte over utf-8 input."""
    out: list[str] = []
    cur: list[str] = []
    for ch in text:
        if ch.isascii():
            if ch.isalnum():
                cur.append(ch.lower())
                continue
            if len(cur) >= 2:
                out.append("".join(cur))
            cur = []
        else:
            if len(cur) >= 2:
                out.append("".join(cur))
            cur = []
            out.append(ch)
    if len(cur) >= 2:
        out.append("".join(cur))
    return out


def query_grams(term: str) -> list[str]:
    """Index lookup tokens for one match() search term: its own
    tokenization (a multi-character CJK term becomes several grams that
    the caller intersects)."""
    return tokenize(term)


def match_token(values: np.ndarray, valid: np.ndarray, token: str) -> np.ndarray:
    """Row mask for WHERE match(f, 'term').

    ASCII terms match whole tokens case-insensitively. Terms with
    non-ASCII characters match as EXACT (byte) substrings — the index
    never case-folds non-ASCII (neither does the reference's
    SimpleGramTokenizer), so the row filter must agree or pruning would
    silently drop rows the filter accepts."""
    has_cjk = not token.isascii()
    term = token if has_cjk else token.lower()
    out = np.zeros(len(values), dtype=np.bool_)
    for i, v in enumerate(values):
        if not (valid[i] and isinstance(v, str)):
            continue
        if has_cjk:
            out[i] = term in v
        else:
            out[i] = term in tokenize(v)
    return out
