"""ctypes bindings for the C++ codec library (native/codecs.cpp).

The reference uses cgo for its native pieces (textindex, lz4, rocksdb);
pybind11 isn't in this image, so the bridge is a plain C ABI + ctypes
(SURVEY.md environment notes). Missing/unbuilt library degrades
gracefully: encoders fall back to the pure-Python/zlib paths, and the
pure-Python gorilla/varint decoders below keep every file readable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_TRIED = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "..", "..", "native", "libogtcodecs.so")


def load():
    """The loaded library or None. Never raises."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.abspath(_lib_path())
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        for name, restype, argtypes in [
            ("ogt_gorilla_encode", ctypes.c_int64,
             [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]),
            ("ogt_gorilla_decode", ctypes.c_int64,
             [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]),
            ("ogt_varint_delta_encode", ctypes.c_int64,
             [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]),
            ("ogt_varint_delta_decode", ctypes.c_int64,
             [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64]),
        ]:
            fn = getattr(lib, name)
            fn.restype = restype
            fn.argtypes = argtypes
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def build() -> bool:
    """Compile the library with g++ (used by native.build / tests)."""
    d = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", "native"))
    try:
        subprocess.run(["make", "-C", d], check=True, capture_output=True)
    except (subprocess.CalledProcessError, OSError):
        return False
    global _TRIED, _LIB
    _TRIED = False
    _LIB = None
    return load() is not None


# -- native-backed codecs ----------------------------------------------------


def gorilla_encode(values: np.ndarray) -> bytes | None:
    lib = load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    cap = len(vals) * 10 + 16
    out = np.zeros(cap, dtype=np.uint8)
    n = lib.ogt_gorilla_encode(
        vals.ctypes.data, len(vals), out.ctypes.data, cap
    )
    if n < 0:
        return None
    return out[:n].tobytes()


def gorilla_decode_native(buf: bytes, n: int) -> np.ndarray | None:
    lib = load()
    if lib is None:
        return None
    inp = np.frombuffer(buf, dtype=np.uint8)
    out = np.empty(n, dtype=np.uint64)
    got = lib.ogt_gorilla_decode(inp.ctypes.data, len(inp), out.ctypes.data, n)
    if got != n:
        raise ValueError("corrupt gorilla block")
    return out.view(np.float64)


def varint_delta_encode(values: np.ndarray) -> bytes | None:
    lib = load()
    if lib is None:
        return None
    vals = np.ascontiguousarray(values, dtype=np.int64)
    cap = len(vals) * 10 + 16
    out = np.zeros(cap, dtype=np.uint8)
    n = lib.ogt_varint_delta_encode(vals.ctypes.data, len(vals), out.ctypes.data, cap)
    if n < 0:
        return None
    return out[:n].tobytes()


def varint_delta_decode_native(buf: bytes, n: int) -> np.ndarray | None:
    lib = load()
    if lib is None:
        return None
    inp = np.frombuffer(buf, dtype=np.uint8)
    out = np.empty(n, dtype=np.int64)
    got = lib.ogt_varint_delta_decode(inp.ctypes.data, len(inp), out.ctypes.data, n)
    if got != n:
        raise ValueError("corrupt varint block")
    return out


# -- pure-python decode fallbacks (files stay readable without the lib) ------


def gorilla_decode_py(buf: bytes, n: int) -> np.ndarray:
    out = np.empty(n, dtype=np.uint64)
    if n == 0:
        return out.view(np.float64)
    bits = _Bits(buf)
    prev = bits.read(64)
    out[0] = prev
    lz = tz = 0
    for i in range(1, n):
        if bits.read(1) == 0:
            out[i] = prev
            continue
        if bits.read(1) == 1:
            lz = bits.read(5)
            mbits = bits.read(6) + 1
            tz = 64 - lz - mbits
            if tz < 0:
                raise ValueError("corrupt gorilla block")
        mbits = 64 - lz - tz
        x = bits.read(mbits) << tz
        prev ^= x
        out[i] = prev & 0xFFFFFFFFFFFFFFFF
    return out.view(np.float64)


def varint_delta_decode_py(buf: bytes, n: int) -> np.ndarray:
    out = np.empty(n, dtype=np.int64)
    pos = 0
    prev = 0
    for i in range(n):
        u = 0
        shift = 0
        while True:
            if pos >= len(buf):
                raise ValueError("corrupt varint block")
            b = buf[pos]
            pos += 1
            u |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        delta = (u >> 1) ^ -(u & 1)
        # int64 wraparound semantics must match the native codec: deltas
        # may overflow int64 by design (encoded mod 2^64)
        prev = (prev + delta) & 0xFFFFFFFFFFFFFFFF
        out[i] = prev - (1 << 64) if prev >= (1 << 63) else prev
        prev = int(out[i])
    return out


class _Bits:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, nbits: int) -> int:
        v = 0
        for _ in range(nbits):
            byte_i = self.pos >> 3
            if byte_i >= len(self.buf):
                raise ValueError("truncated bit stream")
            bit = (self.buf[byte_i] >> (7 - (self.pos & 7))) & 1
            v = (v << 1) | bit
            self.pos += 1
        return v


def gorilla_decode(buf: bytes, n: int) -> np.ndarray:
    got = gorilla_decode_native(buf, n)
    return got if got is not None else gorilla_decode_py(buf, n)


def varint_delta_decode(buf: bytes, n: int) -> np.ndarray:
    got = varint_delta_decode_native(buf, n)
    return got if got is not None else varint_delta_decode_py(buf, n)
