"""Engine: databases -> retention policies -> time-partitioned shards.

Reference: engine/engine.go:112 (NewEngine, WriteRows:1203,
CreateShard:1270, loadShards:299) plus the shard-group time partitioning
from the meta data model (lib/util/lifted/influx/meta data.go). Round-1
scope: a single-node engine embedding its own metadata (the distributed
meta plane lives in opengemini_tpu/meta and layers on top).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from opengemini_tpu.utils import lockdep
import time as _time

from opengemini_tpu.ingest import line_protocol as lp
from opengemini_tpu.record import FieldTypeConflict
from opengemini_tpu.storage.shard import Shard
from opengemini_tpu.utils.failpoint import inject as _fp
from opengemini_tpu.utils.stats import GLOBAL as STATS

NS = 1_000_000_000
DEFAULT_SHARD_DURATION = 7 * 24 * 3600 * NS  # influx 1w default for infinite RPs

# Go time.Time zero (year 1, Jan 1 — a Monday) relative to the Unix epoch:
# the reference aligns shard groups with Go's Truncate, which rounds to
# multiples of the duration SINCE THE ZERO TIME (meta/data.go:2348), so 7d
# groups start on Mondays, not the epoch's Thursday grid. The offset in ns
# overflows int64, so alignment uses its residue mod the duration (the
# phase) — same grid, int64-safe (works for numpy vectorized forms too).
_GO_ZERO_S = -62135596800  # seconds; *NS overflows int64


# -- multi-core ingest pool (reference: influx.ScheduleUnmarshalWork) ----
_INGEST_WORKERS = int(os.environ.get("OGT_INGEST_WORKERS", "0")) or (
    len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity")
    else (os.cpu_count() or 1))
_INGEST_SEGMENT_BYTES = 1 << 20  # split target; bodies below 2MB stay inline
_NEEDS_PYTHON_PARSER = object()  # _write_segmented: skip native re-parse
_ingest_pool_obj = None
_ingest_pool_lock = lockdep.Lock()


def _ingest_pool():
    """Shared parse pool, or None on single-core hosts (threads would only
    add overhead when the C parser has one core to release the GIL to)."""
    global _ingest_pool_obj
    if _INGEST_WORKERS < 2:
        return None
    if _ingest_pool_obj is None:
        from concurrent.futures import ThreadPoolExecutor

        with _ingest_pool_lock:
            if _ingest_pool_obj is None:
                _ingest_pool_obj = ThreadPoolExecutor(
                    max_workers=_INGEST_WORKERS,
                    thread_name_prefix="ogt-ingest")
    return _ingest_pool_obj


def _split_lp_segments(raw: bytes, n: int) -> list[bytes]:
    """Split a line-protocol body into <= n segments at line boundaries."""
    target = max(len(raw) // n, _INGEST_SEGMENT_BYTES)
    segs, start = [], 0
    while start < len(raw) and len(segs) < n - 1:
        cut = raw.find(b"\n", start + target)
        if cut == -1:
            break
        segs.append(raw[start:cut + 1])
        start = cut + 1
    if start < len(raw):
        segs.append(raw[start:])
    return segs


def _check_namespace_name(name: str, what: str) -> None:
    """db/rp names become directory components AND 'db|rp|start' keys in
    the balancer's load reports and placement overrides — separators and
    path characters must be rejected at creation."""
    if not name or any(c in name for c in "|/\\\n\r\0") or name in (".", ".."):
        raise WriteError(f"invalid {what} name {name!r}")


def _go_phase_ns(dur_ns: int) -> int:
    return (_GO_ZERO_S * NS) % dur_ns  # python ints: exact, non-negative


def shard_group_start(t_ns: int, dur_ns: int) -> int:
    """Shard-group start containing t_ns: Go Truncate alignment."""
    phase = _go_phase_ns(dur_ns)
    return (t_ns - phase) // dur_ns * dur_ns + phase


class RetentionPolicy:
    def __init__(self, name: str, duration_ns: int = 0, shard_duration_ns: int = DEFAULT_SHARD_DURATION):
        self.name = name
        self.duration_ns = duration_ns  # 0 = infinite
        self.shard_duration_ns = shard_duration_ns

    def to_json(self):
        return {
            "name": self.name,
            "duration_ns": self.duration_ns,
            "shard_duration_ns": self.shard_duration_ns,
        }

    @classmethod
    def from_json(cls, j):
        return cls(j["name"], j["duration_ns"], j["shard_duration_ns"])


class ContinuousQuery:
    """A registered CQ (reference: meta data model continuous queries +
    services/continuousquery scheduler)."""

    def __init__(self, name: str, select_text: str, resample_every_ns: int = 0,
                 resample_for_ns: int = 0, last_run_ns: int = 0):
        self.name = name
        self.select_text = select_text
        self.resample_every_ns = resample_every_ns
        self.resample_for_ns = resample_for_ns
        self.last_run_ns = last_run_ns

    def to_json(self):
        return {
            "name": self.name,
            "select_text": self.select_text,
            "resample_every_ns": self.resample_every_ns,
            "resample_for_ns": self.resample_for_ns,
            "last_run_ns": self.last_run_ns,
        }

    @classmethod
    def from_json(cls, j):
        return cls(j["name"], j["select_text"], j.get("resample_every_ns", 0),
                   j.get("resample_for_ns", 0), j.get("last_run_ns", 0))


class DownsamplePolicy:
    """Shard-rewrite policy (reference: downsample policies in the meta data
    model, engine_downsample.go): shards older than `age_ns` are rewritten
    at `every_ns` resolution."""

    def __init__(self, age_ns: int, every_ns: int, field_aggs: dict | None = None):
        self.age_ns = age_ns
        self.every_ns = every_ns
        self.field_aggs = field_aggs or {}  # field type name -> agg name

    def to_json(self):
        return {"age_ns": self.age_ns, "every_ns": self.every_ns,
                "field_aggs": self.field_aggs}

    @classmethod
    def from_json(cls, j):
        return cls(j["age_ns"], j["every_ns"], j.get("field_aggs", {}))


class StreamTask:
    """At-ingest window aggregation task (reference: services/stream +
    app/ts-store/stream tag_task/time_task)."""

    def __init__(self, name: str, select_text: str, delay_ns: int = 0):
        self.name = name
        self.select_text = select_text
        self.delay_ns = delay_ns

    def to_json(self):
        return {"name": self.name, "select_text": self.select_text,
                "delay_ns": self.delay_ns}

    @classmethod
    def from_json(cls, j):
        return cls(j["name"], j["select_text"], j.get("delay_ns", 0))


class Database:
    def __init__(self, name: str):
        self.name = name
        self.rps: dict[str, RetentionPolicy] = {}
        self.default_rp = "autogen"
        self.continuous_queries: dict[str, ContinuousQuery] = {}
        # rp name -> [DownsamplePolicy]
        self.downsample: dict[str, list[DownsamplePolicy]] = {}
        self.streams: dict[str, StreamTask] = {}
        self.subscriptions: dict[str, object] = {}
        # declared materialized rollups (storage/rollup.RollupSpec):
        # maintained incrementally on ingest, spliced into eligible
        # GROUP BY time() plans by the executor
        self.rollups: dict[str, object] = {}
        # DROP MEASUREMENT is a mark + deferred purge (reference:
        # MarkMeasurementDelete, lifted/influx/coordinator/
        # statement_executor.go:894): queries hide marked measurements
        # immediately, SHOW SERIES keeps their series until the purge
        # actually runs (the reference black-box suite asserts this,
        # tests/server_test.go TestServer_Query_ShowSeries)
        self.dropped_msts: set[str] = set()


class WriteError(Exception):
    pass


class DatabaseNotFound(WriteError):
    def __init__(self, name: str):
        super().__init__(f"database not found: {name!r}")


class Engine:
    """Single-node storage engine with embedded metadata."""

    def __init__(
        self,
        root: str,
        sync_wal: bool = False,
        flush_threshold_bytes: int = 64 << 20,
        tag_arrays: bool = False,
    ):
        self.root = root
        self.sync_wal = sync_wal
        self.flush_threshold_bytes = flush_threshold_bytes
        # openGemini tag-array expansion (`host=[a,b]`), opt-in like the
        # reference's per-database enableTagArray — brackets are legal
        # literal tag bytes when off
        self.tag_arrays = tag_arrays
        os.makedirs(root, exist_ok=True)
        # hot class: every write/query path serializes through it, so a
        # blocking call here stalls the whole engine (lockdep-enforced;
        # threshold flushes already run outside it, PR 3)
        self._lock = lockdep.mark_hot(lockdep.RLock(), "engine._lock")
        # syscontrol toggles (reference: lib/syscontrol disable write/read)
        self.write_disabled = False
        self.read_disabled = False
        self._write_observers: list = []
        # object-storage tier (reference: lib/fileops obs): shard groups
        # offloaded to the store, hydrated back lazily on query
        self.obs_store = None
        self.obs_shards: set[tuple[str, str, int]] = set()
        self.databases: dict[str, Database] = {}
        # (db, rp, group_start) -> Shard
        self._shards: dict[tuple[str, str, int], Shard] = {}
        self._load_meta()
        self._models = None  # lazy ModelStore (castor)
        # inbound two-phase migrations: mig_id -> (db, rp, start, Shard);
        # staging shards are NEVER in _shards (invisible to queries)
        self._staging: dict[str, tuple] = {}
        # mig_ids whose commit fold is running RIGHT NOW (popped from
        # _staging, marker not yet durable): a retried commit racing the
        # fold must wait for the marker, not 400 "unknown migration"
        self._folding: set[str] = set()
        self._load_shards()
        # materialized-rollup manager (storage/rollup.py): constructed
        # only when a spec is declared AND OGT_ROLLUP != 0 — None keeps
        # every write/query path bit-identical (one attribute check)
        self.rollup_mgr = None
        self._maybe_init_rollups()
        # continuous rule engine (promql/rules.py): set by RuleManager
        # when OGT_RULES enables it — None keeps every write path
        # bit-identical (one attribute check, same contract as rollups)
        self.rules_hook = None
        # live acked-vs-durable gauges ride /debug/vars (utils/stats
        # provider; close() unregisters so dead engines drop out)
        self._durability_provider = self._durability_gauges
        STATS.register_provider("durability", self._durability_provider)
        # quarantined-file gauge (media-fault containment): current
        # count of files pulled from the read set, next to the
        # detection counters the shards increment
        self._quarantine_provider = self._quarantine_gauges
        STATS.register_provider("quarantine", self._quarantine_provider)
        # memtable+WAL backlog joins the resource governor's unified
        # memory ledger and drives the /write backpressure watermark
        # (utils/governor.py; multiple engines sum process-wide)
        from opengemini_tpu.utils.governor import GOVERNOR as _GOVERNOR

        self._governor_provider = self.mem_backlog_bytes
        _GOVERNOR.register_component("memtable", self._governor_provider)

    # -- metadata -----------------------------------------------------------

    @property
    def models(self):
        """Fitted anomaly-detection models (castor fit pipeline),
        persisted under <root>/models/."""
        if self._models is None:
            from opengemini_tpu.services.castor import ModelStore

            self._models = ModelStore(os.path.join(self.root, "models"))
        return self._models

    def _meta_path(self) -> str:
        return os.path.join(self.root, "meta.json")

    def _load_meta(self) -> None:
        p = self._meta_path()
        if not os.path.exists(p):
            return
        with open(p, encoding="utf-8") as f:
            j = json.load(f)
        for dbj in j.get("databases", []):
            db = Database(dbj["name"])
            db.default_rp = dbj.get("default_rp", "autogen")
            for rpj in dbj.get("rps", []):
                rp = RetentionPolicy.from_json(rpj)
                db.rps[rp.name] = rp
            for cqj in dbj.get("cqs", []):
                cq = ContinuousQuery.from_json(cqj)
                db.continuous_queries[cq.name] = cq
            for rp_name, pols in dbj.get("downsample", {}).items():
                db.downsample[rp_name] = [DownsamplePolicy.from_json(p) for p in pols]
            for sj in dbj.get("streams", []):
                st = StreamTask.from_json(sj)
                db.streams[st.name] = st
            from opengemini_tpu.services.subscriber import Subscription

            for sj in dbj.get("subscriptions", []):
                sub = Subscription.from_json(sj)
                db.subscriptions[sub.name] = sub
            db.dropped_msts = set(dbj.get("dropped_msts", []))
            if dbj.get("rollups"):
                from opengemini_tpu.storage.rollup import RollupSpec

                for rj in dbj["rollups"]:
                    spec = RollupSpec.from_json(rj)
                    db.rollups[spec.name] = spec
            self.databases[db.name] = db
        self.obs_shards = {
            (d, r, int(s)) for d, r, s in j.get("obs_shards", [])
        }

    def _save_meta(self) -> None:
        j = {
            "obs_shards": sorted(list(k) for k in self.obs_shards),
            "databases": [
                {
                    "name": db.name,
                    "default_rp": db.default_rp,
                    "rps": [rp.to_json() for rp in db.rps.values()],
                    "cqs": [cq.to_json() for cq in db.continuous_queries.values()],
                    "downsample": {
                        rp: [p.to_json() for p in pols]
                        for rp, pols in db.downsample.items()
                    },
                    "streams": [s.to_json() for s in db.streams.values()],
                    "subscriptions": [
                        s.to_json() for s in db.subscriptions.values()
                    ],
                    "dropped_msts": sorted(db.dropped_msts),
                    "rollups": [r.to_json() for r in db.rollups.values()],
                }
                for db in self.databases.values()
            ]
        }
        from opengemini_tpu.storage import diskfault

        tmp = self._meta_path() + ".tmp"
        if diskfault.armed():
            diskfault.check("write", self._meta_path(),
                            site="meta-save-write")
        # audited (lockdep): the meta fsync runs under the engine lock —
        # DDL is rare control-plane work, and the lock is what keeps the
        # in-memory mutation and its durable record atomic (a failed
        # save raises INSIDE the op; tests/test_diskfault.py pins that).
        # Unlike the PR 7 rollup-state fsync this is not a hot path.
        with lockdep.allow_blocking("engine meta save under DDL lock"), \
                open(tmp, "w", encoding="utf-8") as f:
            json.dump(j, f)
            f.flush()
            if diskfault.armed():
                diskfault.on_fsync(self._meta_path(),
                                   site="meta-save-fsync")
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())

    def create_database(self, name: str) -> None:
        _check_namespace_name(name, "database")
        with self._lock:
            if name in self.databases:
                return
            db = Database(name)
            db.rps["autogen"] = RetentionPolicy("autogen")
            self.databases[name] = db
            self._save_meta()

    def drop_database(self, name: str) -> None:
        import shutil

        obs_purge = []
        with self._lock:
            if name not in self.databases:
                return
            for key in [k for k in self._shards if k[0] == name]:
                shard = self._shards.pop(key)
                shard.close()
                _remove_shard_dir(shard.path)  # follows cold-tier symlinks
            obs_purge = self._purge_obs(lambda k: k[0] == name)
            del self.databases[name]
            self._save_meta()
            p = os.path.join(self.root, "data", name)
            if os.path.exists(p):
                shutil.rmtree(p)
            if self.rollup_mgr is not None:
                # a recreated database must not inherit this one's
                # rollup watermarks (stale-clean windows would splice
                # as empty over the new incarnation's data)
                self.rollup_mgr.drop_db_state(name)
            else:
                shutil.rmtree(os.path.join(self.root, "rollup", name),
                              ignore_errors=True)
            if self.rules_hook is not None:
                # same stale-state hazard for rule groups: a recreated
                # db must not inherit watermarks/alert state
                self.rules_hook.drop_db_state(name)
            else:
                shutil.rmtree(os.path.join(self.root, "rules", name),
                              ignore_errors=True)
        self._delete_obs_prefixes(obs_purge)

    def drop_retention_policy(self, db: str, name: str) -> None:
        obs_purge = []
        with self._lock:
            d = self.databases.get(db)
            if d and name in d.rps:
                del d.rps[name]
                d.downsample.pop(name, None)  # policies die with their rp
                for key in [k for k in self._shards
                            if k[0] == db and k[1] == name]:
                    shard = self._shards.pop(key)
                    shard.close()
                    _remove_shard_dir(shard.path)
                obs_purge = self._purge_obs(
                    lambda k: k[0] == db and k[1] == name)
                if d.default_rp == name:
                    d.default_rp = "autogen" if "autogen" in d.rps else next(
                        iter(d.rps), "autogen"
                    )
                self._save_meta()
        self._delete_obs_prefixes(obs_purge)

    def create_retention_policy(
        self, db: str, name: str, duration_ns: int, shard_duration_ns: int | None = None,
        default: bool = False,
    ) -> None:
        _check_namespace_name(name, "retention policy")
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            if not shard_duration_ns:  # absent or 0 = auto (influx meta)
                shard_duration_ns = _auto_shard_duration(duration_ns)
            d.rps[name] = RetentionPolicy(name, duration_ns, shard_duration_ns)
            if default:
                d.default_rp = name
            self._save_meta()

    def alter_retention_policy(
        self, db: str, name: str, duration_ns: int | None = None,
        shard_duration_ns: int | None = None, default: bool = False,
    ) -> None:
        """Mutate an existing RP in place; None fields stay as they are.
        New shard duration only affects shard groups created after the
        change, matching influx semantics."""
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            rp = d.rps.get(name)
            if rp is None:
                raise ValueError(f"retention policy not found: {name}")
            new_dur = rp.duration_ns if duration_ns is None else duration_ns
            if shard_duration_ns is None:
                new_sd = rp.shard_duration_ns
            else:  # explicit 0 = recompute the auto layout (influx meta)
                new_sd = shard_duration_ns or _auto_shard_duration(new_dur)
            if new_dur and new_dur < new_sd:
                # influx rejects this combination rather than silently
                # rewriting the shard layout (ErrIncompatibleDurations)
                raise ValueError(
                    "retention policy duration must be greater than the "
                    "shard duration")
            rp.duration_ns = new_dur
            rp.shard_duration_ns = new_sd
            if default:
                d.default_rp = name
            self._save_meta()

    def disk_usage(self) -> dict:
        """{"total": bytes, "groups": {"db|rp|start": bytes}} for live
        shard dirs — the load signal the balancer compares across nodes
        (reference: store load report feeding balance_manager.go)."""
        groups: dict[str, int] = {}
        total = 0
        with self._lock:
            items = list(self._shards.items())
        for (db, rp, start), sh in items:
            n = 0
            try:
                for dirpath, _dirs, files in os.walk(
                        os.path.realpath(sh.path)):
                    for f in files:
                        try:
                            n += os.path.getsize(os.path.join(dirpath, f))
                        except OSError:
                            pass
            except OSError:
                pass
            groups[f"{db}|{rp}|{start}"] = n
            total += n
        return {"total": total, "groups": groups}

    def database_names(self) -> list[str]:
        return sorted(self.databases)

    # -- shards -------------------------------------------------------------

    def _shard_dir(self, db: str, rp: str, group_start: int) -> str:
        return os.path.join(self.root, "data", db, rp, str(group_start))

    def _load_shards(self) -> None:
        data_dir = os.path.join(self.root, "data")
        if not os.path.isdir(data_dir):
            return
        for db in os.listdir(data_dir):
            for rp in os.listdir(os.path.join(data_dir, db)):
                rp_obj = self.databases.get(db)
                rp_meta = rp_obj.rps.get(rp) if rp_obj else None
                dur = rp_meta.shard_duration_ns if rp_meta else DEFAULT_SHARD_DURATION
                for g in os.listdir(os.path.join(data_dir, db, rp)):
                    start = int(g)
                    self._shards[(db, rp, start)] = Shard(
                        self._shard_dir(db, rp, start), start, start + dur,
                        self.sync_wal, tag_arrays=self.tag_arrays,
                    )

    def _get_or_create_shard(self, db: str, rp: str, t_ns: int) -> Shard:
        d = self.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        rp_meta = d.rps.get(rp)
        if rp_meta is None:
            raise WriteError(f"retention policy not found: {db}.{rp}")
        dur = rp_meta.shard_duration_ns
        group_start = shard_group_start(t_ns, dur)
        key = (db, rp, group_start)
        shard = self._shards.get(key)
        if shard is None:
            if key in self.obs_shards:
                # writes into an offloaded range must land in the HYDRATED
                # group — a fresh empty shard here would later be clobbered
                # by hydration and the writes silently lost
                shard = self._hydrate_shard(db, rp, group_start)
                if shard is not None:
                    return shard
            shard = Shard(
                self._shard_dir(db, rp, group_start),
                group_start,
                group_start + dur,
                self.sync_wal,
                tag_arrays=self.tag_arrays,
            )
            self._shards[key] = shard
        return shard

    # -- DROP MEASUREMENT: mark + deferred purge ----------------------------

    def mark_measurement_delete(self, db: str, mst: str) -> None:
        """The reference's MarkMeasurementDelete: DROP MEASUREMENT only
        marks; SELECT/SHOW MEASUREMENTS hide it immediately, the data and
        its index entries survive until purge_dropped_measurements runs
        (retention tick, or synchronously before a new write to the name)."""
        d = self.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        to_reset = []
        with self._lock:
            d.dropped_msts.add(mst)
            if self.rollup_mgr is not None:
                # rollups of a dropped measurement drop WITH it: delete
                # their target rows (scoped to the _rollup RP — the
                # db-wide dropped_msts mark would collide with a raw
                # measurement of the same name) and reset the watermark
                # so a recreated name re-folds from scratch
                for spec in d.rollups.values():
                    if spec.measurement == mst:
                        self._purge_rollup_target(db, spec.target)
                        to_reset.append(spec.name)
            self._save_meta()
        for name in to_reset:
            # outside the engine lock: invalidation serializes against
            # in-flight maintenance (st.m_lock), which itself takes
            # engine locks while folding — lock order maintenance-lock
            # before engine lock, never the reverse
            self.rollup_mgr.invalidate(db, name)

    def is_measurement_dropped(self, db: str, mst: str) -> bool:
        d = self.databases.get(db)
        return d is not None and mst in d.dropped_msts

    def purge_dropped_measurements(self, db: str | None = None) -> int:
        """Physically delete mark-dropped measurements. Returns the number
        purged. Driven by the retention service; also runs synchronously
        before writes to a database with pending marks so old rows cannot
        resurface under a recreated measurement name."""
        n = 0
        with self._lock:
            for name, d in self.databases.items():
                if db is not None and name != db:
                    continue
                if not d.dropped_msts:
                    continue
                # offloaded (object-store) groups hold data too: hydrate
                # them first or the purge misses rows that would resurface
                # on the next query-driven hydration
                for (sdb, rp, g) in sorted(self.obs_shards):
                    if sdb == name:
                        self._hydrate_shard(sdb, rp, g)
                for mst in sorted(d.dropped_msts):
                    for (sdb, _rp, _g), sh in list(self._shards.items()):
                        if sdb == name:
                            sh.delete_data(mst)
                    n += 1
                d.dropped_msts.clear()
            if n:
                self._save_meta()
        return n

    def attach_object_store(self, store) -> None:
        self.obs_store = store
        # reconcile a crash between offload's registry save and the local
        # removal: a group present BOTH locally and in the registry keeps
        # the local copy (same or newer) and drops the stale store copy
        from opengemini_tpu.storage.objstore import shard_prefix

        with self._lock:
            stale = [k for k in self.obs_shards if k in self._shards]
        # bucket deletes are HTTP round trips: outside the engine lock
        # (lockdep), like drop_expired_shards
        for db, rp, start in stale:
            store.delete_prefix(shard_prefix(db, rp, start))
        with self._lock:
            for k in stale:
                self.obs_shards.discard(k)
            if stale:
                self._save_meta()

    def offload_shard(self, db: str, rp: str, group_start: int) -> bool:
        """Move one whole shard group into the object store (reference:
        the obs cold tier). Readers holding fds keep working (files are
        unlinked, not truncated); the group hydrates back on next query."""
        from opengemini_tpu.storage.objstore import shard_prefix

        if self.obs_store is None:
            return False
        import shutil as _shutil

        key = (db, rp, group_start)
        with self._lock:
            shard = self._shards.get(key)
            if shard is None:
                return False
        # UPLOAD PHASE — network IO under the SHARD's flush lock only
        # (lockdep caught the old shape: the whole upload ran under
        # engine._lock, stalling every write/query in the process behind
        # one shard's bucket transfer).  _flush_lock freezes the FILE
        # SET — flush/compact/delete/downsample all take it first —
        # while writes stay live; a write landing mid-upload bumps
        # data_version and the swap below aborts, leaving the shard
        # local (the obstier tick retries; attach reconcile prefers
        # local over any orphaned bucket objects).
        with shard._flush_lock:
            shard.flush()
            with shard._lock:
                v0 = shard.data_version
            if shard.mem_backlog_bytes() != 0:
                return False  # raced a write mid-flush: not idle
            prefix = shard_prefix(db, rp, group_start)
            # clear the prefix FIRST: an earlier aborted/crashed upload
            # (swap lost to a mid-upload write) left orphan objects
            # here, and uploading a since-compacted file set OVER them
            # would make a later hydration re-download retired files —
            # resurrecting deleted rows.  The registry never points
            # here until the swap below succeeds, so the delete races
            # no reader.
            self.obs_store.delete_prefix(prefix)
            # follow a cold-tier symlink: files live at the target;
            # recurse so the seriesidx/ mergeset dir travels too
            real = os.path.realpath(shard.path)
            for dirpath, _dirs, files in os.walk(real):
                for fname in sorted(files):
                    full = os.path.join(dirpath, fname)
                    rel = os.path.relpath(full, real)
                    self.obs_store.put(f"{prefix}/{rel}", full)
        # SWAP PHASE — revalidate + retire under the engine lock (no
        # shard lock held on entry: engine -> shard order preserved)
        with self._lock:
            if self._shards.get(key) is not shard:
                return False  # dropped/replaced mid-upload
            with shard._lock:
                dirty = (shard.data_version != v0
                         or shard.mem_backlog_bytes() != 0)
            if dirty:
                return False  # rows landed mid-upload: bucket copy is
                # stale — keep serving local, next tick re-offloads
            # audited (lockdep): retiring an idle fully-synced shard —
            # the close fsyncs are cheap no-ops here and the engine
            # lock is what makes the registry swap atomic
            with lockdep.allow_blocking("cold-tier retire of idle shard"), \
                    shard._flush_lock, shard._lock:
                shard.wal.close()
                shard.index.close()
                # cold-tier offload retires the local files: release the
                # shard's decoded-column cache entries (colcache)
                shard.drop_cached_columns()
            del self._shards[key]
            # registry FIRST: a crash before the local removal leaves both
            # copies (attach_object_store reconciles, preferring local); the
            # reverse order would strand the data in the bucket unreferenced
            self.obs_shards.add(key)
            self._save_meta()
            _remove_shard_dir(shard.path)  # follows cold-tier symlinks
            return True

    # -- two-phase migration staging (reference engine_ha.go Pre*/Rollback) --

    def _staging_root(self) -> str:
        return os.path.join(self.root, "staging")

    def begin_staging(self, db: str, rp: str, group_start: int,
                      mig_id: str) -> None:
        """PreAssign: open an INVISIBLE staging shard for an inbound
        migration (never in self._shards, so queries cannot see half-
        migrated rows). Idempotent — a retried begin reuses the dir."""
        if not mig_id or "/" in mig_id or mig_id.startswith("."):
            raise WriteError(f"bad migration id {mig_id!r}")
        d = self.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        rp_meta = d.rps.get(rp or d.default_rp)
        if rp_meta is None:
            raise WriteError(f"retention policy not found: {db}.{rp}")
        with self._lock:
            if mig_id in self._staging:
                return
            path = os.path.join(self._staging_root(), mig_id)
            dur = rp_meta.shard_duration_ns
            sh = Shard(path, group_start, group_start + dur,
                       self.sync_wal, tag_arrays=self.tag_arrays)
            self._staging[mig_id] = [db, rp or d.default_rp, group_start, sh,
                                     _time.perf_counter()]

    def write_staging(self, mig_id: str, points: list) -> int:
        with self._lock:
            got = self._staging.get(mig_id)
            if got is None:
                raise WriteError(f"unknown migration {mig_id!r}")
            got[4] = _time.perf_counter()  # idle clock, NOT dir mtime: WAL
            # appends never touch the directory timestamp
            sh = got[3]
            n, ticket = sh.write_points_structured(points,
                                                   defer_commit=True)
        # the sync-WAL fsync waits OUTSIDE the engine lock (the deferred-
        # commit discipline of the main write paths, PR 3; caught here by
        # lockdep) — migration staging ingest must not serialize the
        # whole destination engine behind its disk.  A TTL expiry racing
        # the released lock closes the staging WAL with _synced caught
        # up, so commit() returns instantly rather than livelocking.
        sh.wal.commit(ticket)
        return n

    def commit_staging(self, mig_id: str) -> int:
        """Assign: fold the staged rows into the LIVE shard (LWW-idempotent
        structured writes) and discard the staging area. Returns rows.

        IDEMPOTENT: a durable committed-marker is written after the fold,
        so a re-commit of the same mig_id — the pusher retrying because
        the first commit's ACK was lost in transit — answers ok instead
        of failing the pusher into aborting (and re-streaming) a move
        that already completed.  A retry that re-staged rows first (full
        begin/write/commit replay) re-folds them; the structured write
        path is last-write-wins on (series, timestamp), so the fold can
        never duplicate rows."""
        with self._lock:
            got = self._staging.pop(mig_id, None)
            if got is not None:
                self._folding.add(mig_id)
        if got is None:
            # a retried commit can arrive while the FIRST commit is
            # still folding (its RPC timed out client-side, the work
            # did not): wait out the fold, then answer from the marker
            while True:
                with self._lock:
                    inflight = mig_id in self._folding
                if not inflight:
                    break
                _time.sleep(0.05)
            if os.path.exists(self._committed_marker(mig_id)):
                return 0  # already folded; the previous ack was lost
            raise WriteError(f"unknown migration {mig_id!r}")
        try:
            db, rp, _start, sh, _ts = got
            from opengemini_tpu.storage.shard import iter_structured_batches

            rows = 0
            for batch in iter_structured_batches(sh, 20_000):
                rows += self.write_rows(db, batch, rp=rp)
            # a crash HERE (fold durable via WAL, marker absent) is safe:
            # the pusher's retry re-stages + re-folds, LWW dedups
            _fp("engine-staging-commit-before-marker")
            self._write_committed_marker(mig_id, rows)
            self._discard_staging_dir(sh)
        finally:
            with self._lock:
                self._folding.discard(mig_id)
        return rows

    def _committed_marker(self, mig_id: str) -> str:
        return os.path.join(self._staging_root(), mig_id + ".committed")

    def _write_committed_marker(self, mig_id: str, rows: int) -> None:
        """Durable (fsynced, atomic-rename) record that `mig_id` folded:
        the commit-idempotence token, TTL-expired with the staging dirs."""
        os.makedirs(self._staging_root(), exist_ok=True)
        path = self._committed_marker(mig_id)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            # wall-clock record: operator forensics metadata only (the
            # TTL reaper ages markers by file mtime, never this field)
            f.write(json.dumps(
                {"rows": rows, "ts": _time.time()}))  # ogtlint: disable=OGT040
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def staging_ids(self) -> list[str]:
        """In-flight migration staging ids, snapshotted under the engine
        lock (introspection must not race a concurrent begin/commit)."""
        with self._lock:
            return sorted(self._staging)

    def abort_staging(self, mig_id: str) -> bool:
        """Rollback: drop the staging area; live data was never touched."""
        with self._lock:
            got = self._staging.pop(mig_id, None)
        if got is None:
            return False
        self._discard_staging_dir(got[3])
        return True

    def close_staging(self) -> None:
        with self._lock:
            for entry in self._staging.values():
                entry[3].close()
            self._staging.clear()

    def _discard_staging_dir(self, sh) -> None:
        import shutil

        path = sh.path
        sh.close()
        shutil.rmtree(path, ignore_errors=True)

    def expire_staging(self, ttl_s: float = 900.0) -> int:
        """Janitor half of the rollback story: a pusher that died
        mid-stream leaves a staging dir behind; anything older than the
        TTL is discarded — live data is untouched by construction, so
        expiry IS the rollback (reference: the migrate state machine's
        recovery + Rollback RPCs, engine_ha.go:33-258)."""
        import shutil
        import time as _t

        root = self._staging_root()
        if not os.path.isdir(root):
            return 0
        # two clocks: active registrations idle out on the in-process
        # duration clock; orphan DIRS compare against file mtimes, which
        # only the wall clock can be compared to
        now_pc = _t.perf_counter()
        now = _t.time()  # ogtlint: disable=OGT040
        dropped = 0
        with self._lock:
            # ACTIVE registrations expire on IDLE time (last write seen;
            # an in-progress stream keeps refreshing it, so a long
            # migration never self-destructs mid-flight)
            for name, entry in list(self._staging.items()):
                if now_pc - entry[4] >= ttl_s:
                    self._staging.pop(name, None)
                    self._discard_staging_dir(entry[3])
                    dropped += 1
            # ORPHAN dirs (no in-memory entry — e.g. this node restarted
            # mid-migration) expire by their newest content mtime;
            # committed-markers (commit-idempotence tokens) age out the
            # same way once no pusher can still be retrying that commit
            for name in os.listdir(root):
                if name in self._staging or name in self._folding:
                    # a fold in flight is NOT an orphan: its commit
                    # popped the registration but is still reading the
                    # dir (the lock is not held across the fold)
                    continue
                path = os.path.join(root, name)
                if name.endswith(".committed") and os.path.isfile(path):
                    try:
                        if now - os.path.getmtime(path) >= ttl_s:
                            os.remove(path)
                    except OSError:
                        pass
                    continue
                try:
                    newest = max(
                        (os.path.getmtime(os.path.join(path, f))
                         for f in os.listdir(path)),
                        default=os.path.getmtime(path))
                except OSError:
                    continue
                if now - newest < ttl_s:
                    continue
                shutil.rmtree(path, ignore_errors=True)
                dropped += 1
        return dropped

    def drop_shard(self, db: str, rp: str, group_start: int) -> bool:
        """Remove one local shard group entirely (post-migration cleanup:
        the data now lives on its new rendezvous owners). Unlike the
        cold-tier offload above, nothing is registered — ownership moved
        away (reference: migrate_state_machine.go segment cleanup)."""
        key = (db, rp, group_start)
        with self._lock:
            shard = self._shards.pop(key, None)
            if shard is None:
                return False
            shard.close()
            obs_purge = self._purge_obs(lambda k: k == key)
            self._save_meta()
            _remove_shard_dir(shard.path)
        self._delete_obs_prefixes(obs_purge)
        return True

    def _purge_obs(self, match) -> list[str]:
        """Drop offloaded-group registry entries whose key satisfies
        `match` — DROP DATABASE/RP must not let a recreated namespace
        resurrect old offloaded data.  Caller holds the lock and saves
        meta; the returned bucket prefixes must be fed to
        _delete_obs_prefixes AFTER the lock is released (lockdep: the
        deletes are HTTP round trips).  Registry-first ordering means a
        crash mid-delete leaves unreferenced orphan objects (a leak the
        operator can sweep), never a registry entry pointing at a
        half-deleted group (which would fail every later hydration)."""
        from opengemini_tpu.storage.objstore import shard_prefix

        purged = []
        for key in [k for k in self.obs_shards if match(k)]:
            if self.obs_store is not None:
                purged.append((key, shard_prefix(*key)))
            self.obs_shards.discard(key)
        return purged

    def _delete_obs_prefixes(self, purged: list[tuple]) -> None:
        """Bucket-object deletes for _purge_obs — call with NO engine
        lock held.  Each delete RE-CHECKS the registry first: between
        the purge and this call the namespace may have been recreated
        and a fresh offload registered the SAME deterministic prefix —
        deleting it then would erase the only remaining copy of live
        data (the local files are gone after a successful offload)."""
        for key, prefix in purged:
            with self._lock:
                if key in self.obs_shards or key in self._shards:
                    continue  # the prefix belongs to a live incarnation
            if self.obs_store is not None:
                self.obs_store.delete_prefix(prefix)

    def _download_group(self, db: str, rp: str, group_start: int) -> None:
        """Pull an offloaded group's files into its shard dir. NO engine
        lock held — with a real bucket this is seconds of network I/O and
        must not stall every other query/write.

        Downloads land in a staging dir OUTSIDE data/ and swap in whole:
        a crash or torn download must never leave a partial dir that
        _load_shards would install as a live shard (the reconcile in
        attach_object_store would then delete the bucket copy — data
        loss from a half-hydrated shard)."""
        from opengemini_tpu.storage.objstore import shard_prefix

        prefix = shard_prefix(db, rp, group_start)
        keys = self.obs_store.list(prefix)
        if not keys:
            raise WriteError(
                f"offloaded group {db}/{rp}/{group_start} has no objects "
                "in the bucket")
        import uuid

        # unique per-attempt staging dir: two concurrent hydrations of
        # the same group must not clobber each other's downloads
        tmp = os.path.join(self.root, ".hydrate-tmp",
                           f"{db}_{rp}_{group_start}.{uuid.uuid4().hex[:8]}")
        try:
            for key in keys:
                rel = key[len(prefix) + 1 :]  # may be nested (seriesidx/)
                target = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(target), exist_ok=True)
                self.obs_store.get(key, target)
            dest = self._shard_dir(db, rp, group_start)
            # swap under the engine lock: the loser of a concurrent
            # hydration discards its copy instead of replacing a dir the
            # winner may already have OPEN as a live shard
            with self._lock:
                if (db, rp, group_start) in self._shards:
                    shutil.rmtree(tmp, ignore_errors=True)
                    return
                shutil.rmtree(dest, ignore_errors=True)
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                os.replace(tmp, dest)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def _install_hydrated(self, db: str, rp: str, group_start: int,
                          save: bool = True) -> "Shard":
        """Open a downloaded group and register it (caller holds the
        lock). Idempotent: an already-live shard is returned untouched —
        never clobbered. The store copy is kept for future re-offload."""
        key = (db, rp, group_start)
        existing = self._shards.get(key)
        if existing is not None:
            self.obs_shards.discard(key)
            return existing
        d = self.databases[db]
        dur = d.rps[rp].shard_duration_ns
        shard = Shard(self._shard_dir(db, rp, group_start), group_start,
                      group_start + dur, self.sync_wal,
                      tag_arrays=self.tag_arrays)
        self._shards[key] = shard
        self.obs_shards.discard(key)
        if save:
            self._save_meta()
        return shard

    def _hydrate_shard(self, db: str, rp: str, group_start: int) -> "Shard | None":
        """Download + install in one step (write path; caller holds the
        engine lock — rare enough that blocking is acceptable there)."""
        if self.obs_store is None:
            return None
        if (db, rp, group_start) in self._shards:
            return self._install_hydrated(db, rp, group_start)
        # audited (lockdep): a backfill write into an aged-out cold
        # group downloads it under the engine lock by documented design
        # — rare, and routing is mid-flight; the QUERY path hydrates
        # outside the lock (shards_for_range)
        with lockdep.allow_blocking("write-path cold hydration"):
            self._download_group(db, rp, group_start)
        return self._install_hydrated(db, rp, group_start)

    def shards_for_range(self, db: str, rp: str | None, tmin: int, tmax: int) -> list[Shard]:
        """Shards overlapping [tmin, tmax) — the shard-mapping step
        (reference coordinator/shard_mapper.go:61 MapShards). Offloaded
        (object-store) groups in range hydrate back first."""
        d = self.databases.get(db)
        if d is None:
            return []
        rp = rp or d.default_rp
        if self.obs_shards and self.obs_store is not None:
            with self._lock:
                rp_meta = d.rps.get(rp)
                dur = rp_meta.shard_duration_ns if rp_meta else 0
                todo = [
                    k for k in sorted(self.obs_shards)
                    if k[0] == db and k[1] == rp and dur
                    and k[2] + dur > tmin and k[2] < tmax
                ]
            for odb, orp, start in todo:
                try:
                    # download OUTSIDE the lock (bucket I/O must not stall
                    # unrelated queries/writes), install under it
                    if (odb, orp, start) not in self._shards:
                        self._download_group(odb, orp, start)
                    with self._lock:
                        self._install_hydrated(odb, orp, start, save=False)
                except Exception as e:  # noqa: BLE001
                    import logging

                    logging.getLogger("opengemini_tpu.engine").exception(
                        "hydration of %s/%s/%d failed", odb, orp, start
                    )
                    # fail LOUDLY: silently answering without the
                    # offloaded shard would return incomplete results
                    raise WriteError(
                        f"shard {odb}/{orp}/{start} is in the object "
                        f"store and could not be hydrated: {e}") from e
            if todo:
                with self._lock:
                    self._save_meta()
        out = []
        for (sdb, srp, _start), shard in sorted(self._shards.items()):
            if sdb == db and srp == rp and shard.tmin < tmax and shard.tmax > tmin:
                out.append(shard)
        return out

    def all_shards(self) -> list[Shard]:
        return list(self._shards.values())

    def shards_of_db(self, db: str) -> list[Shard]:
        """Every shard of a database across ALL retention policies."""
        return [sh for (sdb, _rp, _s), sh in sorted(self._shards.items()) if sdb == db]

    # -- write path ---------------------------------------------------------

    def write_lines(
        self,
        db: str,
        lines: str | bytes,
        precision: str = "ns",
        rp: str | None = None,
        now_ns: int | None = None,
    ) -> int:
        """Parse + route + apply a line-protocol batch
        (reference write path, SURVEY.md §3.1). Returns points written."""
        if self.write_disabled:
            raise WriteError("writes are disabled (syscontrol)")
        d = self.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        if d.dropped_msts:
            # a marked measurement being rewritten must not resurface its
            # old rows: purge before accepting the batch
            self.purge_dropped_measurements(db)
        rp = rp or d.default_rp
        if now_ns is None:
            now_ns = _time.time_ns()
        raw = lines.encode("utf-8") if isinstance(lines, str) else lines

        # fast path: native columnar parse -> slab writes (reference:
        # pooled VM protoparser feeding the record writer). Falls back to
        # the exact Python parser when the batch uses escapes or the
        # library is absent.
        from opengemini_tpu.ingest import native_lp

        batch = None
        if not (self.tag_arrays and b"=[" in raw):
            # tag-array batches take the exact Python parser (expansion)
            # large bodies fan the native parse out across cores — the C
            # call releases the GIL (reference:
            # httpd/handler.go:1633 influx.ScheduleUnmarshalWork pool)
            n = self._write_segmented(db, rp, raw, precision, now_ns)
            if n is _NEEDS_PYTHON_PARSER:
                pass  # segments already proved native can't parse this
            elif n is not None:
                return n
            else:
                batch = native_lp.parse_columnar(raw, precision, now_ns)
        if batch is not None:
            if len(batch) == 0:
                return 0
            STATS.incr("write", "points", len(batch))
            rtok = None
            if self.rollup_mgr is not None:
                # PRE-apply: a late write's dirty mark is durable before
                # the rows are (storage/rollup.py watermark contract);
                # write_done releases the in-flight fold floor
                rtok = self.rollup_mgr.note_write_columnar(db, rp, batch)
            utok = None
            if self.rules_hook is not None:
                utok = self.rules_hook.note_write_columnar(db, rp, batch)
            try:
                tickets: list = []
                touched: list = []
                with self._lock:
                    n = self._write_columnar_locked(
                        db, rp, batch, raw, precision, now_ns, tickets,
                        touched)
                self._commit_wal_tickets(tickets)
                self._flush_over_threshold(touched)
                if self._write_observers:
                    self._notify_write(db, rp, batch.to_points())
                return n
            finally:
                if rtok is not None:
                    self.rollup_mgr.write_done(rtok)
                if utok is not None:
                    self.rules_hook.write_done(utok)

        points = lp.parse_lines(lines, precision, now_ns,
                                expand_tag_arrays=self.tag_arrays)
        if not points:
            return 0
        STATS.incr("write", "points", len(points))
        rtok = None
        if self.rollup_mgr is not None:
            rtok = self.rollup_mgr.note_write_points(db, rp, points)
        utok = None
        if self.rules_hook is not None:
            utok = self.rules_hook.note_write_points(db, rp, points)
        try:
            tickets: list = []
            with self._lock:
                # group points by target shard (time routing)
                by_shard: dict[int, list] = {}
                shards: dict[int, Shard] = {}
                for p in points:
                    shard = self._get_or_create_shard(db, rp, p[2])
                    key = id(shard)
                    shards[key] = shard
                    by_shard.setdefault(key, []).append(p)
                n = 0
                for key, pts in by_shard.items():
                    got, t = shards[key].write_points(
                        pts, raw, precision, now_ns, defer_commit=True)
                    n += got
                    tickets.append((shards[key], t))
            self._commit_wal_tickets(tickets)  # fsyncs coalesce off-lock
            self._flush_over_threshold(shards.values())
            self._notify_write(db, rp, points)
            return n
        finally:
            if rtok is not None:
                self.rollup_mgr.write_done(rtok)
            if utok is not None:
                self.rules_hook.write_done(utok)

    def _write_segmented(self, db: str, rp: str, raw: bytes,
                         precision: str, now_ns: int):
        """Multi-core ingest: split a large body at line boundaries, parse
        the segments concurrently (the native parser releases the GIL),
        then apply in order. Returns None when the body is small or the
        pool is unavailable (caller takes the single-batch path), or the
        _NEEDS_PYTHON_PARSER sentinel when a segment proved the body
        needs the exact Python parser. Reference:
        lib/util/lifted/influx/httpd/handler.go:1633
        (influx.ScheduleUnmarshalWork worker pool)."""
        from opengemini_tpu.ingest import native_lp
        from opengemini_tpu.ingest.line_protocol import ParseError

        pool = _ingest_pool()
        if pool is None or len(raw) < 2 * _INGEST_SEGMENT_BYTES:
            return None
        if native_lp.load() is None:
            return None
        segs = _split_lp_segments(raw, _INGEST_WORKERS)
        if len(segs) < 2:
            return None
        errs: list = []

        def parse_one(idx_seg):
            idx, seg = idx_seg
            try:
                return native_lp.parse_columnar(seg, precision, now_ns)
            except ParseError as e:
                errs.append((idx, e))
                return None
        parsed = list(pool.map(parse_one, enumerate(segs)))
        if errs:
            # report the FIRST bad line of the body, not whichever worker
            # thread finished first
            idx, e = min(errs)
            off = sum(s.count(b"\n") for s in segs[:idx])
            raise ParseError(off + e.lineno, e.msg)
        if any(b is None for b in parsed):
            return _NEEDS_PYTHON_PARSER  # escapes etc.
        # cross-segment field-type check BEFORE applying anything: the
        # single-batch path rejects an internally-conflicting body with
        # nothing persisted; segments must not differ
        body_types: dict[tuple[str, str], object] = {}
        for batch in parsed:
            for mst_id, name, ftype, _values, valid in batch.cols:
                if not valid.any():
                    continue
                key = (batch.measurements[mst_id], name)
                have = body_types.get(key)
                if have is None:
                    body_types[key] = ftype
                elif have != ftype:
                    raise FieldTypeConflict(name, have, ftype)
        total = 0
        rtoks = []
        utoks = []
        try:
            if self.rollup_mgr is not None:
                # inside the try: a note hook failing for batch k must
                # still release batches <k's in-flight floors via the
                # finally, or the watermark stalls forever
                for batch in parsed:
                    if len(batch):
                        t = self.rollup_mgr.note_write_columnar(
                            db, rp, batch)
                        if t is not None:
                            rtoks.append(t)
            if self.rules_hook is not None:
                for batch in parsed:
                    if len(batch):
                        t = self.rules_hook.note_write_columnar(
                            db, rp, batch)
                        if t is not None:
                            utoks.append(t)
            with self._lock:
                # ONE lock acquisition for the whole body, with every
                # segment pre-validated against the LIVE shard schemas
                # before the first applies: the old per-segment lock
                # dance let a mid-batch schema conflict (or a racing
                # writer) leave a partial write the single-batch path can
                # never produce.  Routing runs ONCE per segment and is
                # reused for the apply.
                routed = []
                for seg, batch in zip(segs, parsed):
                    if len(batch) == 0:
                        continue
                    route = list(self._route_columnar_locked(db, rp, batch))
                    for shard, rows in route:
                        shard._check_columnar_types(batch, rows)
                    routed.append((seg, batch, route))
                tickets: list = []
                touched: list = []
                for seg, batch, route in routed:
                    STATS.incr("write", "points", len(batch))
                    for shard, rows in route:
                        got, t = shard.write_columnar(
                            batch, rows, seg, precision, now_ns,
                            defer_commit=True)
                        total += got
                        tickets.append((shard, t))
                        touched.append(shard)
            self._commit_wal_tickets(tickets)  # fsyncs coalesce off-lock
            self._flush_over_threshold(touched)
            if self._write_observers and total:
                # observers see the body ONCE, post-commit, like
                # write_lines
                pts: list = []
                for batch in parsed:
                    if len(batch):
                        pts.extend(batch.to_points())
                self._notify_write(db, rp, pts)
            return total
        finally:
            for t in rtoks:
                self.rollup_mgr.write_done(t)
            for t in utoks:
                self.rules_hook.write_done(t)

    def _route_columnar_locked(self, db: str, rp: str, batch):
        """Yield (shard, rows) for a ColumnarBatch — ONE routing
        implementation (vectorized Go-Truncate alignment) shared by
        pre-validation and apply, so a segmented body is checked against
        exactly the shards it will write to. Caller holds the engine
        lock. Target shards are created here if missing (a body rejected
        by pre-validation can leave empty shards behind — the same
        behavior as the point write path, which also creates shards
        before type checks)."""
        import numpy as np

        d = self.databases.get(db)
        if d is None:
            # a concurrent DROP DATABASE can land between segments of a
            # segmented body (the lock is per body, drops take it too)
            raise DatabaseNotFound(db)
        rp_meta = d.rps.get(rp)
        if rp_meta is None:
            raise WriteError(f"retention policy not found: {db}.{rp}")
        dur = rp_meta.shard_duration_ns
        phase = _go_phase_ns(dur)
        groups = (batch.ts - phase) // dur * dur + phase
        uniq = np.unique(groups)
        for g in uniq:
            shard = self._get_or_create_shard(db, rp, int(g))
            rows = None if len(uniq) == 1 else np.flatnonzero(groups == g)
            yield shard, rows

    @staticmethod
    def _commit_wal_tickets(tickets) -> None:
        """Finish deferred sync-WAL commits AFTER the engine lock drops:
        concurrent request threads pile onto the WAL's group commit and
        share fsyncs instead of serializing them under the engine lock
        (no-ops instantly when sync is off or a flush already made the
        entries durable)."""
        # lock handoff: engine lock dropped, rows applied, ack pending on
        # the group-commit fsync — a kill here must never lose a row that
        # a caller was told about (the ack happens after this returns)
        _fp("engine-before-wal-commit")
        for shard, ticket in tickets:
            shard.wal.commit(ticket)

    def _flush_over_threshold(self, shards) -> None:
        """Threshold flushes AFTER the engine lock drops: the off-lock
        flush (snapshot-and-swap, storage/shard.py) would otherwise run
        its whole encode+write+fsync while holding the engine lock and
        stall every other writer for the flush duration.  flush_if_over
        re-checks the size under the shard's flush lock (and skips when
        a flush is already in flight), so concurrent writers that all
        saw the same over-threshold memtable trigger ONE flush.  A shard
        dropped/offloaded between the lock release and here fails its
        flush benignly (drop discarded the data on purpose) — re-raise
        only if the shard is still registered."""
        _fp("engine-before-threshold-flush")  # engine lock released
        self._flush_tolerating_drop(
            shards, lambda sh: sh.flush_if_over(self.flush_threshold_bytes))

    def _flush_tolerating_drop(self, shards, flush_fn) -> None:
        """Flush each distinct shard OFF the engine lock, swallowing a
        failure ONLY when a concurrent DROP removed the shard mid-flush
        (its data is gone by design) — a live shard's flush failure
        re-raises.  Shared by the threshold path and flush_all."""
        seen: set[int] = set()
        for shard in shards:
            if id(shard) in seen:
                continue
            seen.add(id(shard))
            try:
                flush_fn(shard)
            except Exception:  # noqa: BLE001 — see docstring
                with self._lock:
                    alive = any(s is shard for s in self._shards.values())
                if alive:
                    raise

    def _write_columnar_locked(self, db: str, rp: str, batch,
                               raw: bytes, precision: str, now_ns: int,
                               tickets: list, touched: list) -> int:
        """Route a ColumnarBatch to its time shards (vectorized: one
        floor-divide over all timestamps) and slab-write each. Caller
        holds the engine lock; deferred WAL commits append to `tickets`
        and written shards to `touched` for the caller to finish
        (commit + threshold flush) off-lock."""
        n = 0
        for shard, rows in self._route_columnar_locked(db, rp, batch):
            got, t = shard.write_columnar(
                batch, rows, raw, precision, now_ns, defer_commit=True)
            n += got
            tickets.append((shard, t))
            touched.append(shard)
        return n

    # -- continuous queries / downsample ----------------------------------

    def create_continuous_query(self, db: str, cq: "ContinuousQuery") -> None:
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            d.continuous_queries[cq.name] = cq
            self._save_meta()

    def drop_continuous_query(self, db: str, name: str) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d and name in d.continuous_queries:
                del d.continuous_queries[name]
                self._save_meta()

    def save_cq_state(self) -> None:
        with self._lock:
            self._save_meta()

    def create_subscription(self, db: str, sub) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            d.subscriptions[sub.name] = sub
            self._save_meta()

    def drop_subscription(self, db: str, name: str) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d and name in d.subscriptions:
                del d.subscriptions[name]
                self._save_meta()

    def create_stream(self, db: str, task: "StreamTask") -> None:
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            d.streams[task.name] = task
            self._save_meta()

    def drop_stream(self, db: str, name: str) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d and name in d.streams:
                del d.streams[name]
                self._save_meta()

    # -- materialized rollups (storage/rollup.py) --------------------------

    def _maybe_init_rollups(self) -> None:
        from opengemini_tpu.storage import rollup as _rollup

        if (self.rollup_mgr is None and _rollup.enabled_by_env()
                and any(d.rollups for d in self.databases.values())):
            self.rollup_mgr = _rollup.RollupManager(self)

    def create_rollup(self, db: str, spec) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            src_rp = spec.rp or d.default_rp
            if src_rp not in d.rps:
                raise WriteError(f"retention policy not found: {db}.{src_rp}")
            _check_namespace_name(spec.name, "rollup")
            if spec.name == spec.measurement:
                # the spec name doubles as the target measurement AND as
                # the dropped-measurement marker on drop_rollup — a name
                # collision with the source would hide the source rows
                raise WriteError(
                    "rollup name must differ from its source measurement")
            if spec.name in d.rollups:
                # silently replacing would leave the old grid's rows and
                # watermark behind — a redeclared interval would then
                # double-count in the splice.  Drop first (the re-fold
                # bootstrap zero-fills the old grid's cells).
                raise WriteError(
                    f"rollup already exists: {db}.{spec.name} "
                    "(drop it first)")
            d.rollups[spec.name] = spec
            self._save_meta()
        self._maybe_init_rollups()

    def drop_rollup(self, db: str, name: str) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d and name in d.rollups:
                spec = d.rollups.pop(name)
                # the persisted cells drop with the spec, scoped to the
                # _rollup RP (orphaned rows would leak disk and answer
                # stale aggregates; a db-wide dropped_msts mark could
                # nuke an unrelated raw measurement sharing the name)
                self._purge_rollup_target(db, spec.target)
                self._save_meta()
        if self.rollup_mgr is not None:
            self.rollup_mgr.drop_state(db, name)
        else:
            # OGT_ROLLUP=0: still remove the state file, or a later
            # re-declare under a re-enabled env resurrects a stale
            # watermark over a purged target
            try:
                os.remove(os.path.join(self.root, "rollup", db,
                                       f"{name}.json"))
            except OSError:
                pass

    def _purge_rollup_target(self, db: str, target: str) -> None:
        """Delete a rollup target's rows from the _rollup RP's shards
        only (caller holds the engine lock)."""
        from opengemini_tpu.storage.rollup import ROLLUP_RP

        for (sdb, rp, _g), sh in list(self._shards.items()):
            if sdb == db and rp == ROLLUP_RP:
                sh.delete_data(target)

    def ensure_rollup_rp(self, db: str) -> None:
        """The system RP rollup rows persist under — infinite retention
        (rollups deliberately outlive their raw source data)."""
        from opengemini_tpu.storage.rollup import ROLLUP_RP

        with self._lock:
            d = self.databases.get(db)
            if d is not None and ROLLUP_RP not in d.rps:
                d.rps[ROLLUP_RP] = RetentionPolicy(
                    ROLLUP_RP, 0, DEFAULT_SHARD_DURATION)
                self._save_meta()

    def add_write_observer(self, fn) -> None:
        """fn(db, rp, points) called after every successful write — the
        stream engine's ingest hook (reference: stream-aware PointsWriter,
        coordinator/points_writer.go stream rows)."""
        self._write_observers.append(fn)

    def _notify_write(self, db: str, rp: str | None, points: list) -> None:
        for fn in self._write_observers:
            try:
                fn(db, rp, points)
            except Exception:  # noqa: BLE001 — observers never break ingest
                import logging

                logging.getLogger("opengemini_tpu.engine").exception(
                    "write observer failed"
                )

    def add_downsample_policy(self, db: str, rp: str, policy: "DownsamplePolicy") -> None:
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            d.downsample.setdefault(rp, []).append(policy)
            self._save_meta()

    def set_downsample_policies(self, db: str, rp: str,
                                policies: list["DownsamplePolicy"],
                                ttl_ns: int = 0) -> None:
        """Replace the rp's whole policy set (replace semantics keep the
        raft-listener replay idempotent; already-exists is the DDL
        layer's check, not the engine's). A nonzero ttl_ns also becomes
        the rp's retention duration (reference: CREATE DOWNSAMPLE's
        Duration is assigned to the rp, data.go SetDownSamplePolicy)."""
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                raise DatabaseNotFound(db)
            if rp not in d.rps:
                raise WriteError(f"retention policy not found: {db}.{rp}")
            d.downsample[rp] = list(policies)
            if ttl_ns:
                d.rps[rp].duration_ns = ttl_ns
            self._save_meta()

    def drop_downsample_policies(self, db: str, rp: str | None = None) -> None:
        with self._lock:
            d = self.databases.get(db)
            if d is None:
                return
            if rp is None:
                d.downsample.clear()
            else:
                d.downsample.pop(rp, None)
            self._save_meta()

    def shards_due_downsample(self, now_ns: int | None = None):
        """[(shard, policy)] whose whole range has aged past a policy and
        whose resolution is still finer (tracked via a marker file)."""
        if now_ns is None:
            now_ns = _time.time_ns()
        due = []
        with self._lock:
            for (db, rp, _start), shard in sorted(self._shards.items()):
                d = self.databases.get(db)
                pols = d.downsample.get(rp, []) if d else []
                best = None
                for p in pols:
                    if shard.tmax <= now_ns - p.age_ns:
                        if best is None or p.every_ns > best.every_ns:
                            best = p
                if best is not None and _downsample_level(shard.path) < best.every_ns:
                    due.append((shard, best))
        return due

    def run_downsample(self, now_ns: int | None = None) -> int:
        """Execute all due downsample rewrites; returns shards rewritten.
        Per-shard failures (e.g. a concurrent retention drop removing the
        directory) are logged and skipped, never aborting the sweep."""
        import logging

        n = 0
        for shard, policy in self.shards_due_downsample(now_ns):
            try:
                shard.rewrite_downsampled(policy.every_ns, policy.field_aggs)
                _set_downsample_level(shard.path, policy.every_ns)
                n += 1
            except Exception:  # noqa: BLE001
                logging.getLogger("opengemini_tpu.engine").exception(
                    "downsample of shard %s failed", shard.path
                )
        return n

    def write_rows(self, db: str, points: list, rp: str | None = None) -> int:
        """Structured write path: points are
        (measurement, tags tuple, t_ns, {field: (FieldType, value)}) —
        used by SELECT INTO and internal services; values never round-trip
        through line-protocol text (reference RecordWriter analogue,
        coordinator/record_writer.go)."""
        if self.write_disabled:
            raise WriteError("writes are disabled (syscontrol)")
        d = self.databases.get(db)
        if d is None:
            raise DatabaseNotFound(db)
        if d.dropped_msts:
            self.purge_dropped_measurements(db)
        rp = rp or d.default_rp
        rtok = None
        if self.rollup_mgr is not None:
            rtok = self.rollup_mgr.note_write_points(db, rp, points)
        utok = None
        if self.rules_hook is not None:
            utok = self.rules_hook.note_write_points(db, rp, points)
        try:
            tickets: list = []
            with self._lock:
                by_shard: dict[int, list] = {}
                shards: dict[int, Shard] = {}
                for p in points:
                    shard = self._get_or_create_shard(db, rp, p[2])
                    key = id(shard)
                    shards[key] = shard
                    by_shard.setdefault(key, []).append(p)
                n = 0
                for key, pts in by_shard.items():
                    got, t = shards[key].write_points_structured(
                        pts, defer_commit=True)
                    n += got
                    tickets.append((shards[key], t))
            self._commit_wal_tickets(tickets)  # fsyncs coalesce off-lock
            self._flush_over_threshold(shards.values())
            self._notify_write(db, rp, points)
            return n
        finally:
            if rtok is not None:
                self.rollup_mgr.write_done(rtok)
            if utok is not None:
                self.rules_hook.write_done(utok)

    def flush_all(self) -> None:
        # snapshot under the lock, flush OUTSIDE it: shard.flush encodes
        # + fsyncs, and holding the engine lock across that stalls every
        # write path behind one shard's disk — the PR 3 threshold-flush
        # stall class, caught on this explicit path by lockdep's
        # blocking-under-hot-lock check
        with self._lock:
            shards = list(self._shards.values())
        self._flush_tolerating_drop(shards, lambda sh: sh.flush())

    # -- durability ledger (PR 4) ------------------------------------------

    def durability_snapshot(self) -> dict:
        """Aggregate + per-shard acked-vs-durable ledgers (see
        storage/shard.DurabilityLedger).  Per shard, `missing` > 0 means
        acked rows are not accounted for in mem or published files —
        silent loss; < 0 means a snapshot published twice.  The TOTAL
        sums absolute values: a loss on one shard must never cancel a
        double-publish on another in the gauge operators alert on."""
        with self._lock:
            shards = list(self._shards.items())
        agg = {"acked": 0, "replayed": 0, "published": 0, "tsf_rows": 0,
               "mem_rows": 0, "missing": 0, "dirty_shards": 0,
               "shards": len(shards)}
        per_shard = {}
        for (db, rp, start), sh in shards:
            snap = sh.ledger_snapshot()
            per_shard[f"{db}|{rp}|{start}"] = snap
            for k in ("acked", "replayed", "published", "tsf_rows",
                      "mem_rows"):
                agg[k] += snap[k]
            agg["missing"] += abs(snap["missing"])
            agg["dirty_shards"] += 1 if snap["dirty"] else 0
        return {"totals": agg, "shards": per_shard}

    def durability_check(self, snapshot: dict | None = None) -> list[dict]:
        """Online invariant checker: every clean shard's ledger must
        conserve rows (acked + replayed == published + mem).  Returns
        violations (empty = healthy); the torture harness and
        /debug/ctrl?mod=durability call this live.  Pass a
        durability_snapshot() to check exactly the state being reported
        (no second pass over the shard locks)."""
        snap = snapshot if snapshot is not None else self.durability_snapshot()
        return [
            {"shard": key, **s}
            for key, s in snap["shards"].items()
            if not s["dirty"] and s["missing"] != 0
        ]

    def _durability_gauges(self) -> dict:
        return self.durability_snapshot()["totals"]

    # -- quarantine (media-fault containment) ------------------------------

    def quarantine_snapshot(self) -> dict:
        """Every quarantined file across shards: {"files": [{shard,
        path, why}], "total": n} — the /debug/ctrl?mod=scrub view."""
        with self._lock:
            shards = list(self._shards.items())
        files = []
        for (db, rp, start), sh in shards:
            for path, why in sorted(sh.quarantined().items()):
                files.append({"shard": f"{db}|{rp}|{start}",
                              "path": path, "why": why})
        return {"files": files, "total": len(files)}

    def _quarantine_gauges(self) -> dict:
        with self._lock:
            shards = list(self._shards.values())
        n = sum(len(sh.quarantined()) for sh in shards)
        return {"files_current": n} if n else {}

    def purge_quarantined(self) -> int:
        """Delete quarantined files + markers from disk across all
        shards (operator action after repair / accepted loss)."""
        with self._lock:
            shards = list(self._shards.values())
        return sum(sh.purge_quarantined() for sh in shards)

    def mem_backlog_bytes(self) -> int:
        """Un-flushed resident bytes (live + frozen memtables + live WAL
        logs) across every shard — the write-backpressure input of the
        resource governor's ledger (utils/governor.py)."""
        with self._lock:
            shards = list(self._shards.values())
        return sum(sh.mem_backlog_bytes() for sh in shards)

    def drop_expired_shards(self, now_ns: int | None = None) -> list[tuple[str, str, int]]:
        """Retention enforcement (reference services/retention/service.go:81):
        drop shards whose whole range is past the RP duration."""
        import shutil

        if now_ns is None:
            now_ns = _time.time_ns()
        dropped = []
        with self._lock:
            for key in list(self._shards):
                db, rp, start = key
                d = self.databases.get(db)
                rp_meta = d.rps.get(rp) if d else None
                if rp_meta is None or rp_meta.duration_ns == 0:
                    continue
                shard = self._shards[key]
                if shard.tmax <= now_ns - rp_meta.duration_ns:
                    shard.close()
                    _remove_shard_dir(shard.path)
                    del self._shards[key]
                    dropped.append(key)
            # offloaded groups age out too (delete the store copy) —
            # only COLLECTED here; the bucket deletes are HTTP calls and
            # run outside the engine lock below (lockdep: retention must
            # not stall every write/query behind object-store round
            # trips)
            purged = []
            for key in sorted(self.obs_shards):
                db, rp, start = key
                d = self.databases.get(db)
                rp_meta = d.rps.get(rp) if d else None
                if rp_meta is None or rp_meta.duration_ns == 0:
                    continue
                if start + rp_meta.shard_duration_ns <= now_ns - rp_meta.duration_ns:
                    from opengemini_tpu.storage.objstore import shard_prefix

                    self.obs_shards.discard(key)
                    dropped.append(key)
                    if self.obs_store is not None:
                        purged.append((key, shard_prefix(*key)))
            if dropped:
                self._save_meta()
        # registry-first, deletes off-lock with re-check — same ordering
        # and race protection as _purge_obs/_delete_obs_prefixes
        self._delete_obs_prefixes(purged)
        return dropped

    def close(self) -> None:
        STATS.unregister_provider("durability", self._durability_provider)
        STATS.unregister_provider("quarantine", self._quarantine_provider)
        if self.rollup_mgr is not None:
            self.rollup_mgr.close()
        from opengemini_tpu.utils.governor import GOVERNOR as _GOVERNOR

        _GOVERNOR.unregister_component("memtable", self._governor_provider)
        # the HTTP layer may have pointed the process-global querytracker
        # at this engine's ledger: a closed engine must neither serve
        # frozen durability state as live nor stay pinned in memory
        from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER

        _TRACKER.detach_durability_provider(self.durability_snapshot)
        with self._lock:
            # audited (lockdep): shutdown fsyncs (each shard's final WAL
            # flush) run under the engine lock deliberately — the lock
            # is what makes close atomic against in-flight writes, and
            # nothing productive contends with a closing engine
            with lockdep.allow_blocking("engine.close shutdown fsyncs"):
                for shard in self._shards.values():
                    shard.close()
                self._shards.clear()
                for entry in self._staging.values():
                    entry[3].close()
                self._staging.clear()


def _remove_shard_dir(path: str) -> None:
    """Delete a shard directory, following a cold-tier symlink: the cold
    copy is removed too, then the link — expired tiered data must not leak
    or resurrect on restart."""
    import shutil as _shutil

    if os.path.islink(path):
        target = os.path.realpath(path)
        _shutil.rmtree(target, ignore_errors=True)
        try:
            os.unlink(path)
        except OSError:
            pass
    else:
        _shutil.rmtree(path, ignore_errors=True)


def _downsample_level(shard_path: str) -> int:
    """Current resolution of a shard (0 = raw), persisted as a marker file
    (the reference tracks per-shard downsample levels in meta,
    engine_downsample.go:23 GetShardDownSampleLevel)."""
    p = os.path.join(shard_path, "downsample.level")
    try:
        with open(p, encoding="utf-8") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return 0


def _set_downsample_level(shard_path: str, every_ns: int) -> None:
    p = os.path.join(shard_path, "downsample.level")
    with open(p, "w", encoding="utf-8") as f:
        f.write(str(every_ns))


def _auto_shard_duration(duration_ns: int) -> int:
    """Influx defaults: RP < 2d -> 1h groups, < 6mo -> 1d, else 7d."""
    day = 24 * 3600 * NS
    if duration_ns == 0:
        return 7 * day
    if duration_ns < 2 * day:
        return 3600 * NS
    if duration_ns < 180 * day:
        return day
    return 7 * day
