"""Shard: a time-ranged slice of one database/RP — WAL + memtable +
immutable TSF files + series index.

Reference: engine/shard.go:117 (WriteRows :512, Snapshot/flush :731,
Compact :688, commitSnapshot :1008) and the per-shard WAL replay
(engine/wal.go:390).
"""

from __future__ import annotations

import os
import itertools
import threading
from opengemini_tpu.utils import lockdep

import numpy as np

from opengemini_tpu.ingest import line_protocol as lp
from opengemini_tpu.index.mergeset import open_series_index
from opengemini_tpu.record import (
    Column, FieldTypeConflict, Record, merge_bulk_parts,
    merge_sorted_records, _zeroed as _rec_zeroed,
)
from opengemini_tpu.storage import colcache, scanpool
from opengemini_tpu.storage.memtable import MemTable
from opengemini_tpu.storage.tsf import (
    PACK_MIN_SERIES, PACK_ROWS, CorruptFile, TSFReader, TSFWriter,
)
from opengemini_tpu.storage.wal import WAL, WALCorruption
from opengemini_tpu.utils.failpoint import inject as _fp
from opengemini_tpu.utils.querytracker import GLOBAL as _TRACKER
from opengemini_tpu.utils.stats import GLOBAL as _STATS
from opengemini_tpu.utils.stats import histogram as _stats_histogram

# flush wall-time distribution (ogt_flush_seconds at /metrics) — the
# counters above it carry totals; the histogram carries the p99 an
# operator actually pages on
_H_FLUSH = _stats_histogram("flush_seconds")


def _pack_entries(buffer: list) -> tuple[np.ndarray, Record]:
    """[(sid, rec)] (sid-ascending, per-rec time-sorted) -> one PK-sorted
    packed block: sid column + union-schema field columns (absent fields
    pad invalid)."""
    total = sum(len(rec) for _sid, rec in buffer)
    sids = np.concatenate(
        [np.full(len(rec), sid, np.int64) for sid, rec in buffer])
    times = np.concatenate([rec.times for _sid, rec in buffer])
    ftypes: dict[str, object] = {}
    for _sid, rec in buffer:
        for name, col in rec.columns.items():
            ftypes.setdefault(name, col.ftype)
    cols = {}
    for name, ftype in ftypes.items():
        # zero-init (see record.merge_bulk_parts): garbage in invalid slots
        # would persist into packed chunks and break digest equality
        values = _rec_zeroed(ftype, total)
        valid = np.zeros(total, dtype=np.bool_)
        at = 0
        for _sid, rec in buffer:
            n = len(rec)
            col = rec.columns.get(name)
            if col is not None:
                values[at:at + n] = col.values
                valid[at:at + n] = col.valid
            at += n
        cols[name] = Column(ftype, values, valid)
    return sids, Record(times, cols)


# bulk (sid, time) merge lives in record.py; shard call sites keep the
# old private name
_merge_bulk_parts = merge_bulk_parts


def _sid_entries(rec: Record, uniq, starts, ends):
    """(sid, per-series Record) views over one (sid, time)-sorted bulk
    table — the flush path's bridge from memtable tables to chunk writes.
    Column slicing + all-invalid drop shares memtable._series_slice so the
    per-series shape (and content_digest) can never diverge by path."""
    from opengemini_tpu.storage.memtable import _series_slice

    for sid, lo, hi in zip(uniq, starts, ends):
        yield int(sid), _series_slice(rec, lo, hi)


def _write_measurement_chunks(w: TSFWriter, tidx, mst: str, entries,
                              n_series: int | None = None) -> int:
    """Write one measurement's series records: per-sid chunks at low
    cardinality, PK-sorted packed chunks (reference: colstore) once a
    flush carries >= PACK_MIN_SERIES series.  `entries` iterates
    (sid, rec) in ascending sid order; records stream out every
    PACK_ROWS rows so compaction never holds a whole measurement.
    Returns rows submitted to the writer — the flush path feeds this
    into the durability ledger's tsf_rows counter."""
    rows = 0
    if n_series is None:
        entries = list(entries)
        n_series = len(entries)
    if n_series < PACK_MIN_SERIES:
        for sid, rec in entries:
            w.add_chunk(mst, sid, rec)
            tidx.add(mst, sid, rec)
            rows += len(rec)
        return rows
    buffer: list = []
    buffered = 0
    for sid, rec in entries:
        if len(rec) == 0:
            continue
        tidx.add(mst, sid, rec)
        buffer.append((sid, rec))
        buffered += len(rec)
        rows += len(rec)
        if buffered >= PACK_ROWS:
            sids, packed = _pack_entries(buffer)
            w.add_packed_chunk(mst, sids, packed)
            buffer, buffered = [], 0
    if buffer:
        sids, packed = _pack_entries(buffer)
        w.add_packed_chunk(mst, sids, packed)
    return rows


def iter_structured_batches(sh, chunk_rows: int):
    """Yield a shard's full content as structured-point batches
    (measurement, tags, t_ns, {field: (type, value)}) of <= chunk_rows —
    the ONE extraction loop shared by migration pushes
    (parallel/cluster._push_shard) and staging commits
    (engine.commit_staging)."""
    batch: list = []
    for mst in sh.measurements():
        for sid in sorted(sh.index.series_ids(mst)):
            rec = sh.read_series(mst, sid)
            if not len(rec):
                continue
            _m, tags = sh.index.series_entry(sid)
            cols = list(rec.columns.items())
            for i in range(len(rec)):
                fields = {}
                for name, col in cols:
                    if col.valid[i]:
                        v = col.values[i]
                        fields[name] = (
                            col.ftype,
                            v.item() if hasattr(v, "item") else v,
                        )
                if fields:
                    batch.append((mst, tags, int(rec.times[i]), fields))
                if len(batch) >= chunk_rows:
                    yield batch
                    batch = []
    if batch:
        yield batch


_DATA_VERSIONS = itertools.count(1)  # see Shard.data_version
_MUT_LOG_MAX = 512  # bounded mutation history; overflow = assume-changed


class FileQuarantined(Exception):
    """A read hit media damage in an immutable file; the file has been
    QUARANTINED (out of the read set, durable `.quar` marker) and this
    query failed cleanly before any wrong value was produced.  The NEXT
    query over this shard skips the file; at rf>1 the coordinator's scan
    failover classifies the resulting 500 as node-down for the round and
    serves the ranges from a replica instead."""

    def __init__(self, path: str, why: str):
        super().__init__(
            f"file quarantined after media fault: {path}: {why}")
        self.path = path
        self.why = why


class DurabilityLedger:
    """Acked-rows vs durable-rows accounting for one shard (PR 4).

    Flow conservation: every row the shard ACCEPTED (acked at the write
    call's return, or re-applied by WAL replay on open) is either still
    in an in-memory part (live memtable or a frozen flush snapshot) or
    was handed to exactly one PUBLISHED TSF.  `published` counts rows at
    the memtable's accounting (frozen.row_count, pre-dedup), so

        acked + replayed == published + rows_in_mem_parts

    holds at every instant the shard lock is held — a dropped snapshot
    shows as a positive `missing`, a double-published one as negative.
    `tsf_rows` counts rows actually written into published flush files
    (post last-write-wins dedup): `published - tsf_rows` is legitimate
    duplicate-timestamp collapse, and for a unique-timestamp workload
    (the stress/torture harnesses) any nonzero gap is silent row loss —
    exactly how the PR-4 consolidation-cache bug was pinned down.

    All mutation happens under the shard lock; `dirty` marks shards
    whose content was rewritten by delete/downsample (accounting
    rebased — conservation no longer checkable)."""

    __slots__ = ("acked", "replayed", "published", "tsf_rows", "dirty")

    def __init__(self):
        self.acked = 0
        self.replayed = 0
        self.published = 0
        self.tsf_rows = 0
        self.dirty = False

    def snapshot(self, mem_rows: int) -> dict:
        missing = (self.acked + self.replayed - self.published - mem_rows)
        return {
            "acked": self.acked,
            "replayed": self.replayed,
            "published": self.published,
            "tsf_rows": self.tsf_rows,
            "mem_rows": mem_rows,
            "dirty": self.dirty,
            # >0: acked rows vanished; <0: a snapshot published twice
            "missing": 0 if self.dirty else missing,
        }


class Shard:
    supports_preagg = True  # RemoteShard proxies set False (no chunk meta)

    def __init__(self, path: str, tmin: int, tmax: int, sync_wal: bool = False,
                 tag_arrays: bool = False):
        self.path = path
        self.tag_arrays = tag_arrays  # WAL replay must expand like ingest
        self.tmin = tmin  # inclusive ns
        self.tmax = tmax  # exclusive ns
        os.makedirs(path, exist_ok=True)
        self.index = open_series_index(path)
        # LOGICAL-content version + bounded mutation log: versions are
        # drawn from a process-global counter so a (path, version) pair
        # can never repeat — a dropped-and-recreated shard at the same
        # path cannot alias a stale cache signature. The log records each
        # mutation's TIME RANGE so the incremental result cache
        # (query/resultcache.py) invalidates only the touched windows of
        # this shard, not all of them (a 7d shard covers every window of a
        # dashboard query). Flush/compact change layout, not content, and
        # do not bump. Reference analogue: the query iterID + write
        # tracking of inc_agg_transform.go / lib/resultcache.
        self.data_version = next(_DATA_VERSIONS)
        self._mut_floor = self.data_version  # history unknown at/below
        self._mutations: list[tuple[int, int, int]] = []
        # decoded-column cache namespace (storage/colcache.py): a
        # process-unique shard id stamped onto every reader this shard
        # opens, so cache keys identify (shard, file, chunk) even when a
        # dropped-and-recreated shard reuses a path
        self.cache_ns = next(_DATA_VERSIONS)
        # measurement -> field -> FieldType; owned here so it survives
        # memtable generations and is seeded from immutable files on open.
        self.schemas: dict[str, dict] = {}
        self.mem = MemTable(self.schemas)
        # hot class (lockdep): fsync/sleep/socket under it is a
        # violation — the one audited exception is WAL.rotate's fsync,
        # which this very lock fences (see storage/wal.py)
        self._lock = lockdep.mark_hot(lockdep.RLock(), "shard._lock")
        # flush/rewrite serialization. Lock ORDER: _flush_lock before
        # _lock, always — flush holds _flush_lock across its off-lock
        # encode while taking _lock only to freeze and to publish;
        # anything that both holds _lock and (transitively) flushes
        # (delete/downsample rewrites, tier offload) must take
        # _flush_lock first or it deadlocks against an in-flight flush.
        self._flush_lock = lockdep.name_class(
            lockdep.RLock(), "shard._flush_lock")
        # snapshot-and-swap flush state: memtables frozen under the lock,
        # encoded + written OFF it. Each entry is (frozen memtable,
        # rotated WAL segment path | None); readers merge frozen
        # snapshots between the files and the live memtable until the
        # TSF is published (engine/shard.go Snapshot/commitSnapshot).
        # An immutable TUPLE replaced on every change, so hot per-series
        # probes (_mem_parts) can snapshot it with one attribute read —
        # no lock acquisition per series.
        self._frozen: tuple[tuple[MemTable, str | None], ...] = ()
        self._wal_seg_seq = 1
        # rotated segments found at open (crash between publish and
        # segment removal) or left by a failed flush: their rows replay
        # into the memtable / stay in files, so the next successful
        # flush removes them
        self._stale_wal_segs: list[str] = []
        self._files: list[TSFReader] = []
        self._tidx_cache: dict[str, object] = {}  # tsf path -> parsed | None
        self._next_file_seq = 1
        # acked-vs-durable row accounting (see DurabilityLedger);
        # _replaying routes replay-applied rows into the replayed bucket
        self.ledger = DurabilityLedger()
        self._replaying = False
        # media-damaged files pulled out of the read set: path -> why.
        # Durable `.quar` markers keep quarantine sticky across reopens;
        # the file itself stays on disk as evidence (and for operator
        # purge via /debug/ctrl?mod=scrub&op=purge) — at rf>1 the scrub
        # service heals the lost rows back in through anti-entropy.
        self._quarantined: dict[str, str] = {}
        self._load_files()
        for r in self._files:
            for mst in r.measurements():
                self.schemas.setdefault(mst, {}).update(r.schema(mst))
        # replay BEFORE opening the live WAL handle: interior-corruption
        # recovery may quarantine + rewrite wal.log on disk, and the
        # append handle must open over the REWRITTEN file
        self._replay_wal()
        self.wal = WAL(os.path.join(path, "wal.log"), sync=sync_wal)

    def _adopt(self, reader: TSFReader) -> TSFReader:
        """Stamp the shard's cache namespace onto a freshly-opened reader
        (decoded-column cache key component, storage/colcache.py)."""
        reader.owner_ns = self.cache_ns
        return reader

    def drop_cached_columns(self) -> int:
        """Invalidate every decoded-column cache entry of this shard's
        CURRENT files (close/offload hook; file-set swaps invalidate the
        retired readers at the swap site). Returns entries dropped."""
        return colcache.GLOBAL.invalidate_gens([r.gen for r in self._files])

    # -- quarantine (media-fault containment) -------------------------------

    def _write_quar_marker(self, path: str, why: str) -> None:
        """Durable `.quar` marker write+fsync.  Lock-free by design
        (lockdep: the fsync must not stall writers/readers behind
        media-fault bookkeeping) and idempotent — concurrent detectors
        just rewrite the same marker."""
        import json as _json

        _fp("quarantine-before-mark")  # detected, marker not yet durable
        marker = _quar_marker(path)
        tmp = marker + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                # wall-clock record: operator forensics metadata only
                _json.dump({"why": why,
                            "ts": __import__("time").time()},  # ogtlint: disable=OGT040
                           f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, marker)
        except OSError:
            pass  # marker is sticky-convenience; in-memory state governs

    def _record_quarantined(self, path: str, why: str) -> None:
        """In-memory quarantine record + counters (marker already
        durable — see _write_quar_marker)."""
        import logging

        self._quarantined[path] = why
        _STATS.incr("quarantine", "tsf_files_total")
        logging.getLogger("opengemini_tpu.shard").error(
            "quarantined TSF file %s: %s", path, why)
        from opengemini_tpu.utils.governor import GOVERNOR as _GOV

        _GOV.trigger_diagnostic(f"TSF file quarantined: {path}: {why}")

    def _quarantine_path(self, path: str, why: str) -> None:
        """Record + durably mark one file quarantined (no reader swap —
        open-time path, or the reader is already gone).  The `.quar`
        marker keeps quarantine sticky across reopens; a crash between
        detection and the marker just re-detects next open."""
        self._write_quar_marker(path, why)
        self._record_quarantined(path, why)

    def quarantine_file(self, path: str, why: str) -> bool:
        """Runtime quarantine: pull a damaged file out of the read set.
        Returns True when THIS call quarantined it (False = already
        quarantined or not one of this shard's files).  Queries that
        were mid-scan keep their reader refs (POSIX fds survive);
        every later scan snapshot simply excludes the file."""
        with self._lock:
            if not any(r.path == path for r in self._files):
                return False
        # durable marker OFF the shard lock (lockdep: the fsync must not
        # stall writers/readers behind media-fault bookkeeping); written
        # before the swap so detection stays sticky even if we crash
        # mid-quarantine, and idempotent under concurrent detectors
        self._write_quar_marker(path, why)
        with self._lock:
            idx = next((i for i, r in enumerate(self._files)
                        if r.path == path), None)
            if idx is None:
                return False  # lost the race: another detector (or a
                # compaction retire) already pulled the file
            reader = self._files[idx]
            self._record_quarantined(path, why)
            self._files = self._files[:idx] + self._files[idx + 1:]
            self._tidx_cache.pop(path, None)
            colcache.GLOBAL.invalidate_gens([reader.gen])
            # logical content changed (rows vanished until repair):
            # cached query results over the file's range must not mix
            # with post-quarantine scans
            lo = reader.tmin if reader.tmin is not None else self.tmin
            hi = reader.tmax + 1 if reader.tmax is not None else self.tmax
            self._note_mutation(lo, hi)
        return True

    def note_corrupt(self, exc: CorruptFile):
        """Read-path handler: quarantine the damaged file and fail THIS
        query cleanly (FileQuarantined) — detection always beats serving
        a wrong value.  Unaffected queries (and retries of this one)
        proceed without the file."""
        self.quarantine_file(exc.path, exc.why)
        raise FileQuarantined(exc.path, exc.why) from exc

    def quarantined(self) -> dict[str, str]:
        """{path: why} of this shard's quarantined files."""
        with self._lock:
            return dict(self._quarantined)

    def purge_quarantined(self) -> int:
        """Operator/scrub cleanup: delete quarantined files + markers
        from disk (after rf>1 repair re-replicated the rows, or the
        operator accepted the loss).  Returns files purged."""
        with self._lock:
            doomed = list(self._quarantined)
            self._quarantined.clear()
        n = 0
        for path in doomed:
            for p in (path, _quar_marker(path), _tidx_path(path)):
                try:
                    os.remove(p)
                    n += p == path
                except OSError:
                    pass
        return n

    def _note_mutation(self, lo: int, hi: int) -> None:
        """Record a logical-content change over [lo, hi) ns."""
        self.data_version = next(_DATA_VERSIONS)
        self._mutations.append((self.data_version, lo, hi))
        if len(self._mutations) > _MUT_LOG_MAX:
            drop = len(self._mutations) // 2
            self._mut_floor = self._mutations[drop - 1][0]
            # REPLACE, never truncate in place: lockless readers iterate
            # their own snapshot (a shrinking list would silently end a
            # reversed() iterator early and hide recent mutations)
            self._mutations = self._mutations[drop:]

    def changed_since(self, version: int, lo: int, hi: int) -> bool:
        """Did any mutation newer than `version` touch [lo, hi)?
        Conservative: truncated history answers True."""
        if version < self._mut_floor:
            return True
        muts = self._mutations  # snapshot ref (list is replaced, not cut)
        for v, mlo, mhi in reversed(muts):
            if v <= version:
                break
            if mhi > lo and mlo < hi:
                return True
        return False

    # -- open/recovery ------------------------------------------------------

    def _load_files(self) -> None:
        import json as _json

        # sweep crash leftovers: a .merge/.tmp that never reached its
        # os.replace would otherwise accumulate as full-size garbage
        for f in os.listdir(self.path):
            if f.endswith((".merge", ".tmp")):
                try:
                    os.remove(os.path.join(self.path, f))
                except OSError:
                    pass
        names = sorted(
            f for f in os.listdir(self.path) if f.endswith(".tsf")
        )
        for name in names:
            full = os.path.join(self.path, name)
            # the sequence advances past EVERY file, quarantined or not:
            # a later flush must never reuse a damaged file's name
            seq = int(name.split(".")[0])
            self._next_file_seq = max(self._next_file_seq, seq + 1)
            marker = _quar_marker(full)
            if os.path.exists(marker):
                try:
                    with open(marker, encoding="utf-8") as f:
                        why = _json.load(f).get("why", "marker present")
                except (OSError, ValueError):
                    why = "marker present"
                self._quarantined[full] = why
                continue
            try:
                reader = TSFReader(full)
            except CorruptFile as e:
                # damaged trailer/meta/magic: the old behavior crashed
                # the whole shard open (every query on every other file
                # died with it) — quarantine the one file instead
                self._quarantine_path(full, e.why)
                continue
            self._files.append(self._adopt(reader))

    def _replay_wal(self) -> None:
        self._replaying = True
        try:
            self._replay_wal_inner()
        finally:
            self._replaying = False

    def _replay_wal_inner(self) -> None:
        wal_path = os.path.join(self.path, "wal.log")
        # rotated segments first (oldest → newest), then the live log:
        # the append order every last-write-wins rank derives from. A
        # segment present at open means a crash hit the window between
        # WAL rotation and segment removal — its rows either replay fresh
        # (TSF never published) or dedup against the published file.
        for seg in WAL.segments(wal_path):
            self._stale_wal_segs.append(seg)
            seq = seg.rsplit(".", 1)[-1]
            if seq.isdigit():
                self._wal_seg_seq = max(self._wal_seg_seq, int(seq) + 1)
            self._replay_one(seg)
        self._replay_one(wal_path)

    def _replay_one(self, wal_path: str) -> None:
        try:
            for entry in WAL.replay(wal_path):
                self._replay_entry(entry)
        except WALCorruption as e:
            self._recover_wal_corruption(wal_path, e)

    def _recover_wal_corruption(self, wal_path: str, e: WALCorruption) -> None:
        """Interior WAL damage (media fault, never a crash artifact):
        re-apply the salvaged suffix — every frame after the damage
        holds rows that were ACKED — preserve the damaged log as a
        quarantine sidecar, and rewrite a clean log from the decodable
        frames so the recovered rows stay durable and the next reopen
        replays cleanly (reopen idempotence).  At most the one destroyed
        frame is lost, and LOUDLY: counters, log line, sherlock dump."""
        import logging
        import shutil as _shutil

        for entry in e.salvaged_entries():
            self._replay_entry(entry)
        n_good = len(e.clean_frames) + len(e.salvaged_frames)
        qdir = os.path.join(self.path, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        qpath = os.path.join(
            qdir, os.path.basename(wal_path) + f".corrupt-{e.offset}")
        try:
            if not os.path.exists(qpath):  # keep the FIRST evidence copy
                _shutil.copy2(wal_path, qpath)
        except OSError:
            qpath = None  # evidence copy is best-effort, recovery is not
        # rewrite the log with every decodable frame, atomically: the
        # salvaged rows must not live only in this process's memtable
        import zlib as _z

        from opengemini_tpu.storage.wal import _HEADER as _WH

        tmp = wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for kind, payload in (*e.clean_frames, *e.salvaged_frames):
                f.write(_WH.pack(len(payload), _z.crc32(payload), kind)
                        + payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, wal_path)
        _STATS.incr("wal", "interior_corruptions")
        _STATS.incr("wal", "salvaged_frames", len(e.salvaged_frames))
        _STATS.incr("quarantine", "wal_salvages_total")
        logging.getLogger("opengemini_tpu.shard").error(
            "WAL %s: interior corruption at offset %d — one frame "
            "destroyed, %d frame(s) salvaged, damaged log preserved at "
            "%s", wal_path, e.offset, len(e.salvaged_frames), qpath)
        from opengemini_tpu.utils.governor import GOVERNOR as _GOV

        _GOV.trigger_diagnostic(
            f"WAL interior corruption in {wal_path} (offset {e.offset}, "
            f"{n_good} frames recovered)")

    def _replay_entry(self, entry) -> None:
        from opengemini_tpu.ingest import native_lp

        if entry[0] == "lines":
            _, lines, precision, now_ns = entry
            batch = None
            try:
                if not (self.tag_arrays and b"=[" in lines):
                    batch = native_lp.parse_columnar(
                        lines, precision, now_ns)
            except lp.ParseError:
                batch = None
            if batch is not None:
                try:
                    self._apply_columnar(batch, check_types=True)
                except FieldTypeConflict:
                    # partial-write semantics: a batch rejected at write
                    # time must not poison replay either
                    pass
                return
            points = lp.parse_lines(lines, precision, now_ns,
                                    expand_tag_arrays=self.tag_arrays)
        else:
            points = entry[1]
        replayed = 0
        for p in points:
            mst, tags, t, fields = p
            if self.tmin <= t < self.tmax:
                sid = self.index.get_or_create(mst, tags)
                try:
                    self.mem.write_row(sid, mst, t, fields)
                except FieldTypeConflict:
                    continue
                replayed += 1
        if replayed:  # one batched credit per entry, not per row
            self._ledger_accept(replayed)

    # -- write path ---------------------------------------------------------

    def write_points(self, points: list, raw_lines: bytes, precision: str,
                     now_ns: int, defer_commit: bool = False):
        """Apply pre-parsed points in this shard's range; `raw_lines` is the
        original batch logged for replay (replay re-filters by time range).
        Returns rows written. Raises FieldTypeConflict BEFORE touching the
        WAL — a rejected batch must not poison replay.

        The sync-WAL durability wait happens OUTSIDE the shard lock, so
        concurrent writers coalesce into one fsync (WAL group commit)
        instead of serializing an fsync each under the lock.  With
        `defer_commit=True` returns (rows, ticket) and the CALLER owns
        the `wal.commit(ticket)` — the engine lifts the wait out of its
        own lock too, so fsyncs coalesce across server threads.

        Sync-failure semantics (group commit): rows apply to the
        memtable BEFORE the fsync barrier, so a write erroring at
        commit() is already readable and will become durable with the
        next successful sync/flush.  The old inline path had the mirror
        inconsistency (the frame was written pre-fsync, so error-acked
        rows resurfaced via replay after restart); either way an
        errored ack means durability UNKNOWN, not rejected."""
        with self._lock:
            self._check_types(points)
            ticket = self.wal.append_lines(raw_lines, precision, now_ns)
            n = self._apply(points)
        if defer_commit:
            return n, ticket
        self.wal.commit(ticket)
        return n

    def write_points_structured(self, points: list,
                                defer_commit: bool = False):
        """Same as write_points but WAL-logged as structured points (kind 2)
        — the SELECT INTO / internal write path, no line-protocol text."""
        with self._lock:
            self._check_types(points)
            ticket = self.wal.append_points(points)
            n = self._apply(points)
        if defer_commit:
            return n, ticket
        self.wal.commit(ticket)
        return n

    def write_columnar(self, batch, rows: np.ndarray | None,
                       raw_lines: bytes, precision: str, now_ns: int,
                       defer_commit: bool = False):
        """Apply a native-parsed ColumnarBatch (ingest/native_lp.py). `rows`
        selects this shard's row indices (None = all rows). WAL-logs the
        ORIGINAL batch text (replay re-filters by time range, exactly like
        write_points). Type conflicts raise BEFORE the WAL append."""
        with self._lock:
            self._check_columnar_types(batch, rows)
            ticket = self.wal.append_lines(raw_lines, precision, now_ns)
            n = self._apply_columnar(batch, rows=rows)
        if defer_commit:
            return n, ticket
        self.wal.commit(ticket)  # see write_points: group-commit wait
        return n

    def _check_columnar_types(self, batch, rows) -> None:
        pending: dict[tuple[int, str], object] = {}
        for mst_id, name, ftype, _values, valid in batch.cols:
            sel = valid if rows is None else valid[rows]
            if not sel.any():
                continue
            mst = batch.measurements[mst_id]
            schema = self.schemas.get(mst, {})
            have = schema.get(name) or pending.get((mst_id, name))
            if have is None:
                pending[(mst_id, name)] = ftype
            elif have != ftype:
                raise FieldTypeConflict(name, have, ftype)

    def _resolve_sids(self, batch, refs: np.ndarray) -> np.ndarray:
        """Map unique series refs -> sids via the series index (new series
        register here). Returns an array indexed by ref."""
        sid_by_ref = np.zeros(len(batch.series_keys), np.int64)
        bulk = getattr(self.index, "get_or_create_bulk", None)
        if bulk is not None and len(refs) > 8:
            ref_list = [int(r) for r in refs]
            sids = bulk([batch.series_keys[r] for r in ref_list])
            sid_by_ref[ref_list] = sids
            return sid_by_ref
        for ref in refs:
            sid_by_ref[ref] = self.index.get_or_create_by_key(
                batch.series_keys[int(ref)])
        return sid_by_ref

    def _apply_columnar(self, batch, rows: np.ndarray | None = None,
                        check_types: bool = False) -> int:
        """Memtable-apply the batch's selected rows (per-measurement slab
        appends). `check_types=True` is the WAL-replay path (no prior
        _check_columnar_types call; conflicts raise before any mutation).
        Rows outside [tmin, tmax) are filtered here — replay feeds whole
        batches."""
        ts = batch.ts if rows is None else batch.ts[rows]
        in_range = (ts >= self.tmin) & (ts < self.tmax)
        if not in_range.all():
            rows = (np.flatnonzero(in_range) if rows is None
                    else rows[in_range])
            ts = batch.ts[rows]
        if len(ts) == 0:
            return 0
        if check_types:
            self._check_columnar_types(batch, rows)
        refs = batch.series_ref if rows is None else batch.series_ref[rows]
        sid_by_ref = self._resolve_sids(batch, np.unique(refs))
        sids = sid_by_ref[refs]
        row_mst = batch.series_mst[refs]
        n = 0
        for mst_id in np.unique(row_mst):
            mst = batch.measurements[int(mst_id)]
            sel = row_mst == mst_id
            all_rows = sel.all()
            idx = None if all_rows else np.flatnonzero(sel)
            cols = {}
            for c_mst, name, ftype, values, valid in batch.cols:
                if c_mst != mst_id:
                    continue
                v = values if rows is None else values[rows]
                ok = valid if rows is None else valid[rows]
                if not all_rows:
                    v, ok = v[idx], ok[idx]
                if ok.any():
                    cols[name] = (ftype, v, ok)
            m_sids = sids if all_rows else sids[idx]
            m_ts = ts if all_rows else ts[idx]
            self.mem.write_columnar(mst, m_sids, m_ts, cols)
            n += len(m_ts)
        if n:
            self._note_mutation(int(ts.min()), int(ts.max()) + 1)
            self._ledger_accept(n)
        return n

    def _ledger_accept(self, n: int) -> None:
        """Rows entered the memtable (caller holds the shard lock):
        credit the acked bucket — or replayed, when WAL replay is the
        writer (those rows were acked in a previous process life).
        /debug/vars durability gauges come from the live ledgers (stats
        provider), never from separate counters — two diverging copies
        of the same number would poison the alerting surface."""
        if self._replaying:
            self.ledger.replayed += n
        else:
            self.ledger.acked += n

    def _check_types(self, points: list) -> None:
        pending: dict[str, dict] = {}
        for mst, _tags, _t, fields in points:
            schema = self.schemas.get(mst, {})
            batch_schema = pending.setdefault(mst, {})
            for name, (ftype, _v) in fields.items():
                have = schema.get(name) or batch_schema.get(name)
                if have is None:
                    batch_schema[name] = ftype
                elif have != ftype:
                    raise FieldTypeConflict(name, have, ftype)

    def _apply(self, points: list) -> int:
        n = 0
        for mst, tags, t, fields in points:
            sid = self.index.get_or_create(mst, tags)
            self.mem.write_row(sid, mst, t, fields)
            n += 1
        if n:
            self._note_mutation(
                min(p[2] for p in points), max(p[2] for p in points) + 1)
            self._ledger_accept(n)
        return n

    def flush(self) -> None:
        """Memtable -> new TSF file, then drop the covering WAL segment.

        Snapshot-and-swap (reference Snapshot/commitSnapshot,
        engine/shard.go:731/:1008): under the shard lock the memtable is
        FROZEN, the WAL rotates to a fresh segment, and a new memtable
        installs — microseconds.  Encoding (pipelined through the encode
        pool) and file writing then run OFF the shard lock, so concurrent
        ingest and reads proceed for the whole encode+write+fsync;
        readers merge the frozen snapshot between the files and the live
        memtable until the new TSF publishes.  Crash-safe ordering is
        unchanged: the file is fsynced and atomically renamed BEFORE the
        rotated segment (and only it) is removed; a crash anywhere
        replays the surviving segments over whatever published, and
        last-write-wins dedup makes the overlap idempotent.  A failed
        flush keeps its frozen snapshot queued (readable, recoverable);
        the next flush drains it first, oldest first.

        Measurement chunks emit in sorted-name order (since r3): TSF file
        layout can differ from files written by older versions for
        multi-measurement shards. Replica comparison is CONTENT-based
        (content_digest hashes logical rows, not file bytes), so
        mixed-version replicas still agree."""
        with self._flush_lock:
            with self._lock:
                if len(self.mem) == 0 and not self._frozen:
                    return
                self.index.flush()
                if len(self.mem):
                    seg = os.path.join(
                        self.path, f"wal.log.{self._wal_seg_seq:06d}")
                    self._wal_seg_seq += 1
                    seg = self.wal.rotate(seg)
                    self.mem.freeze()
                    self._frozen = self._frozen + ((self.mem, seg),)
                    self.mem = MemTable(self.schemas)
                    # armed site between the freeze/rotate/swap (done,
                    # still under both locks) and the off-lock encode —
                    # a kill here leaves a rotated segment + frozen
                    # snapshot that replay must fully recover
                    _fp("shard-flush-after-rotate")
            # off the shard lock: encode + write + fsync + publish, one
            # file per frozen snapshot, oldest first (file append order =
            # write order keeps last-write-wins ranking exact)
            while True:
                with self._lock:
                    if not self._frozen:
                        return
                    frozen, seg = self._frozen[0]
                    path = os.path.join(
                        self.path, f"{self._next_file_seq:08d}.tsf")
                    self._next_file_seq += 1
                self._flush_frozen(frozen, seg, path)

    def flush_if_over(self, threshold_bytes: int) -> bool:
        """Threshold-path flush: N concurrent writers that all saw the
        same over-threshold memtable must trigger ONE flush, not N
        cascading rotations of a few trickle rows each.  Non-blocking: a
        flush already in flight covers this crossing (rows written after
        its freeze accumulate toward the next one), so the caller —
        usually a request thread — never queues behind a full
        encode+fsync just to re-check and no-op."""
        if not self._flush_lock.acquire(blocking=False):
            return False
        try:
            if self.mem.approx_bytes <= threshold_bytes and not self._frozen:
                return False
            self.flush()
            return True
        finally:
            self._flush_lock.release()

    def _flush_frozen(self, frozen: MemTable, seg: str | None,
                      path: str) -> None:
        """Encode+write one frozen memtable into `path`, publish it, then
        remove the WAL segment(s) its rows came from.  Caller holds
        _flush_lock but NOT _lock (except re-entrantly, when a rewrite
        op flushes inline)."""
        import time as _time

        t0 = _time.perf_counter_ns()
        _fp("shard-flush-before-encode")  # off-lock encode begins
        w = TSFWriter(path, kind="flush")
        tidx = _TextSidecar()
        tsf_rows = 0
        try:
            for mst, sid_arr, rec in frozen.measurement_tables():
                uniq, starts = np.unique(sid_arr, return_index=True)
                ends = np.append(starts[1:], len(sid_arr))
                tsf_rows += _write_measurement_chunks(
                    w, tidx, mst,
                    _sid_entries(rec, uniq, starts, ends),
                    n_series=len(uniq))
            # post-dedup rows can only ever SHRINK vs the snapshot's
            # accepted-row count; more means duplicated rows — abort
            # BEFORE finish() makes the bad file durable
            if tsf_rows > frozen.row_count:
                raise RuntimeError(
                    f"flush wrote {tsf_rows} rows from a "
                    f"{frozen.row_count}-row snapshot (duplication)")
            _fp("shard-flush-before-publish")  # reference: engine/shard.go:457
            w.finish()
        except BaseException:
            w.abort()
            raise
        with self._lock:
            reader = self._adopt(TSFReader(path))
            self._files.append(reader)
            # publish + un-freeze atomically: a reader snapshots either
            # (old files + frozen) or (files + new TSF) — never neither
            self._frozen = self._frozen[1:]
            if seg is not None:
                self._stale_wal_segs.append(seg)
            # ledger: the snapshot's rows moved from mem-parts to a
            # published file — same lock hold as the swap, so the
            # conservation invariant never wobbles mid-publish (gauges
            # ride the stats provider; see _ledger_accept)
            self.ledger.published += frozen.row_count
            self.ledger.tsf_rows += tsf_rows
        _fp("shard-flush-after-publish")
        # sidecar AFTER adoption: w.finish() already made the TSF
        # visible on disk, so a sidecar failure here must not leave the
        # snapshot queued (a retry would write the same rows into a
        # SECOND file next to the adopted-on-reopen orphan). The brief
        # no-sidecar window only disables text pruning — reads stay
        # exact. Written under the lock, and only while OUR reader still
        # owns the path: an in-place compaction that already replaced
        # this file wrote the MERGED sidecar, which a stale write here
        # must not clobber (silent text-prune under-reporting).
        with self._lock:
            if any(r is reader for r in self._files):
                tidx.write(path)
                self._tidx_cache.pop(path, None)
        _STATS.incr("flush", "flushes")
        _STATS.incr("flush", "rows", frozen.row_count)
        _STATS.incr("flush", "total_ns", _time.perf_counter_ns() - t0)
        _H_FLUSH.observe_ns(_time.perf_counter_ns() - t0)
        _fp("shard-flush-before-wal-truncate")
        # rows are durable in the published file: the rotated segment —
        # and any stale ones from crashes/failed flushes — can go
        stale, self._stale_wal_segs = self._stale_wal_segs, []
        for p in stale:
            try:
                os.remove(p)
            except OSError:
                pass
        _fp("shard-flush-after-wal-truncate")

    @staticmethod
    def _merge_readers(readers, w: "TSFWriter", tidx: "_TextSidecar") -> None:
        """Shared merge body of compact()/compact_level(): all chunks per
        series across `readers` (oldest first: timestamp last-write-wins
        dedup holds), written merged into `w` + the text sidecar.  Output
        re-packs into PK-sorted multi-series chunks at high cardinality."""
        per_mst: dict[str, set[int]] = {}
        for r in readers:
            for mst in r.measurements():
                sids = per_mst.setdefault(mst, set())
                for c in r.chunks(mst):
                    if c.packed:
                        sids.update(
                            int(s) for s in
                            np.unique(r.read_packed_sids(c, cache=False)))
                    else:
                        sids.add(c.sid)
        BATCH = 65536  # sids per merge batch: bounds resident rows
        for mst in sorted(per_mst):
            sids_sorted = sorted(per_mst[mst])
            n_series = len(sids_sorted)

            def merged_entries():
                for b0 in range(0, n_series, BATCH):
                    batch = np.asarray(sids_sorted[b0:b0 + BATCH], np.int64)
                    batch_set = set(batch.tolist())
                    # one decode per chunk per batch (cache=False: the
                    # soon-to-be-retired readers must not pin memory);
                    # decodes fan across the scan pool, yielding in file
                    # order so last-write-wins ranking is unchanged
                    def decode(r, c):
                        if c.packed:
                            s_arr, rec = r.read_packed_bulk(
                                mst, c, None, sid_filter=batch, cache=False)
                            return (s_arr, rec) if len(rec) else None
                        rec = r.read_chunk(mst, c, cache=False)
                        return (np.full(len(rec), c.sid, np.int64), rec)

                    jobs = []
                    ests = []
                    for r in readers:
                        for c in r.chunks(mst):
                            if c.packed:
                                if c.smax < batch[0] or c.smin > batch[-1]:
                                    continue
                            elif c.sid not in batch_set:
                                continue
                            jobs.append(lambda r=r, c=c: decode(r, c))
                            ests.append(scanpool.est_chunk_bytes(c, None))
                    parts = [p for p in scanpool.map_ordered(jobs, ests)
                             if p is not None]
                    sid_arr, rec = _merge_bulk_parts(
                        parts, -(2**63), 2**63 - 1)
                    uniq, starts = np.unique(sid_arr, return_index=True)
                    ends = np.append(starts[1:], len(sid_arr))
                    for sid, lo, hi in zip(uniq, starts, ends):
                        yield int(sid), Record(
                            rec.times[lo:hi],
                            {
                                name: Column(col.ftype, col.values[lo:hi],
                                             col.valid[lo:hi])
                                for name, col in rec.columns.items()
                            },
                        )

            _write_measurement_chunks(
                w, tidx, mst, merged_entries(), n_series=n_series)

    def file_count(self) -> int:
        with self._lock:
            return len(self._files)

    @staticmethod
    def _find_run(cur: list, run: list) -> int | None:
        """Position of `run` inside `cur` — matched by READER IDENTITY,
        contiguous and in order — or None when any member vanished
        (quarantine pulled a file, or a delete/downsample rewrite swapped
        the whole set).  The off-lock compaction swap revalidates its
        snapshot through this before publishing."""
        if not run:
            return None
        for j, r in enumerate(cur):
            if r is run[0]:
                if (j + len(run) <= len(cur)
                        and all(cur[j + k] is run[k]
                                for k in range(1, len(run)))):
                    return j
                return None
        return None

    def _compact_offlock(self, pick, *, full: bool) -> bool:
        """Shared snapshot -> off-lock merge -> revalidated-swap engine
        behind compact()/compact_level()/compact_out_of_order() (the PR 3
        flush publish discipline applied to background rewrites).

        `pick(files)` inspects an immutable snapshot and returns the
        contiguous run [i0, i0+n) to merge, or None for nothing to do.
        `full=True` collapses the run into a file under a FRESH sequence
        number; `full=False` lands the output at the run's first path
        (in-place run merge, file order — and with it timestamp LWW
        rank — preserved).

        Locking: the snapshot (and, for a full merge, the output seq
        reservation) happens under `_flush_lock` + `_lock`; the merge,
        encode and fsync run with NO lock held, so ingest/flush/queries
        never stall behind a compaction.  The seq-order == publish-order
        rule survives because the output seq is reserved BEFORE going
        off-lock, exactly like flush reserves its path: a flush that
        publishes mid-merge takes a strictly higher seq, so the merged
        (older) rows can never outrank it by name on reopen.  The swap
        re-acquires both locks and revalidates the snapshot by identity —
        files appended meanwhile (flush publishes) are preserved after
        the spliced output; a vanished input (quarantine, delete or
        downsample rewrite) aborts the whole merge (output removed,
        inputs untouched, next tick retries) because publishing it could
        resurrect rows the concurrent rewrite dropped."""
        with self._flush_lock, self._lock:
            files = list(self._files)
            sel = pick(files)
            if sel is None:
                return False
            i0, n = sel
            run = files[i0:i0 + n]
            if full:
                out_path = os.path.join(
                    self.path, f"{self._next_file_seq:08d}.tsf")
                self._next_file_seq += 1
            else:
                out_path = run[0].path
        # merge into a `.merge` temp OFF both locks: invisible to queries
        # and swept by _load_files if we crash before the swap
        tmp = out_path + ".merge"
        w = TSFWriter(tmp, kind="compact")
        tidx = _TextSidecar()
        try:
            self._merge_readers(run, w, tidx)
            w.finish()  # atomically lands at tmp, fsynced
        except CorruptFile as e:
            # damaged merge input: quarantine it so the NEXT compaction
            # (and every query) proceeds without it — merging a corrupt
            # block into the output would launder the damage past its
            # checksum forever
            w.abort()
            self.note_corrupt(e)
        except BaseException:
            w.abort()
            raise
        # self-verify the output OFF-lock before it may replace an
        # input: an in-place merge clobbers run[0] at the swap, so a
        # torn write / bitflip on the output (diskfault tier) must
        # abort HERE with every input intact — publishing first and
        # trusting read-path CRCs would quarantine the merged file
        # and lose the run's rows on a single replica
        try:
            rv = TSFReader(tmp)
            try:
                for loc in rv.data_locs():
                    rv.verify_block(loc)
            finally:
                rv.close()
        except Exception:  # noqa: BLE001 — any unreadable output aborts
            try:
                os.remove(tmp)
            except OSError:
                pass
            _STATS.incr("compact", "output_verify_aborts")
            return False
        published = False
        try:
            _fp("compact-before-replace")
            with self._flush_lock, self._lock:
                j = self._find_run(self._files, run)
                if j is None:
                    # input vanished mid-merge (quarantine / rewrite):
                    # abort — the next tick retries over the new set
                    _STATS.incr("compact", "swap_aborts")
                    return False
                os.replace(tmp, out_path)
                _fp("compact-after-replace")
                published = True
                tidx.write(out_path)
                new_reader = self._adopt(TSFReader(out_path))
                self._files = (
                    self._files[:j] + [new_reader] + self._files[j + n:]
                )
                self._tidx_cache = {}
                _fp("compact-before-retire")  # new set live, old not gone
                if full:
                    _retire_files(run)
                else:
                    _retire_files(run[1:])  # old run[0] reader keeps its fd
                    # run[0]'s OLD reader was replaced in place (same
                    # path, new generation): its path needs no unlink,
                    # but its cached decoded columns can never hit again
                    # and would otherwise pin budget forever
                    colcache.GLOBAL.invalidate_gens([run[0].gen])
            _STATS.incr("compact", "offlock_merges")
            return True
        finally:
            if not published:
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    def compact(self, max_files: int = 1) -> bool:
        """Full merge of immutable files (level compaction analogue,
        reference engine/immutable/compact.go LevelCompact:120). Rewrites
        all chunks per series merged+deduped into one file under a fresh
        sequence number. Returns whether a merge happened (False both for
        nothing-to-do and for a merge aborted by the revalidating swap)."""
        def pick(files):
            if len(files) <= max_files:
                return None
            return (0, len(files))

        return self._compact_offlock(pick, full=True)

    @staticmethod
    def _file_level(path: str) -> int:
        """Size-tiered level: L0 < 1MB, each level 8x larger (reference:
        immutable LevelCompact's level groups, compact.go:120 — here the
        level derives from size, no extra metadata)."""
        import math

        try:
            size = os.path.getsize(path)
        except OSError:
            return 0
        if size < (1 << 20):
            return 0
        return 1 + int(math.log(size / (1 << 20), 8))

    def compact_level(self, fanout: int = 4) -> bool:
        """Merge ONE run of >= fanout consecutive same-level files into a
        single file, preserving file order (the merged output replaces the
        run's FIRST file in place, so timestamp last-write-wins dedup
        across remaining files stays correct). O(run) per call instead of
        the full-merge's O(shard) — bounded write amplification."""
        fanout = max(2, fanout)  # fanout=1 would rewrite a file in place

        def pick(files):
            if len(files) < fanout:
                return None
            levels = [self._file_level(r.path) for r in files]
            run_start = run_len = 0
            for i in range(len(levels)):
                if i > 0 and levels[i] == levels[i - 1]:
                    run_len += 1
                else:
                    run_start, run_len = i, 1
                if run_len >= fanout:
                    # merge exactly `fanout` files per call: bounded work,
                    # deterministic, and repeated ticks converge
                    return (run_start, fanout)
            return None

        return self._compact_offlock(pick, full=False)

    def has_time_overlap(self) -> bool:
        """True when any two immutable files' time ranges overlap (the
        out-of-order state that inflates every read with merge work)."""
        with self._lock:
            ranges = sorted(
                (r.tmin, r.tmax) for r in self._files if r.tmin is not None
            )
        for (a_lo, a_hi), (b_lo, b_hi) in zip(ranges, ranges[1:]):
            if b_lo <= a_hi:
                return True
        return False

    def compact_out_of_order(self, max_files: int = 4) -> bool:
        """Merge time-OVERLAPPING files regardless of level (reference:
        engine/immutable/merge_out_of_order.go).  Late-arriving data
        lands in new files whose ranges overlap old ones; leveled
        compaction alone only merges once >= fanout same-level files
        pile up, so overlap — and with it per-read merge amplification —
        could persist indefinitely.  Merges the contiguous run from the
        first overlapping file toward its overlap partner, capped at
        `max_files` per call; repeated calls converge to disjoint
        ranges."""
        def pick(files):
            if len(files) < 2:
                return None
            ranges = [(r.tmin, r.tmax) for r in files]
            for i in range(len(ranges)):
                if ranges[i][0] is None:
                    continue
                for j in range(i + 1, len(ranges)):
                    if ranges[j][0] is None:
                        continue
                    if (ranges[j][0] <= ranges[i][1]
                            and ranges[i][0] <= ranges[j][1]):
                        # the run must stay contiguous (an intervening
                        # file's rows must not change rank relative to
                        # the merge output)
                        return (i, min(j - i + 1, max(2, max_files)))
            return None

        return self._compact_offlock(pick, full=False)

    def rewrite_downsampled(self, every_ns: int, field_aggs: dict | None = None) -> int:
        """Rewrite this shard at `every_ns` resolution (reference:
        engine_downsample StartDownSampleTask). Returns rows written.
        Flushes the memtable first; replaces all files atomically at the
        end (write-new-then-swap, reference compaction_file_info.go)."""
        from opengemini_tpu.storage.downsample import downsample_records

        # _flush_lock FIRST (see __init__ lock-order note): the inline
        # flush below re-enters it, and holding it for the whole rewrite
        # keeps a concurrent off-lock flush from publishing a pre-rewrite
        # snapshot AFTER the file-set swap resurrects dropped rows
        # audited (lockdep): unlike compaction (now fully off-lock, see
        # _compact_offlock), this rewrite derives its output from the
        # LIVE memtable+file state, so it must exclude ingest for its
        # whole read-rewrite-swap span — the exemption stays audited
        with lockdep.allow_blocking("downsample rewrite under shard lock"), \
                self._flush_lock, self._lock:
            self.flush()
            path = os.path.join(self.path, f"{self._next_file_seq:08d}.tsf")
            w = TSFWriter(path, kind="downsample")
            rows = 0
            # schema changes are staged and applied only after the new file
            # is durable — a mid-rewrite failure must not leave in-memory
            # schemas diverged from on-disk (still raw) data
            staged_schemas: dict[str, dict] = {}
            try:
                for mst in self.measurements():
                    per_sid: dict[int, Record] = {}
                    for sid in sorted(self.index.series_ids(mst)):
                        rec = self.read_series(mst, sid)
                        if len(rec):
                            per_sid[sid] = rec
                    out, new_schema = downsample_records(
                        per_sid, self.schema(mst), self.tmin, self.tmax,
                        every_ns, field_aggs,
                    )
                    staged_schemas[mst] = new_schema
                    for sid in sorted(out):
                        w.add_chunk(mst, sid, out[sid])
                        rows += len(out[sid])
                w.finish()
            except BaseException:
                w.abort()
                raise
            _TextSidecar().write(path)  # downsampled output drops strings
            self.schemas.update(staged_schemas)
            self._next_file_seq += 1
            old = self._files
            self._files = [self._adopt(TSFReader(path))]
            self._tidx_cache = {}
            _retire_files(old)
            self._note_mutation(self.tmin, self.tmax)  # after swap (see delete_data)
            self.ledger.dirty = True  # content rebased: counts no longer reconcile
            return rows

    def delete_data(
        self,
        measurement: str,
        sids: set[int] | None = None,
        tmin: int | None = None,
        tmax: int | None = None,
    ) -> None:
        """Delete rows (whole measurement, whole series, or a time range)
        by rewriting immutable files without the deleted rows — the
        reference's drop/delete paths also rewrite/tombstone immutable data
        (engine DropMeasurement / DeleteSeries). Flushes first so the
        memtable participates."""
        # _flush_lock first: see rewrite_downsampled
        # audited (lockdep): like rewrite_downsampled (and unlike the
        # off-lock compactions), the rewrite reads live state and must
        # exclude ingest end-to-end — the exemption stays audited
        with lockdep.allow_blocking("delete rewrite under shard lock"), \
                self._flush_lock, self._lock:
            self.flush()
            if measurement not in self.measurements():
                return
            if sids is not None:
                sids = set(sids) & self.index.series_ids(measurement)
                if not sids:
                    return
            lo = tmin if tmin is not None else -(2**62)
            hi = tmax if tmax is not None else 2**62
            full_series_delete = tmin is None and tmax is None
            path = os.path.join(self.path, f"{self._next_file_seq:08d}.tsf")
            w = TSFWriter(path, kind="delete")
            wrote = False
            try:
                for mst in self.measurements():
                    for sid in sorted(self.index.series_ids(mst)):
                        rec = self.read_series(mst, sid)
                        if len(rec) == 0:
                            continue
                        if mst == measurement and (sids is None or sid in sids):
                            if full_series_delete:
                                continue
                            keep = (rec.times < lo) | (rec.times >= hi)
                            if not keep.any():
                                continue
                            rec = rec.take(np.nonzero(keep)[0])
                        w.add_chunk(mst, sid, rec)
                        wrote = True
                w.finish()
            except BaseException:
                w.abort()
                raise
            self._next_file_seq += 1
            old = self._files
            self._files = [self._adopt(TSFReader(path))] if wrote else []
            if not wrote:
                os.remove(path)
            _retire_files(old)
            self.ledger.dirty = True  # rows dropped: accounting rebased
            # version bump AFTER the swap: a concurrent query that scanned
            # the old files must cache under the OLD version so the next
            # execution invalidates it (bump-before would let pre-delete
            # rows be served from cache under the post-delete version)
            self._note_mutation(
                tmin if tmin is not None else self.tmin,
                tmax if tmax is not None else self.tmax)
            # index + schema cleanup for fully-deleted series
            if full_series_delete:
                doomed = sids if sids is not None else self.index.series_ids(measurement)
                self.index.remove_sids(set(doomed))
                if not self.index.series_ids(measurement):
                    self.schemas.pop(measurement, None)

    # -- read path ----------------------------------------------------------

    def _scan_state(self) -> tuple[list, list]:
        """(files, memtables oldest → newest, live last) in ONE lock
        acquisition: a flush publish swaps (append file, pop frozen)
        atomically under the same lock, so a reader sees the rows in the
        frozen snapshot or in the new file — never in neither."""
        with self._lock:
            mems = [m for m, _seg in self._frozen]
            mems.append(self.mem)
            return list(self._files), mems

    def _mem_parts(self) -> list:
        """Memtable snapshots a read must merge, oldest → newest (frozen
        flush snapshots first, live memtable last).  LOCK-FREE: _frozen
        is an immutable tuple replaced on change, so per-series hot
        paths pay one attribute read, not a lock acquisition."""
        return [m for m, _seg in self._frozen] + [self.mem]

    def mem_overlaps_range(self, sid: int, tmin: int, tmax: int) -> bool:
        """Does ANY in-memory part (frozen snapshots or live memtable)
        hold rows of `sid` in [tmin, tmax]?  Probes each part separately
        — no merge, no lock — for the per-series fast-path checks."""
        for m in self._mem_parts():
            rec = m.record_for(sid)
            if rec is not None and len(rec.slice_time(tmin, tmax)):
                return True
        return False

    def mem_record_for(self, sid: int):
        """Merged in-memory rows of one series across frozen flush
        snapshots + the live memtable (newest last, last-write-wins) —
        what `self.mem.record_for` meant before off-lock flush."""
        recs = [r for r in (m.record_for(sid) for m in self._mem_parts())
                if r is not None]
        if not recs:
            return None
        return recs[0] if len(recs) == 1 else merge_sorted_records(recs)

    def mem_sids_for(self, measurement: str) -> set[int]:
        out: set[int] = set()
        for m in self._mem_parts():
            out |= m.sids_for(measurement)
        return out

    def mem_time_range(self) -> tuple[int | None, int | None]:
        """(min, max) ns across frozen + live memtables (None = no rows)."""
        tmin = tmax = None
        for m in self._mem_parts():
            if m.min_time is not None:
                tmin = m.min_time if tmin is None else min(tmin, m.min_time)
                tmax = m.max_time if tmax is None else max(tmax, m.max_time)
        return tmin, tmax

    def mem_backlog_bytes(self) -> int:
        """Un-flushed resident bytes: live + frozen memtables plus the
        live WAL log.  LOCK-FREE (one _frozen tuple read + int reads) —
        the resource governor polls this on every governed /write
        (utils/governor.py write watermark; engine sums it per process)."""
        return (sum(m.backlog_bytes for m in self._mem_parts())
                + self.wal.backlog_bytes)

    def measurements(self) -> list[str]:
        msts = set(self.index.measurements())
        for r in self._files:
            msts.update(r.measurements())
        return sorted(msts)

    def schema(self, measurement: str) -> dict:
        return dict(self.schemas.get(measurement, {}))

    def file_chunks(self, measurement: str, sids=None, tmin=None, tmax=None):
        """[(reader, ChunkMeta)] oldest file first — the merge order that
        makes last-write-wins correct."""
        out = []
        for r in self._files:
            for c in r.chunks(measurement, sids, tmin, tmax):
                out.append((r, c))
        return out

    def approx_rows(self, measurement: str, tmin=None, tmax=None
                    ) -> tuple[int, int]:
        """(row count, chunk count) for the measurement in the time range,
        from chunk metadata + memtable — no decode. Over-counts rows of
        chunks straddling the range edges; the scan-slice planner only
        needs the order of magnitude."""
        rows = 0
        chunks = 0
        files, mems = self._scan_state()
        for r in files:
            for c in r.chunks(measurement, None, tmin, tmax):
                rows += c.rows
                chunks += 1
        # memtable rows (frozen flush snapshots included) count whole
        # (order-of-magnitude estimate; the memtable has no
        # per-measurement row bookkeeping)
        return rows + sum(len(m) for m in mems), chunks

    def text_match_sids(self, mst: str, field: str, token: str):
        """Series whose PERSISTED rows may contain `token` in `field`
        (pruning set; rows are verified exactly afterwards), or None when
        any file predates the sidecar format (no pruning possible).
        Memtable rows are unindexed — callers must union live-memtable
        sids before intersecting."""
        import json as _json

        from opengemini_tpu.native.textindex import query_grams

        if token.isascii():
            # pure-ASCII terms are whole lowercased tokens in the index
            grams = [token.lower()]
        else:
            # mixed/CJK terms: prune on the NON-ASCII single-char grams
            # only (raw bytes — the index never case-folds non-ASCII).
            # ASCII fragments of a mixed term may be substrings of longer
            # indexed tokens ('log' inside 'logfile') and must not
            # constrain the pruning set.
            grams = [g for g in query_grams(token) if not g.isascii()]
        out: set[int] = set()
        # whole lookup under the shard lock: compact() swaps the file set
        # and resets the cache; populating the cache outside the lock
        # would re-insert entries for retired files forever (RLock —
        # sidecar JSONs are small, so the hold is short)
        with self._lock:
            for r in self._files:
                cached = self._tidx_cache.get(r.path, False)
                if cached is False:
                    try:
                        with open(_tidx_path(r.path), encoding="utf-8") as f:
                            cached = _json.load(f)
                    except (OSError, ValueError):
                        cached = None
                    self._tidx_cache[r.path] = cached
                if cached is None:
                    return None
                toks = cached.get(mst, {}).get(field, {})
                # multi-gram terms (CJK) intersect their grams' postings
                per_file: set[int] | None = None
                for g in grams:
                    got = set(toks.get(g, []))
                    per_file = got if per_file is None else per_file & got
                out.update(per_file or ())
        return out

    def read_series(
        self,
        measurement: str,
        sid: int,
        tmin: int | None = None,
        tmax: int | None = None,
        fields: list[str] | None = None,
    ) -> Record:
        """Merged view of one series: immutable chunks (oldest first) +
        memtable last, deduped last-wins, then time-sliced. Multi-chunk
        decodes fan out across the scan pool (storage/scanpool.py) in
        file order; KILL QUERY still interrupts mid-series — the pool's
        ordered yield re-checks the tracker per chunk exactly like the
        old serial loop did (reference:
        ts-store/transport/query/manager.go:130 IsKilled checked inside
        cursor loops)."""
        files, mems = self._scan_state()
        chunks = [(r, c) for r in files
                  for c in r.chunks(measurement, {sid}, tmin, tmax)]
        n_fields = len(fields) if fields is not None else None
        # same deferred-decode contract as read_series_bulk: eligible
        # value blocks come back as still-encoded EncodedColumns so the
        # grid freeze's offload planner (query/offload.py) keeps the
        # device route available; every host consumer decodes lazily,
        # bit-identically
        from opengemini_tpu.ops import device_decode as _devdec

        encoded_ok = _devdec.active()

        def decode(r, c):
            if c.packed:
                return r.read_packed_sid(measurement, c, sid, fields,
                                         encoded_ok=encoded_ok)
            return r.read_chunk(measurement, c, fields,
                                encoded_ok=encoded_ok)

        # decoded-column cache consult BEFORE pool dispatch
        # (storage/colcache.py): fully-cached chunks assemble inline and
        # never enter the pool; misses fill through it, so the in-flight
        # backpressure budget keeps applying to everything that decodes
        recs: list = [None] * len(chunks)
        jobs, ests, miss_at = [], [], []
        for i, (r, c) in enumerate(chunks):
            # a fully-cached scan submits nothing to the pool, so the
            # pool's per-chunk kill points never run — keep KILL QUERY
            # responsive per chunk on the warm path too
            _TRACKER.check()
            got = (r.read_packed_sid_if_cached(measurement, c, sid, fields)
                   if c.packed
                   else r.read_chunk_if_cached(measurement, c, fields))
            if got is not None:
                recs[i] = got
            else:
                jobs.append(lambda r=r, c=c: decode(r, c))
                ests.append(scanpool.est_chunk_bytes(c, n_fields))
                miss_at.append(i)
        try:
            for i, out in zip(miss_at, scanpool.map_ordered(jobs, ests)):
                recs[i] = out
        except CorruptFile as e:
            # media damage surfaced mid-scan (block CRC / short read):
            # quarantine the file, fail THIS query cleanly — never
            # return a partial/garbage record
            self.note_corrupt(e)
        # frozen flush snapshots (oldest first) then the live memtable:
        # both are newer than every file, live is newest of all
        for m in mems:
            mem_rec = m.record_for(sid)
            if mem_rec is None:
                continue
            if fields is not None:
                mem_rec = Record(
                    mem_rec.times,
                    {k: v for k, v in mem_rec.columns.items() if k in fields},
                )
            recs.append(mem_rec)
        merged = merge_sorted_records(recs)
        if tmin is not None or tmax is not None:
            lo = tmin if tmin is not None else -(2**63)
            hi = tmax if tmax is not None else 2**63 - 1
            merged = merged.slice_time(lo, hi)
        return merged

    def read_series_bulk(
        self,
        measurement: str,
        sids: np.ndarray,
        tmin: int | None = None,
        tmax: int | None = None,
        fields: list[str] | None = None,
    ) -> tuple[np.ndarray, Record]:
        """Batched multi-series read: (sid_column, record) for every
        requested series, rows grouped by sid and time-sorted within a
        sid, last-write-wins deduped.  Packed chunks decode ONCE for all
        their series — the per-sid Python loop this replaces was the
        measured bottleneck at 1M series (BASELINE.md config #5)."""
        sids = np.asarray(sorted(int(s) for s in sids), dtype=np.int64)
        lo_t = tmin if tmin is not None else -(2**63)
        hi_t = tmax if tmax is not None else 2**63 - 1
        # parts MUST append in file order (oldest first): _merge_bulk_parts
        # ranks later parts as newer for last-write-wins; interleaving
        # packed and per-sid chunks out of file order would let stale
        # rows win
        parts: list[tuple[np.ndarray, Record]] = []
        sid_set = set(int(s) for s in sids)
        files, mems = self._scan_state()
        n_fields = len(fields) if fields is not None else None
        # device-decode bulk path (ops/device_decode.py): eligible value
        # blocks come back as still-encoded EncodedColumns so the grid
        # freeze can ship compressed payloads to the accelerator; any
        # merge/filter/fallback that touches .values host-decodes them
        # bit-identically
        from opengemini_tpu.ops import device_decode as _devdec

        encoded_ok = _devdec.active()

        def decode_packed(r, c):
            s_arr, rec = r.read_packed_bulk(
                measurement, c, fields, sid_filter=sids,
                encoded_ok=encoded_ok)
            return (s_arr, rec) if len(rec) else None

        def decode_single(r, c):
            rec = r.read_chunk(measurement, c, fields,
                               encoded_ok=encoded_ok)
            return (np.full(len(rec), c.sid, np.int64), rec)

        # chunk decodes fan out across the scan pool; map_ordered yields
        # in submission (= file) order, so the parts list is identical to
        # the old serial loop's and last-write-wins ranking is unchanged.
        # Per-chunk kill points live inside map_ordered (see read_series).
        # Fully-cached chunks (decoded-column cache, storage/colcache.py)
        # assemble inline and skip the pool; `slots` keeps file order.
        jobs = []
        ests = []
        slots: list = []
        miss_at = []
        for r in files:
            for c in r.chunks(measurement, None, tmin, tmax):
                if c.packed:
                    if c.smax < sids[0] or c.smin > sids[-1]:
                        continue
                    _TRACKER.check()  # warm-path kill point (see read_series)
                    got = r.read_packed_bulk_if_cached(
                        measurement, c, fields, sid_filter=sids)
                    if got is not None:
                        slots.append(got if len(got[1]) else None)
                        continue
                    jobs.append(lambda r=r, c=c: decode_packed(r, c))
                elif c.sid in sid_set:
                    _TRACKER.check()  # warm-path kill point
                    got = r.read_chunk_if_cached(measurement, c, fields)
                    if got is not None:
                        slots.append(
                            (np.full(len(got), c.sid, np.int64), got))
                        continue
                    jobs.append(lambda r=r, c=c: decode_single(r, c))
                else:
                    continue
                miss_at.append(len(slots))
                slots.append(None)
                ests.append(scanpool.est_chunk_bytes(c, n_fields))
        try:
            for i, part in zip(miss_at, scanpool.map_ordered(jobs, ests)):
                slots[i] = part
        except CorruptFile as e:
            self.note_corrupt(e)  # see read_series
        parts.extend(p for p in slots if p is not None)
        for m in mems:  # frozen snapshots oldest first, live memtable last
            for sid_arr, mem_rec in m.bulk_parts(measurement, sids):
                if fields is not None:
                    mem_rec = Record(
                        mem_rec.times,
                        {k: v for k, v in mem_rec.columns.items()
                         if k in fields},
                    )
                parts.append((sid_arr, mem_rec))
        return _merge_bulk_parts(parts, lo_t, hi_t)

    def content_digest(self) -> dict:
        """Per-measurement logical content digest: {mst: [rows, hash64]}.
        Order-independent (per-series hashes fold with XOR) and keyed by
        canonical series KEYS, never sids (sids differ across replicas).
        Two replicas holding identical logical rows produce identical
        digests regardless of file layout (reference: anti-entropy
        digests for replicated shards, engine/engine_replication.go).
        Cached until the file set or memtable changes."""
        import zlib as _z

        from opengemini_tpu.ingest.line_protocol import series_key

        with self._lock:
            state = (
                tuple((r.path, os.path.getsize(r.path)) for r in self._files
                      if os.path.exists(r.path)),
                tuple(len(m) for m, _seg in self._frozen),
                len(self.mem),
            )
            cached = getattr(self, "_digest_cache", None)
            if cached is not None and cached[0] == state:
                return cached[1]
        out: dict[str, list] = {}
        for mst in self.measurements():
            rows = 0
            acc = 0
            for sid in sorted(self.index.series_ids(mst)):
                rec = self.read_series(mst, sid)
                if not len(rec):
                    continue
                rows += len(rec)
                _m, tags = self.index.series_entry(sid)
                h = _z.crc32(series_key(mst, tags).encode())
                h = _z.crc32(np.ascontiguousarray(rec.times).tobytes(), h)
                for name in sorted(rec.columns):
                    col = rec.columns[name]
                    h = _z.crc32(name.encode(), h)
                    vals = col.values
                    if vals.dtype == object:
                        payload = "\x00".join(
                            "" if v is None else str(v) for v in vals
                        ).encode()
                    else:
                        payload = np.ascontiguousarray(vals).tobytes()
                    h = _z.crc32(payload, h)
                    h = _z.crc32(np.ascontiguousarray(col.valid).tobytes(), h)
                acc ^= h
            if rows:
                out[mst] = [rows, acc]
        with self._lock:
            self._digest_cache = (state, out)
        return out

    def mem_overlaps(self, measurement: str, sid: int) -> bool:
        return any(m.record_for(sid) is not None for m in self._mem_parts())

    def ledger_snapshot(self) -> dict:
        """Consistent acked-vs-durable snapshot (see DurabilityLedger).
        Taken under the shard lock, so a concurrent write or flush
        publish can never show a half-applied state."""
        with self._lock:
            mem_rows = sum(len(m) for m in self._mem_parts())
            return self.ledger.snapshot(mem_rows)

    def close(self) -> None:
        # _flush_lock first: an in-flight off-lock flush finishes (or we
        # get in line ahead of the next one) before handles close
        # audited (lockdep): the final WAL fsync runs under the shard
        # lock — close must be atomic against in-flight writes
        with lockdep.allow_blocking("shard.close shutdown fsyncs"), \
                self._flush_lock, self._lock:
            self.wal.flush()
            self.wal.close()
            self.index.flush()
            self.index.close()
            # retention drops / DROP DATABASE / engine close all arrive
            # here: release every decoded-column cache entry this shard
            # pinned (in-flight readers keep their arrays via refcounts)
            self.drop_cached_columns()
            for r in self._files:
                r.close()

def _retire_files(readers: list) -> None:
    """Unlink replaced immutable files WITHOUT closing their readers:
    in-flight queries hold (reader, chunk) pairs outside the shard lock, and
    POSIX keeps unlinked files readable through existing fds. The fds close
    when the reader objects are garbage-collected after the last query
    releases them (the reference's file-set swap works the same way).
    Decoded-column cache entries of the retired generations drop here too
    (compaction / downsample / delete rewrites); queries mid-scan keep
    any arrays they already hold via normal refcounting."""
    import os as _os

    colcache.GLOBAL.invalidate_gens([r.gen for r in readers])
    for r in readers:
        for p in (r.path, _tidx_path(r.path)):
            try:
                _os.remove(p)
            except OSError:
                pass


def _tidx_path(tsf_path: str) -> str:
    return tsf_path[:-4] + ".tidx" if tsf_path.endswith(".tsf") else tsf_path + ".tidx"


def _quar_marker(tsf_path: str) -> str:
    """Durable quarantine marker path for a damaged immutable file."""
    return tsf_path + ".quar"


class _TextSidecar:
    """Per-file inverted text index over string fields, built as chunks
    are written (reference: the logstore per-segment token index,
    lib/logstore + engine/index/textindex — here a token -> sids map used
    to PRUNE series before decode; rows are still verified exactly)."""

    def __init__(self):
        self.idx: dict[str, dict[str, dict[str, set]]] = {}

    def add(self, mst: str, sid: int, rec) -> None:
        from opengemini_tpu.native.textindex import tokenize
        from opengemini_tpu.record import FieldType

        for name, col in rec.columns.items():
            if col.ftype != FieldType.STRING:
                continue
            toks = self.idx.setdefault(mst, {}).setdefault(name, {})
            for v, ok in zip(col.values, col.valid):
                if ok and isinstance(v, str):
                    for t in set(tokenize(v)):
                        toks.setdefault(t, set()).add(sid)

    def write(self, tsf_path: str) -> None:
        import json as _json

        p = _tidx_path(tsf_path)
        data = {
            m: {f: {t: sorted(s) for t, s in toks.items()}
                for f, toks in flds.items()}
            for m, flds in self.idx.items()
        }
        tmp = p + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            _json.dump(data, f)
        os.replace(tmp, p)  # crash before this: missing sidecar = no prune
