"""Binary TSF chunk-meta codec (format v2).

Reference: engine/immutable/chunk_meta_codec.go — the reference encodes
chunk metadata as packed binary so meta decode cost and memory stay flat
as chunk counts grow; the round-1 zlib-JSON meta decoded every value
into Python objects.  This codec writes the same logical content as the
JSON form in a length-prefixed binary layout and decodes with struct /
frombuffer, no JSON tree.

Layout (all little-endian; str = u16 len + utf8):
  u32 n_measurements
  per measurement:
    str name
    u16 n_fields; per field: str name, u8 ftype
    u32 n_chunks
    per chunk:
      u8 flags (bit0: packed, bit1: has sparse)
      if packed: u64 smin, u64 smax, u64 sid_off, u32 sid_len,
                 [u32 n_sparse; per entry u64 sid, u32 row]
      else:      u64 sid
      u32 rows; i64 tmin; i64 tmax; u64 time_off; u32 time_len
      u16 n_cols
      per col:
        u16 field_index
        u64 v_off, u32 v_len
        u8 has_mask; if set: u64 m_off, u32 m_len
        pre-agg: u32 count; u8 has_minmaxsum;
                 if set: f64 vmin, f64 vmax, f64 vsum
                 u8 n_hist; u32 hist[n_hist]

Pre-agg note: INT columns carry exact int sums in the JSON form; the
binary form stores f64 (2^53 cliff). Columns whose |vsum| exceeds 2^53
set has_minmaxsum=2 and append the three values as decimal strings,
keeping int-exactness.
"""

from __future__ import annotations

import struct

_EXACT_LIMIT = 1 << 53


def _pstr(out: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    out += struct.pack("<H", len(b))
    out += b


def _rstr(buf: bytes, off: int) -> tuple[str, int]:
    (n,) = struct.unpack_from("<H", buf, off)
    off += 2
    return buf[off:off + n].decode("utf-8"), off + n


def encode_meta(meta: dict) -> bytes:
    """meta: the TSFWriter JSON-shaped dict
    {mst: {"schema": {field: int}, "chunks": [chunk json]}} -> bytes."""
    out = bytearray()
    out += struct.pack("<I", len(meta))
    for mst, m in meta.items():
        _pstr(out, mst)
        fields = list(m["schema"].items())
        findex = {name: i for i, (name, _t) in enumerate(fields)}
        out += struct.pack("<H", len(fields))
        for name, ftype in fields:
            _pstr(out, name)
            out += struct.pack("<B", int(ftype))
        chunks = m["chunks"]
        out += struct.pack("<I", len(chunks))
        for c in chunks:
            packed = bool(c.get("packed"))
            sparse = c.get("sparse") or []
            flags = (1 if packed else 0) | (2 if sparse else 0)
            out += struct.pack("<B", flags)
            if packed:
                out += struct.pack("<QQQI", c["smin"], c["smax"],
                                   c["sids"][0], c["sids"][1])
                if sparse:
                    out += struct.pack("<I", len(sparse))
                    for s_, row in sparse:
                        out += struct.pack("<QI", s_, row)
            else:
                out += struct.pack("<Q", c["sid"])
            out += struct.pack("<IqqQI", c["rows"], c["tmin"], c["tmax"],
                               c["time"][0], c["time"][1])
            cols = c["cols"]
            out += struct.pack("<H", len(cols))
            for name, cc in cols.items():
                out += struct.pack("<H", findex[name])
                out += struct.pack("<QI", cc["v"][0], cc["v"][1])
                if cc["m"]:
                    out += struct.pack("<BQI", 1, cc["m"][0], cc["m"][1])
                else:
                    out += struct.pack("<B", 0)
                count, vmin, vmax, vsum, hist = cc["pre"]
                out += struct.pack("<I", count)
                if vmin is None:
                    out += struct.pack("<B", 0)
                elif (isinstance(vsum, int)
                      and (abs(vsum) > _EXACT_LIMIT
                           or abs(int(vmin)) > _EXACT_LIMIT
                           or abs(int(vmax)) > _EXACT_LIMIT)):
                    out += struct.pack("<B", 2)
                    _pstr(out, repr(vmin))
                    _pstr(out, repr(vmax))
                    _pstr(out, repr(vsum))
                else:
                    out += struct.pack("<Bddd", 1, float(vmin), float(vmax),
                                       float(vsum))
                    # int columns round-trip exactly below 2^53; flag the
                    # intness so decode restores int type
                    out += struct.pack(
                        "<B", 1 if isinstance(vsum, int) else 0)
                hist = hist or []
                out += struct.pack("<B", len(hist))
                for h in hist:
                    out += struct.pack("<I", h)
    return bytes(out)


def decode_meta(buf: bytes) -> dict:
    """bytes -> the same JSON-shaped dict encode_meta consumed."""
    off = 0
    (n_msts,) = struct.unpack_from("<I", buf, off)
    off += 4
    meta: dict = {}
    for _ in range(n_msts):
        mst, off = _rstr(buf, off)
        (n_fields,) = struct.unpack_from("<H", buf, off)
        off += 2
        fields = []
        schema = {}
        for _ in range(n_fields):
            name, off = _rstr(buf, off)
            (ftype,) = struct.unpack_from("<B", buf, off)
            off += 1
            fields.append(name)
            schema[name] = ftype
        (n_chunks,) = struct.unpack_from("<I", buf, off)
        off += 4
        chunks = []
        for _ in range(n_chunks):
            (flags,) = struct.unpack_from("<B", buf, off)
            off += 1
            c: dict = {}
            if flags & 1:
                smin, smax, s_off, s_len = struct.unpack_from("<QQQI", buf, off)
                off += 28
                c["packed"] = 1
                c["smin"], c["smax"] = smin, smax
                c["sids"] = [s_off, s_len]
                sparse = []
                if flags & 2:
                    (n_sp,) = struct.unpack_from("<I", buf, off)
                    off += 4
                    for _ in range(n_sp):
                        s_, row = struct.unpack_from("<QI", buf, off)
                        off += 12
                        sparse.append([s_, row])
                c["sparse"] = sparse
            else:
                (c["sid"],) = struct.unpack_from("<Q", buf, off)
                off += 8
            rows, tmin, tmax, t_off, t_len = struct.unpack_from(
                "<IqqQI", buf, off)
            off += 32
            c.update(rows=rows, tmin=tmin, tmax=tmax, time=[t_off, t_len])
            (n_cols,) = struct.unpack_from("<H", buf, off)
            off += 2
            cols = {}
            for _ in range(n_cols):
                (fi,) = struct.unpack_from("<H", buf, off)
                off += 2
                v_off, v_len = struct.unpack_from("<QI", buf, off)
                off += 12
                (has_mask,) = struct.unpack_from("<B", buf, off)
                off += 1
                mloc = None
                if has_mask:
                    m_off, m_len = struct.unpack_from("<QI", buf, off)
                    off += 12
                    mloc = [m_off, m_len]
                (count,) = struct.unpack_from("<I", buf, off)
                off += 4
                (pre_kind,) = struct.unpack_from("<B", buf, off)
                off += 1
                vmin = vmax = vsum = None
                if pre_kind == 1:
                    vmin, vmax, vsum = struct.unpack_from("<ddd", buf, off)
                    off += 24
                    (is_int,) = struct.unpack_from("<B", buf, off)
                    off += 1
                    if is_int:
                        vmin, vmax, vsum = int(vmin), int(vmax), int(vsum)
                elif pre_kind == 2:
                    s1, off = _rstr(buf, off)
                    s2, off = _rstr(buf, off)
                    s3, off = _rstr(buf, off)
                    vmin, vmax, vsum = int(s1), int(s2), int(s3)
                (n_hist,) = struct.unpack_from("<B", buf, off)
                off += 1
                hist = None
                if n_hist:
                    hist = list(struct.unpack_from(f"<{n_hist}I", buf, off))
                    off += 4 * n_hist
                cols[fields[fi]] = {
                    "v": [v_off, v_len], "m": mloc,
                    "pre": [count, vmin, vmax, vsum, hist],
                }
            c["cols"] = cols
            chunks.append(c)
        meta[mst] = {"schema": schema, "chunks": chunks}
    return meta
