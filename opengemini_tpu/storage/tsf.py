"""TSF — the immutable columnar file format (TSSP analogue).

Reference: engine/immutable/tssp_file.go:65-146 (trailer + chunk meta +
bloom), pre_aggregation.go:40 (per-column-segment count/min/max/sum that
lets aggregate queries skip data blocks entirely).

Layout (format revision 2 — "survive the disk"):
    "OGTSF02\\n"                      8-byte magic
    column blocks, each SEALED: [encoded bytes][u32 crc32] — the
          end-to-end per-block checksum verified on every decode
          (self-describing payloads, see storage/encoding.py)
    meta: "BM02" + zlib(binary chunk meta — storage/chunkmeta.py,
          reference chunk_meta_codec.go); legacy zlib(JSON) still reads
    trailer: [u64 meta_off][u32 meta_len][u32 meta_crc]"OGTSFEND"

Revision 1 files ("OGTSF01\\n", CRC-less blocks) remain readable: the
head magic selects per-block verification, so a flipped bit in a v2
data block raises CorruptFile at decode time — before any wrong value
reaches a query — instead of silently decoding garbage (or crashing the
codec).  Block locs cover the sealed length; `_read` strips the seal.

Chunks are either one series' rows for one flush (time + field columns,
validity masks, numeric pre-aggregation) or PK-sorted packed
multi-series blocks (colstore layout, see add_packed_chunk).
"""

from __future__ import annotations

import itertools
import json
import os
import struct
import threading
from opengemini_tpu.utils import lockdep
import time
import zlib
from collections import OrderedDict

import numpy as np

from opengemini_tpu.record import Column, EncodedColumn, FieldType, Record
from opengemini_tpu.storage import colcache, diskfault, encodepool, encoding
from opengemini_tpu.utils.bloom import BloomFilter
from opengemini_tpu.utils.stats import GLOBAL as _STATS

MAGIC = b"OGTSF01\n"   # revision 1: CRC-less blocks (read-only legacy)
MAGIC2 = b"OGTSF02\n"  # revision 2: per-block crc32 seals (written)
END_MAGIC = b"OGTSFEND"
_TRAILER = struct.Struct("<QII")
_BLOCK_CRC = struct.Struct("<I")


HIST_BINS = 32


class PreAgg:
    """count/min/max/sum of the valid values of one numeric column chunk,
    plus a small equi-width histogram — the sketch that serves
    percentile_approx() from metadata alone (reference: OGSketch
    quantile sketches, engine/executor/ogsketch.go, except persisted
    per chunk so queries skip data blocks entirely)."""

    __slots__ = ("count", "vmin", "vmax", "vsum", "hist")

    def __init__(self, count: int, vmin, vmax, vsum, hist=None):
        self.count = count
        self.vmin = vmin
        self.vmax = vmax
        self.vsum = vsum
        self.hist = hist  # HIST_BINS int counts over [vmin, vmax], or None

    @classmethod
    def of(cls, col: Column) -> "PreAgg | None":
        if col.ftype not in (FieldType.FLOAT, FieldType.INT):
            return cls(int(col.valid.sum()), None, None, None)
        vals = col.values[col.valid]
        if len(vals) == 0:
            return cls(0, None, None, None)
        vmin = vals.min().item()
        vmax = vals.max().item()
        finite = np.isfinite(np.asarray(vals, dtype=np.float64))
        hist = None
        if finite.all() and vmax > vmin:
            hist = np.histogram(
                vals.astype(np.float64), bins=HIST_BINS, range=(vmin, vmax)
            )[0].tolist()
        return cls(len(vals), vmin, vmax, vals.sum().item(), hist)

    def to_json(self):
        return [self.count, self.vmin, self.vmax, self.vsum, self.hist]

    @classmethod
    def from_json(cls, j) -> "PreAgg":
        # older files carry 4-element pre-agg entries (no histogram)
        return cls(*j) if len(j) >= 5 else cls(*j, None)


class ChunkMeta:
    __slots__ = ("sid", "rows", "tmin", "tmax", "time_loc", "cols",
                 "smin", "smax", "sid_loc", "sparse")

    def __init__(self, sid, rows, tmin, tmax, time_loc, cols,
                 smin=None, smax=None, sid_loc=None, sparse=None):
        self.sid = sid  # None for packed (multi-series) chunks
        self.rows = rows
        self.tmin = tmin
        self.tmax = tmax
        self.time_loc = time_loc  # (off, len)
        # field -> {"v": (off,len), "m": (off,len)|None, "pre": PreAgg}
        self.cols = cols
        # packed chunks (PK-sorted column store, reference
        # engine/immutable/colstore): rows sorted by (sid, time); the
        # sid column is its own block and `sparse` is the sparse
        # primary-key index [(sid, row_offset)] every SPARSE_K rows
        # (reference engine/index/sparseindex/primary_index.go)
        self.smin = smin
        self.smax = smax
        self.sid_loc = sid_loc
        self.sparse = sparse

    @property
    def packed(self) -> bool:
        return self.sid is None


# packed-chunk tuning: pack when a measurement flushes many series; the
# sparse PK index records every SPARSE_K-th row boundary
PACK_MIN_SERIES = 64
PACK_ROWS = 131072
SPARSE_K = 1024


def _col_nbytes(col: Column) -> int:
    """Encode-input size estimate of one column (pipeline backpressure)."""
    values = col.values
    if getattr(values, "dtype", None) is not None and values.dtype == object:
        nb = 32 * len(values)
    else:
        nb = int(getattr(values, "nbytes", 8 * len(values)))
    return nb + int(col.valid.nbytes)


class TSFWriter:
    """Writes one TSF file.  Chunk encodes pipeline through the encode
    pool (storage/encodepool.py): add_chunk submits the pure
    numpy/zlib/gorilla encode of chunk N+1 while chunk N's blocks are
    written, draining in submission order so offsets — and file bytes —
    are identical to the serial path (OGT_ENCODE_WORKERS=1 degrades to
    exactly that path).  `kind` tags the /debug/vars counters
    ({kind}_encode_ns / {kind}_write_ns / {kind}_bytes under `tsfwrite`)
    so flush vs compaction vs downsample encode time stays attributable.

    NOT thread-safe: one writer thread owns the file (offsets and meta
    are assigned at drain time on that thread)."""

    def __init__(self, path: str, kind: str = "write"):
        self.path = path
        self._kind = kind
        self._tmp = path + ".tmp"
        self._f = open(self._tmp, "wb")
        self._f.write(MAGIC2)
        self._off = len(MAGIC2)
        # mst -> {"schema": {field: int}, "chunks": [meta json]}
        self._meta: dict = {}
        self._pipe = encodepool.OrderedEncodePipe(self._write_encoded)

    def _write_block(self, buf: bytes) -> tuple[int, int]:
        """Seal + write one block: [payload][u32 crc32(payload)] — the
        ONE chokepoint every data block flows through, so the end-to-end
        checksum can never be skipped by a new writer path.  Offsets and
        lengths cover the sealed bytes; `TSFReader._read` verifies and
        strips.  The diskfault hook may tear/corrupt what the media
        actually holds — the writer still accounts the full sealed
        length (a real torn sector lies to the writer the same way)."""
        sealed = buf + _BLOCK_CRC.pack(zlib.crc32(buf))
        off = self._off
        out = sealed
        if diskfault.armed():
            out = diskfault.on_write(self.path, sealed,
                                     site="tsf-block-write")
        self._f.write(out)
        if len(out) != len(sealed):  # torn write: keep file offsets true
            self._f.seek(off + len(sealed))
        self._off += len(sealed)
        return (off, len(sealed))

    def _check_schema(self, m: dict, rec: Record) -> None:
        """Synchronous (submit-time) schema merge: a type conflict raises
        at the add_chunk call that introduced it, exactly like the serial
        path — never later from inside a drained encode job."""
        schema = m["schema"]
        for name, col in rec.columns.items():
            have = schema.get(name)
            if have is None:
                schema[name] = int(col.ftype)
            elif have != int(col.ftype):
                raise ValueError(
                    f"field type conflict in file for {name!r}: {have} vs {int(col.ftype)}"
                )

    @staticmethod
    def _encode_job(measurement: str, sid, sids, rec: Record):
        """Pure per-chunk encode (runs on a pool worker): every buffer and
        pre-agg this chunk needs, NO offsets — those are assigned at
        drain time in submission order."""
        t0 = time.perf_counter_ns()
        time_buf = encoding.encode_ints(rec.times)
        sid_buf = encoding.encode_ints(sids) if sids is not None else None
        cols = []
        for name, col in rec.columns.items():
            vbuf, mbuf = encoding.encode_column(col)
            cols.append((name, vbuf, mbuf, PreAgg.of(col).to_json()))
        return (measurement, sid, sids, rec, time_buf, sid_buf, cols,
                time.perf_counter_ns() - t0)

    def _write_encoded(self, item) -> None:
        """Drain stage (writer thread): assign offsets, write blocks,
        append the chunk's meta entry."""
        (measurement, sid, sids, rec, time_buf, sid_buf, cols,
         encode_ns) = item
        t0 = time.perf_counter_ns()
        m = self._meta[measurement]
        time_loc = self._write_block(time_buf)
        entry: dict = {
            "rows": len(rec),
            "time": time_loc,
        }
        if sid_buf is not None:
            entry["packed"] = 1
            entry["smin"] = int(sids[0])
            entry["smax"] = int(sids[-1])
            entry["sids"] = self._write_block(sid_buf)
            entry["sparse"] = [
                [int(sids[i]), i] for i in range(0, len(sids), SPARSE_K)]
            entry["tmin"] = int(rec.times.min())
            entry["tmax"] = int(rec.times.max())
        else:
            entry["sid"] = sid
            entry["tmin"] = int(rec.times[0])
            entry["tmax"] = int(rec.times[-1])
        out_cols = {}
        nbytes = len(time_buf) + (len(sid_buf) if sid_buf else 0)
        for name, vbuf, mbuf, pre in cols:
            vloc = self._write_block(vbuf)
            mloc = self._write_block(mbuf) if mbuf else None
            nbytes += len(vbuf) + (len(mbuf) if mbuf else 0)
            out_cols[name] = {"v": vloc, "m": mloc, "pre": pre}
        entry["cols"] = out_cols
        m["chunks"].append(entry)
        _STATS.incr("tsfwrite", f"{self._kind}_encode_ns", encode_ns)
        _STATS.incr("tsfwrite", f"{self._kind}_write_ns",
                    time.perf_counter_ns() - t0)
        _STATS.incr("tsfwrite", f"{self._kind}_bytes", nbytes)

    def add_chunk(self, measurement: str, sid: int, rec: Record) -> None:
        """rec must be time-sorted ascending and deduped.  The record's
        arrays must stay unmutated until finish()/abort() — the encode
        job may run concurrently (flush encodes a FROZEN memtable;
        compaction/downsample records are freshly built)."""
        if len(rec) == 0:
            return
        m = self._meta.setdefault(measurement, {"schema": {}, "chunks": []})
        self._check_schema(m, rec)
        est = int(rec.times.nbytes) + sum(
            _col_nbytes(c) for c in rec.columns.values())
        self._pipe.submit(
            lambda: self._encode_job(measurement, sid, None, rec), est)

    def add_packed_chunk(self, measurement: str, sids: np.ndarray,
                         rec: Record) -> None:
        """One multi-series chunk: rows sorted by (sid, time) — the
        PK-sorted column store layout (reference:
        engine/immutable/colstore/chunk_builder.go).  `sids` is int64,
        aligned with rec rows, non-decreasing; rows of one sid are
        time-sorted and deduped."""
        if len(rec) == 0:
            return
        m = self._meta.setdefault(measurement, {"schema": {}, "chunks": []})
        self._check_schema(m, rec)
        est = int(rec.times.nbytes) + int(sids.nbytes) + sum(
            _col_nbytes(c) for c in rec.columns.values())
        self._pipe.submit(
            lambda: self._encode_job(measurement, None, sids, rec), est)

    def finish(self) -> None:
        from opengemini_tpu.storage import chunkmeta

        self._pipe.drain()  # every chunk lands before the meta freezes
        # binary chunk meta (format v2, reference chunk_meta_codec.go):
        # decode cost stays flat as chunk counts grow; v1 zlib-JSON files
        # remain readable
        meta_buf = b"BM02" + zlib.compress(chunkmeta.encode_meta(self._meta), 1)
        meta_off = self._off
        tail = (meta_buf
                + _TRAILER.pack(meta_off, len(meta_buf), zlib.crc32(meta_buf))
                + END_MAGIC)
        if diskfault.armed():
            tail = diskfault.on_write(self.path, tail, site="tsf-meta-write")
        self._f.write(tail)
        self._f.flush()
        if diskfault.armed():
            diskfault.on_fsync(self.path, site="tsf-fsync")
        os.fsync(self._f.fileno())
        self._f.close()
        os.replace(self._tmp, self.path)  # atomic visibility

    def abort(self) -> None:
        self._pipe.abort()
        self._f.close()
        if os.path.exists(self._tmp):
            os.remove(self._tmp)


# process-global file generations: a reader opened over a path that a
# compaction later rewrites IN PLACE (os.replace) gets a fresh number, so
# a (generation, chunk) cache key can never alias stale decoded data
_READER_GEN = itertools.count(1)


class TSFReader:
    def __init__(self, path: str):
        self.path = path
        # decoded-column cache identity (storage/colcache.py): gen is the
        # invalidation handle; owner_ns is stamped by the owning Shard
        self.gen = next(_READER_GEN)
        self.owner_ns: int | None = None
        self._f = open(path, "rb")
        self._f.seek(0, os.SEEK_END)
        size = self._f.tell()
        tail = _TRAILER.size + len(END_MAGIC)
        if size < len(MAGIC) + tail:
            raise CorruptFile(path, "too small")
        head = os.pread(self._f.fileno(), len(MAGIC), 0)
        if diskfault.armed():
            head = diskfault.on_read(path, head, site="tsf-open-read")
        if head == MAGIC2:
            # revision 2: every block carries a crc32 seal, verified on
            # every decode (including colcache fills) in _read
            self.block_crc = True
        elif head == MAGIC:
            self.block_crc = False  # legacy: readable, nothing to verify
        else:
            raise CorruptFile(path, "bad magic")
        self._f.seek(size - tail)
        trailer = self._f.read(tail)
        if diskfault.armed():
            trailer = diskfault.on_read(path, trailer, site="tsf-open-read")
        if trailer[-len(END_MAGIC) :] != END_MAGIC:
            raise CorruptFile(path, "bad end magic")
        meta_off, meta_len, meta_crc = _TRAILER.unpack(trailer[: _TRAILER.size])
        self._f.seek(meta_off)
        meta_buf = self._f.read(meta_len)
        if diskfault.armed():
            meta_buf = diskfault.on_read(path, meta_buf, site="tsf-open-read")
        if zlib.crc32(meta_buf) != meta_crc:
            raise CorruptFile(path, "meta crc mismatch")
        if meta_buf[:4] == b"BM02":
            from opengemini_tpu.storage import chunkmeta

            raw = chunkmeta.decode_meta(zlib.decompress(meta_buf[4:]))
        else:
            raw = json.loads(zlib.decompress(meta_buf))
        # mst -> (schema, [ChunkMeta])
        self.meta: dict[str, tuple[dict, list[ChunkMeta]]] = {}
        self.tmin: int | None = None
        self.tmax: int | None = None
        for mst, m in raw.items():
            schema = {k: FieldType(v) for k, v in m["schema"].items()}
            chunks = []
            for c in m["chunks"]:
                cols = {
                    name: {
                        "v": tuple(cc["v"]),
                        "m": tuple(cc["m"]) if cc["m"] else None,
                        "pre": PreAgg.from_json(cc["pre"]),
                    }
                    for name, cc in c["cols"].items()
                }
                if c.get("packed"):
                    cm = ChunkMeta(
                        None, c["rows"], c["tmin"], c["tmax"],
                        tuple(c["time"]), cols,
                        smin=c["smin"], smax=c["smax"],
                        sid_loc=tuple(c["sids"]),
                        sparse=[(p0, p1) for p0, p1 in c["sparse"]],
                    )
                else:
                    cm = ChunkMeta(c["sid"], c["rows"], c["tmin"], c["tmax"],
                                   tuple(c["time"]), cols)
                chunks.append(cm)
                if self.tmin is None or cm.tmin < self.tmin:
                    self.tmin = cm.tmin
                if self.tmax is None or cm.tmax > self.tmax:
                    self.tmax = cm.tmax
            self.meta[mst] = (schema, chunks)
        # per-measurement sid bloom (reference: lib/bloomfilter): single-
        # series lookups reject in O(k) instead of scanning chunk metas —
        # built from in-memory metadata, so no format change
        self._col_cache: OrderedDict = OrderedDict()
        self._cache_bytes = 0
        self._cache_lock = lockdep.Lock()
        self._sid_bloom: dict[str, BloomFilter] = {}
        # per-(mst, sid) chunk lists: single-series lookups are O(own
        # chunks); without this a scan over S series costs S x all-chunks
        # meta filtering — quadratic at high cardinality
        self._sid_chunks: dict[str, dict[int, list[ChunkMeta]]] = {}
        # packed chunks are listed separately: a single-sid lookup takes
        # its per-sid chunks PLUS the packed chunks whose [smin, smax]
        # span covers the sid (sparse index narrows the rows at read time)
        self._packed_chunks: dict[str, list[ChunkMeta]] = {}
        for mst, (_s, chunks) in self.meta.items():
            bf = BloomFilter(len(chunks))
            by_sid: dict[int, list[ChunkMeta]] = {}
            packed: list[ChunkMeta] = []
            for c in chunks:
                if c.packed:
                    packed.append(c)
                    continue
                bf.add(c.sid)
                by_sid.setdefault(c.sid, []).append(c)
            self._sid_bloom[mst] = bf
            self._sid_chunks[mst] = by_sid
            self._packed_chunks[mst] = packed

    def close(self) -> None:
        self._f.close()

    def measurements(self) -> list[str]:
        return list(self.meta)

    def schema(self, measurement: str) -> dict[str, FieldType]:
        entry = self.meta.get(measurement)
        return entry[0] if entry else {}

    def chunks(
        self,
        measurement: str,
        sids: set[int] | None = None,
        tmin: int | None = None,
        tmax: int | None = None,
    ) -> list[ChunkMeta]:
        """Chunk metas matching series + time range (tmax exclusive) —
        the block-skip step (reference location.go / pre-agg pruning)."""
        entry = self.meta.get(measurement)
        if entry is None:
            return []
        packed = self._packed_chunks.get(measurement, ())
        if sids is not None and len(sids) == 1:
            sid = next(iter(sids))
            bf = self._sid_bloom.get(measurement)
            if bf is not None and sid not in bf:
                cand = ()
            else:
                cand = self._sid_chunks.get(measurement, {}).get(sid, ())
        else:
            cand = entry[1]
        out = []
        for c in cand:
            if c.packed:
                continue  # appended below with the sid-span filter
            if sids is not None and c.sid not in sids:
                continue
            if tmin is not None and c.tmax < tmin:
                continue
            if tmax is not None and c.tmin >= tmax:
                continue
            out.append(c)
        for c in packed:
            if sids is not None and not any(
                    c.smin <= s_ <= c.smax for s_ in sids):
                continue
            if tmin is not None and c.tmax < tmin:
                continue
            if tmax is not None and c.tmin >= tmax:
                continue
            out.append(c)
        return out

    def _read(self, loc: tuple[int, int]) -> bytes:
        # positioned read: concurrent query threads share this fd, and an
        # interleaved seek+read pair would decode bytes from the wrong
        # offset (and the column cache would then serve the garbage forever)
        buf = os.pread(self._f.fileno(), loc[1], loc[0])
        if diskfault.armed():
            buf = diskfault.on_read(self.path, buf, site="tsf-block-read")
        if len(buf) != loc[1]:
            # a short pread means the media lost the block's tail (file
            # truncated under us): surface it, never decode a prefix
            raise CorruptFile(
                self.path,
                f"short read at {loc[0]}: {len(buf)}/{loc[1]} bytes")
        if not self.block_crc:
            return buf  # legacy revision-1 file: no seal to verify
        payload, seal = buf[:-_BLOCK_CRC.size], buf[-_BLOCK_CRC.size:]
        if zlib.crc32(payload) != _BLOCK_CRC.unpack(seal)[0]:
            raise CorruptFile(
                self.path, f"block crc mismatch at offset {loc[0]}")
        return payload

    def read_times(self, chunk: ChunkMeta) -> np.ndarray:
        return encoding.decode_ints(self._read(chunk.time_loc))

    # decoded-column caching (reference: lib/readcache — hot chunks
    # decode once, not per query). Safe because TSF files are immutable
    # and no read path mutates decoded arrays in place. Two regimes:
    # with the process-global decoded-column cache enabled
    # (storage/colcache.py, OGT_COLCACHE_MB > 0) columns live there,
    # keyed (shard, file generation, chunk, series, field) with explicit
    # invalidation at every file-set swap; with it disabled, the original
    # per-open-file byte-budgeted LRU below serves bit-identically. Bulk
    # one-pass scans (compaction, downsample, export) bypass BOTH
    # (cache=False) so soon-to-be-retired readers never pin decoded
    # arrays.
    _CACHE_BYTES = 16 << 20  # decoded-bytes budget per open file

    @staticmethod
    def _val_nbytes(val) -> int:
        if getattr(val, "is_decoded", True) is False:
            # still-encoded numeric column: one shared accounting rule
            # (record.EncodedColumn), never firing the lazy decode
            return val.accounted_nbytes()
        if isinstance(val, Column):
            return int(val.values.nbytes if hasattr(val.values, "nbytes")
                       else len(val.values) * 64) + int(val.valid.nbytes)
        return int(getattr(val, "nbytes", 64))

    def _colcache_key(self, chunk: ChunkMeta, name):
        # (shard id, file generation, chunk id, series, field): the sid
        # is the chunk's own for per-series chunks, None for packed
        # multi-series chunks (whose columns cache whole; per-sid slicing
        # is a cheap binary search over the cached arrays)
        return (self.owner_ns, self.gen, id(chunk), chunk.sid, name)

    def _cached_col(self, chunk: ChunkMeta, name, decode):
        """Decode-once lookup for one column of one chunk: `name` is the
        field name, None for the time column, "\\x00sids" for a packed
        chunk's sid column."""
        cc = colcache.GLOBAL
        if cc.enabled():
            key = self._colcache_key(chunk, name)
            got = cc.get(key)
            if got is not None:
                return got
            val = decode()
            cc.put(key, val)
            return val
        key = (id(chunk), name)
        with self._cache_lock:
            got = self._col_cache.get(key)
            if got is not None:
                self._col_cache.move_to_end(key)
                return got
        val = decode()
        nb = self._val_nbytes(val)
        if nb > self._CACHE_BYTES:
            return val  # a single oversized column never enters the cache
        with self._cache_lock:
            if key not in self._col_cache:
                self._col_cache[key] = val
                self._cache_bytes += nb
            self._col_cache.move_to_end(key)
            while self._cache_bytes > self._CACHE_BYTES and self._col_cache:
                _k, old = self._col_cache.popitem(last=False)
                self._cache_bytes -= self._val_nbytes(old)
        return val

    def read_chunk(
        self, measurement: str, chunk: ChunkMeta,
        fields: list[str] | None = None, cache: bool = True,
        encoded_ok: bool = False,
    ) -> Record:
        """``encoded_ok=True`` (the device-decode bulk scan,
        storage/shard.py read_series_bulk) returns numeric value columns
        whose blocks are device-decodable as still-encoded
        record.EncodedColumn — the CRC seal is verified here as always,
        but the payload decode is deferred to the accelerator (or to the
        column's lazy host fallback).  Times and masks always decode on
        the host (they drive window/run planning)."""
        schema = self.schema(measurement)

        def times_decode():
            return self.read_times(chunk)

        times = (self._cached_col(chunk, None, times_decode)
                 if cache else times_decode())
        cols = {}
        names = fields if fields is not None else list(chunk.cols)
        for name in names:
            loc = chunk.cols.get(name)
            if loc is None:
                continue

            def decode(loc=loc, name=name):
                vbuf = self._read(loc["v"])
                mbuf = self._read(loc["m"]) if loc["m"] else b""
                ftype = schema[name]
                if encoded_ok and ftype in (FieldType.FLOAT,
                                            FieldType.INT):
                    db = encoding.device_block(vbuf)
                    if db is not None:
                        return EncodedColumn(
                            ftype, [vbuf],
                            encoding.decode_mask(mbuf, db.n),
                            encoding.decode_value_blocks)
                return encoding.decode_column(ftype, vbuf, mbuf)

            cols[name] = (self._cached_col(chunk, name, decode)
                          if cache else decode())
        return Record(times, cols)

    def _chunk_from_cache(self, chunk: ChunkMeta,
                          fields: list[str] | None) -> Record | None:
        """The consult-before-dispatch fast path: assemble a chunk Record
        purely from already-cached columns, or None on ANY miss (the
        caller then decodes through the scan pool, whose in-flight-bytes
        backpressure keeps bounding memory). No IO, no decode."""
        import time as _time

        cc = colcache.GLOBAL
        if not cc.enabled():
            return None
        t0 = _time.perf_counter_ns()
        times = cc.peek(self._colcache_key(chunk, None))
        if times is None:
            return None
        cols = {}
        names = fields if fields is not None else list(chunk.cols)
        for name in names:
            if name not in chunk.cols:
                continue
            col = cc.peek(self._colcache_key(chunk, name))
            if col is None:
                return None
            cols[name] = col
        cc.count_peek(1 + len(cols), _time.perf_counter_ns() - t0)
        return Record(times, cols)

    def read_chunk_if_cached(
        self, measurement: str, chunk: ChunkMeta,
        fields: list[str] | None = None,
    ) -> Record | None:
        return self._chunk_from_cache(chunk, fields)


    # -- packed (PK-sorted column store) reads ------------------------------

    def read_packed_sids(self, chunk: ChunkMeta, cache: bool = True) -> np.ndarray:
        """The sid column of a packed chunk (non-decreasing int64)."""
        def decode():
            return encoding.decode_ints(self._read(chunk.sid_loc))

        return (self._cached_col(chunk, "\x00sids", decode)
                if cache else decode())

    @staticmethod
    def _sid_row_range(chunk: ChunkMeta, sids: np.ndarray,
                       sid: int) -> tuple[int, int]:
        """[lo, hi) row window of one sid inside a packed chunk: the
        sparse PK index bounds the candidates, an exact binary search on
        the sid column finds the run."""
        import bisect

        sp = chunk.sparse or []
        entry_sids = [e[0] for e in sp]
        j = bisect.bisect_left(entry_sids, sid)
        w_lo = sp[j - 1][1] if j > 0 else 0
        k = bisect.bisect_right(entry_sids, sid)
        w_hi = sp[k][1] if k < len(sp) else chunk.rows
        win = sids[w_lo:w_hi]
        lo = w_lo + int(np.searchsorted(win, sid, "left"))
        hi = w_lo + int(np.searchsorted(win, sid, "right"))
        return lo, hi

    @staticmethod
    def _slice_rows(rec: Record, lo: int, hi: int) -> Record:
        """Row window [lo, hi) of a chunk record.  Plain columns slice as
        views; EncodedColumns compose an encoded row-run view instead —
        keeping the raw blocks attached for the device-decode route while
        any host consumer decodes ONCE through the shared root column
        (record.EncodedColumn.take), bit-identically."""
        cols = {}
        for name, col in rec.columns.items():
            if isinstance(col, EncodedColumn):
                cols[name] = col.take(np.arange(lo, hi))
            else:
                cols[name] = Column(col.ftype, col.values[lo:hi],
                                    col.valid[lo:hi])
        return Record(rec.times[lo:hi], cols)

    def read_packed_sid(
        self, measurement: str, chunk: ChunkMeta, sid: int,
        fields: list[str] | None = None, cache: bool = True,
        encoded_ok: bool = False,
    ) -> Record:
        """One series' rows out of a packed chunk: the sparse PK index
        bounds the candidate row window (and rejects out-of-span sids
        without touching data), then an exact binary search on the
        (cached) sid column finds the rows — the hybrid store reader
        (reference engine/immutable/colstore reader +
        sparseindex/primary_index.go).  ``encoded_ok`` defers numeric
        value decode exactly like read_chunk: the sid's rows come back as
        an encoded row-run view over the chunk's blocks."""
        if sid < chunk.smin or sid > chunk.smax:
            return Record(np.empty(0, np.int64), {})
        sids = self.read_packed_sids(chunk, cache)
        lo, hi = self._sid_row_range(chunk, sids, sid)
        if lo == hi:
            return Record(np.empty(0, np.int64), {})
        rec = self.read_chunk(measurement, chunk, fields, cache,
                              encoded_ok=encoded_ok)
        return self._slice_rows(rec, lo, hi)

    def read_packed_sid_if_cached(
        self, measurement: str, chunk: ChunkMeta, sid: int,
        fields: list[str] | None = None,
    ) -> Record | None:
        """read_packed_sid served purely from cached columns, or None on
        any miss.  Out-of-span sids answer the empty record directly (no
        decode would have happened either way)."""
        if sid < chunk.smin or sid > chunk.smax:
            return Record(np.empty(0, np.int64), {})
        cc = colcache.GLOBAL
        if not cc.enabled():
            return None
        sids = cc.peek(self._colcache_key(chunk, "\x00sids"))
        if sids is None:
            return None
        lo, hi = self._sid_row_range(chunk, sids, sid)
        if lo == hi:
            cc.count_peek(1)
            return Record(np.empty(0, np.int64), {})
        rec = self._chunk_from_cache(chunk, fields)
        if rec is None:
            return None
        cc.count_peek(1)  # the sid-column peek on top of the record's
        return self._slice_rows(rec, lo, hi)

    def read_packed_bulk(
        self, measurement: str, chunk: ChunkMeta,
        fields: list[str] | None = None,
        sid_filter: np.ndarray | None = None, cache: bool = True,
        encoded_ok: bool = False,
    ) -> tuple[np.ndarray, Record]:
        """(sids, record) of a packed chunk in ONE decode; when
        `sid_filter` (sorted int64 array) is given, rows are masked to
        those series — the batched multi-series scan that replaces
        per-sid Python loops at high cardinality.  ``encoded_ok`` defers
        numeric value decode exactly like read_chunk — a sid filter that
        actually drops rows slices the columns, which host-decodes the
        lazy ones (bit-identical fallback)."""
        sids = self.read_packed_sids(chunk, cache)
        rec = self.read_chunk(measurement, chunk, fields, cache,
                              encoded_ok=encoded_ok)
        return self._packed_bulk_filter(sids, rec, sid_filter)

    @staticmethod
    def _packed_bulk_filter(sids, rec, sid_filter):
        if sid_filter is None:
            return sids, rec
        keep = np.isin(sids, sid_filter)
        if keep.all():
            return sids, rec
        return sids[keep], Record(
            rec.times[keep],
            {
                name: Column(col.ftype, col.values[keep], col.valid[keep])
                for name, col in rec.columns.items()
            },
        )

    # -- integrity scrub surface (services/scrub.py) ------------------------

    def data_locs(self) -> list[tuple[int, int]]:
        """Every data-block (off, len) of this file in a stable order —
        the scrub service's work list.  Pure metadata walk, no IO."""
        out: list[tuple[int, int]] = []
        for mst in sorted(self.meta):
            for c in self.meta[mst][1]:
                out.append(c.time_loc)
                if c.sid_loc:
                    out.append(c.sid_loc)
                for name in sorted(c.cols):
                    cc = c.cols[name]
                    out.append(cc["v"])
                    if cc["m"]:
                        out.append(cc["m"])
        return out

    def verify_block(self, loc: tuple[int, int]) -> int:
        """Read + CRC-verify one block WITHOUT decoding or caching it;
        returns bytes read.  Raises CorruptFile on any mismatch."""
        self._read(loc)
        return loc[1]

    def read_packed_bulk_if_cached(
        self, measurement: str, chunk: ChunkMeta,
        fields: list[str] | None = None,
        sid_filter: np.ndarray | None = None,
    ) -> tuple[np.ndarray, Record] | None:
        """read_packed_bulk served purely from cached columns, or None on
        any miss (the sid filter is applied per call — cached columns
        stay whole so every sid set shares one entry)."""
        cc = colcache.GLOBAL
        if not cc.enabled():
            return None
        sids = cc.peek(self._colcache_key(chunk, "\x00sids"))
        if sids is None:
            return None
        rec = self._chunk_from_cache(chunk, fields)
        if rec is None:
            return None
        cc.count_peek(1)
        return self._packed_bulk_filter(sids, rec, sid_filter)


class CorruptFile(Exception):
    """Media-level damage detected in a TSF file (bad magic/trailer,
    meta CRC mismatch, short block read, block CRC mismatch).  Carries
    the path so the shard's read paths can QUARANTINE the file — the
    error taxonomy's boundary between "this query failed" and "this
    file is damaged" (storage/shard.py quarantine)."""

    def __init__(self, path: str, why: str):
        super().__init__(f"corrupt TSF file {path}: {why}")
        self.path = path
        self.why = why
